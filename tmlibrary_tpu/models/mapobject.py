"""Mapobject types: the registry of segmented and static object classes.

Reference parity: ``tmlib/models/mapobject.py`` — ``MapobjectType`` (name,
``ref_type`` distinguishing *static* types generated from experiment
geometry — Plates/Wells/Sites — from *segmented* types produced by
jterator), ``Mapobject`` and ``MapobjectSegmentation`` (PostGIS polygon +
centroid per object per (tpoint, zplane), Citus-distributed).

Here the per-object geometries live in the segmentation store (label
stacks + polygon Parquet shards, see
:class:`~tmlibrary_tpu.models.store.ExperimentStore`); this module holds
the *type registry* (a JSON document in the store) and the generator for
static mapobject geometry: axis-aligned outlines of plates, wells and
sites in plate-mosaic pixel coordinates, which is what the reference
creates so the viewer can overlay the grid.
"""

from __future__ import annotations

import dataclasses
import json
import math
from pathlib import Path

import numpy as np

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.models.experiment import Experiment

#: static mapobject type names the reference auto-creates per experiment
STATIC_TYPES = ("Plates", "Wells", "Sites")


@dataclasses.dataclass(frozen=True)
class MapobjectType:
    """One class of map objects (reference ``MapobjectType`` row).

    ``ref_type`` is ``"segmented"`` for jterator outputs or one of
    ``STATIC_TYPES``'s singular forms for geometry-derived types.
    ``min_poly_zoom`` is the pyramid zoom level below which the viewer
    renders centroids instead of polygons (computed from object size in
    the reference; recorded here for the serving layer).
    """

    name: str
    ref_type: str = "segmented"
    min_poly_zoom: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "MapobjectType":
        return cls(**d)


class MapobjectTypeRegistry:
    """JSON-backed registry of an experiment's mapobject types.

    The reference keeps these as ORM rows keyed by experiment; jterator's
    collect phase inserts segmented types and ``delete_cascade`` removes a
    type with its objects.  Same operations here, against the store's
    ``mapobject_types.json``.
    """

    FILENAME = "mapobject_types.json"

    def __init__(self, root: Path):
        self.path = Path(root) / self.FILENAME

    def _read(self) -> dict[str, dict]:
        if not self.path.exists():
            return {}
        return json.loads(self.path.read_text())

    def _write(self, d: dict[str, dict]) -> None:
        self.path.write_text(json.dumps(d, indent=2, sort_keys=True))

    def register(self, mtype: MapobjectType) -> None:
        d = self._read()
        d[mtype.name] = mtype.to_dict()
        self._write(d)

    def get(self, name: str) -> MapobjectType:
        d = self._read()
        if name not in d:
            raise MetadataError(f"no mapobject type '{name}'")
        return MapobjectType.from_dict(d[name])

    def list(self) -> list[MapobjectType]:
        return [MapobjectType.from_dict(v) for v in self._read().values()]

    def names(self) -> list[str]:
        return sorted(self._read())

    def delete(self, name: str) -> None:
        """Remove a type from the registry (reference
        ``MapobjectType.delete_cascade`` also drops the object rows; the
        caller owns deleting the store's label/feature artifacts)."""
        d = self._read()
        d.pop(name, None)
        self._write(d)


#: plural static type name → the singular ``ref_type`` recorded on it
STATIC_REF_TYPES = {"Plates": "plate", "Wells": "well", "Sites": "site"}


# ------------------------------------------------------------- static geometry
def plate_grid(exp: Experiment, plate_name: str) -> tuple[int, int, int, int]:
    """(n_well_rows, n_well_cols, sites_y, sites_x) for one plate — the
    single source of truth for plate-grid geometry, shared by illuminati's
    stitching, the static outlines and the pyramid-depth computation."""
    plate = next((p for p in exp.plates if p.name == plate_name), None)
    if plate is None:
        raise MetadataError(f"no plate named '{plate_name}'")
    n_rows = max((w.row for w in plate.wells), default=0) + 1
    n_cols = max((w.column for w in plate.wells), default=0) + 1
    sy = max((s.y for w in plate.wells for s in w.sites), default=0) + 1
    sx = max((s.x for w in plate.wells for s in w.sites), default=0) + 1
    return n_rows, n_cols, sy, sx


def plate_mosaic_shape(
    exp: Experiment, plate_name: str, well_spacing: int = 0
) -> tuple[int, int]:
    """(height, width) in pixels of one plate's stitched mosaic — the
    single source of truth shared by illuminati's stitching and the
    pyramid-depth computation."""
    n_rows, n_cols, sy, sx = plate_grid(exp, plate_name)
    wh = sy * exp.site_height
    ww = sx * exp.site_width
    return (
        n_rows * wh + (n_rows - 1) * well_spacing,
        n_cols * ww + (n_cols - 1) * well_spacing,
    )


def _rect(y0: int, x0: int, y1: int, x1: int) -> np.ndarray:
    """Closed rectangle outline, (5, 2) [y, x] int32 — same vertex
    convention as ops.polygons traces.  The winding is counter-clockwise
    in y-down image coordinates (equivalently clockwise in math-convention
    y-up axes); signed-area consumers must account for the y-down frame."""
    return np.array(
        [[y0, x0], [y1, x0], [y1, x1], [y0, x1], [y0, x0]], dtype=np.int32
    )


def static_mapobjects(
    exp: Experiment, plate_name: str, well_spacing: int = 0
) -> dict[str, list[tuple[str, np.ndarray]]]:
    """Outlines of the plate, its wells, and its sites in plate-mosaic
    pixel coordinates (reference: the static MapobjectTypes created during
    pyramid build so the viewer can draw the grid).

    ``well_spacing`` adds a pixel gutter between wells, matching
    illuminati's mosaic layout option.  Returns
    ``{"Plates"|"Wells"|"Sites": [(label, (5, 2) outline), ...]}``.
    """
    n_rows, n_cols, sy, sx = plate_grid(exp, plate_name)
    wh = sy * exp.site_height  # well height in px
    ww = sx * exp.site_width
    out: dict[str, list[tuple[str, np.ndarray]]] = {
        "Plates": [], "Wells": [], "Sites": []
    }
    plate_h = n_rows * wh + (n_rows - 1) * well_spacing
    plate_w = n_cols * ww + (n_cols - 1) * well_spacing
    out["Plates"].append((plate_name, _rect(0, 0, plate_h, plate_w)))
    plate = next(p for p in exp.plates if p.name == plate_name)
    for well in plate.wells:
        oy = well.row * (wh + well_spacing)
        ox = well.column * (ww + well_spacing)
        out["Wells"].append((well.name, _rect(oy, ox, oy + wh, ox + ww)))
        for site in well.sites:
            sy0 = oy + site.y * exp.site_height
            sx0 = ox + site.x * exp.site_width
            out["Sites"].append(
                (
                    f"{well.name}_{site.y}_{site.x}",
                    _rect(sy0, sx0, sy0 + exp.site_height, sx0 + exp.site_width),
                )
            )
    return out


def min_poly_zoom(n_levels: int, mean_object_px: float) -> int:
    """Zoom level below which polygons degrade to centroids: the level at
    which a typical object spans < ~2 display pixels (reference computes
    the same threshold from segmentation size when creating a
    MapobjectType; levels count 0 = most zoomed-out)."""
    if mean_object_px <= 0:
        return n_levels - 1
    diameter = math.sqrt(mean_object_px)
    # at level L (0 = coarsest of n_levels), scale = 2^(n_levels-1-L)
    for level in range(n_levels):
        scale = 2 ** (n_levels - 1 - level)
        if diameter / scale >= 2.0:
            return level
    return n_levels - 1
