"""User and experiment-sharing records.

Reference parity: ``tmlib/models/user.py`` (``User``) and the
``ExperimentShare`` association in ``tmlib/models/experiment.py``.  The
reference stores these as ORM rows to drive the web UI's auth/ACL; this
framework has no server, so they are a JSON registry file
(``users.json`` next to the experiment stores) that records ownership and
read/write grants — enough for a front-end to enforce the same semantics.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path


@dataclasses.dataclass
class User:
    """Reference ``tmlib.models.user.User`` (minus password auth — auth
    belongs to the serving layer, not the compute library)."""

    name: str
    email: str = ""


@dataclasses.dataclass
class ExperimentShare:
    """Grant of access to one experiment (reference ``ExperimentShare``)."""

    experiment: str
    user: str
    write: bool = False


class UserRegistry:
    """JSON-file registry of users, experiment ownership and shares."""

    def __init__(self, path: Path):
        self.path = Path(path)
        self._data = {"users": {}, "owners": {}, "shares": []}
        if self.path.exists():
            self._data = json.loads(self.path.read_text())

    def _save(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.path.write_text(json.dumps(self._data, indent=2))

    def add_user(self, user: User) -> None:
        self._data["users"][user.name] = {"email": user.email}
        self._save()

    def users(self) -> list[User]:
        return [User(n, d.get("email", "")) for n, d in sorted(self._data["users"].items())]

    def set_owner(self, experiment: str, user: str) -> None:
        if user not in self._data["users"]:
            raise KeyError(f"unknown user '{user}'")
        self._data["owners"][experiment] = user
        self._save()

    def share(self, share: ExperimentShare) -> None:
        if share.user not in self._data["users"]:
            raise KeyError(f"unknown user '{share.user}'")
        self._data["shares"].append(dataclasses.asdict(share))
        self._save()

    def can_read(self, experiment: str, user: str) -> bool:
        if self._data["owners"].get(experiment) == user:
            return True
        return any(
            s["experiment"] == experiment and s["user"] == user
            for s in self._data["shares"]
        )

    def can_write(self, experiment: str, user: str) -> bool:
        if self._data["owners"].get(experiment) == user:
            return True
        return any(
            s["experiment"] == experiment and s["user"] == user and s["write"]
            for s in self._data["shares"]
        )
