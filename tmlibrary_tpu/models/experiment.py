"""Experiment manifest: the structural data model.

Reference parity: ``tmlib/models/experiment.py``, ``plate.py``, ``well.py``,
``site.py``, ``channel.py``, ``acquisition.py``, ``cycle.py`` — SQLAlchemy
models over PostgreSQL in the reference; a JSON-serializable manifest here.

The canonical index hierarchy (matching the reference's object model) is::

    Experiment
      └─ Plate (name)
          └─ Well (row, column)              # e.g. 16 x 24 = 384-well
              └─ Site (y, x in well grid)    # acquisition site
    Experiment.channels   (name, wavelength) # shared across plates
    Experiment.cycles     (index)            # multiplexing acquisition rounds
    Experiment.tpoints / zplanes             # time series / z-stacks

Every pixel plane is addressed by the tuple
``(plate, well, site, cycle, channel, tpoint, zplane)``.  Sites share a fixed
``(height, width)`` per experiment — this is what makes the site axis a clean
``vmap``/shard dimension on TPU.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Iterator

from tmlibrary_tpu.errors import MetadataError


@dataclasses.dataclass(frozen=True)
class Channel:
    """A fluorescence channel (reference: ``tmlib/models/channel.py``)."""

    index: int
    name: str
    wavelength: str | None = None
    bit_depth: int = 16


@dataclasses.dataclass(frozen=True)
class Site:
    """An acquisition site within a well (reference: ``tmlib/models/site.py``).

    ``y``/``x`` are the site's grid coordinates inside its well.
    """

    y: int
    x: int


@dataclasses.dataclass(frozen=True)
class Well:
    """A well within a plate (reference: ``tmlib/models/well.py``).

    ``row``/``column`` are zero-based plate-grid coordinates; ``name`` is the
    conventional label (e.g. ``"A01"``).
    """

    row: int
    column: int
    sites: tuple[Site, ...]

    @property
    def name(self) -> str:
        if self.row >= 26:
            # double-letter rows for >26-row plates (e.g. 1536-well)
            first = chr(ord("A") + self.row // 26 - 1)
            second = chr(ord("A") + self.row % 26)
            prefix = first + second
        else:
            prefix = chr(ord("A") + self.row)
        return f"{prefix}{self.column + 1:02d}"


@dataclasses.dataclass(frozen=True)
class Plate:
    """A multi-well plate (reference: ``tmlib/models/plate.py``)."""

    name: str
    wells: tuple[Well, ...]


@dataclasses.dataclass(frozen=True)
class SiteRef:
    """Fully-qualified site address — the unit of per-site work.

    The linear enumeration of ``SiteRef``s is the batching axis: the
    reference partitions this list into GC3Pie jobs
    (``create_run_batches``); we partition it into ``vmap`` batches and
    shard it over the device mesh.
    """

    plate: str
    well_row: int
    well_column: int
    site_y: int
    site_x: int

    def as_tuple(self) -> tuple:
        return (self.plate, self.well_row, self.well_column, self.site_y, self.site_x)


@dataclasses.dataclass
class Experiment:
    """Top-level experiment manifest (reference: ``tmlib/models/experiment.py``).

    Unlike the reference (ORM rows in the main DB + a per-experiment
    Citus-sharded DB), the manifest is a plain JSON document stored at the
    experiment root; pixel data lives next to it in the
    :class:`~tmlibrary_tpu.models.store.ExperimentStore`.
    """

    name: str
    plates: list[Plate]
    channels: list[Channel]
    site_height: int
    site_width: int
    n_cycles: int = 1
    n_tpoints: int = 1
    n_zplanes: int = 1

    # ------------------------------------------------------------------ axes
    def sites(self) -> Iterator[SiteRef]:
        """Enumerate all sites in canonical (plate, well, site) order."""
        for plate in self.plates:
            for well in plate.wells:
                for site in well.sites:
                    yield SiteRef(plate.name, well.row, well.column, site.y, site.x)

    @property
    def n_sites(self) -> int:
        return sum(len(w.sites) for p in self.plates for w in p.wells)

    @property
    def n_channels(self) -> int:
        return len(self.channels)

    def channel_index(self, name: str) -> int:
        for ch in self.channels:
            if ch.name == name:
                return ch.index
        raise MetadataError(f"no channel named '{name}'")

    # ------------------------------------------------------------- serialize
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "site_height": self.site_height,
            "site_width": self.site_width,
            "n_cycles": self.n_cycles,
            "n_tpoints": self.n_tpoints,
            "n_zplanes": self.n_zplanes,
            "channels": [dataclasses.asdict(c) for c in self.channels],
            "plates": [
                {
                    "name": p.name,
                    "wells": [
                        {
                            "row": w.row,
                            "column": w.column,
                            "sites": [[s.y, s.x] for s in w.sites],
                        }
                        for w in p.wells
                    ],
                }
                for p in self.plates
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Experiment":
        return cls(
            name=d["name"],
            site_height=d["site_height"],
            site_width=d["site_width"],
            n_cycles=d.get("n_cycles", 1),
            n_tpoints=d.get("n_tpoints", 1),
            n_zplanes=d.get("n_zplanes", 1),
            channels=[Channel(**c) for c in d["channels"]],
            plates=[
                Plate(
                    name=p["name"],
                    wells=tuple(
                        Well(
                            row=w["row"],
                            column=w["column"],
                            sites=tuple(Site(y=s[0], x=s[1]) for s in w["sites"]),
                        )
                        for w in p["wells"]
                    ),
                )
                for p in d["plates"]
            ],
        )

    def save(self, path: Path) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.to_dict(), indent=2))

    @classmethod
    def load(cls, path: Path) -> "Experiment":
        return cls.from_dict(json.loads(Path(path).read_text()))


def grid_experiment(
    name: str = "demo",
    n_plates: int = 1,
    well_rows: int = 2,
    well_cols: int = 2,
    sites_per_well: tuple[int, int] = (2, 2),
    channel_names: tuple[str, ...] = ("DAPI",),
    site_shape: tuple[int, int] = (256, 256),
    n_cycles: int = 1,
    n_tpoints: int = 1,
    n_zplanes: int = 1,
) -> Experiment:
    """Build a regular-grid experiment manifest (test/demo helper)."""
    sites = tuple(
        Site(y=sy, x=sx)
        for sy in range(sites_per_well[0])
        for sx in range(sites_per_well[1])
    )
    plates = [
        Plate(
            name=f"plate{p:02d}",
            wells=tuple(
                Well(row=r, column=c, sites=sites)
                for r in range(well_rows)
                for c in range(well_cols)
            ),
        )
        for p in range(n_plates)
    ]
    channels = [Channel(index=i, name=n) for i, n in enumerate(channel_names)]
    return Experiment(
        name=name,
        plates=plates,
        channels=channels,
        site_height=site_shape[0],
        site_width=site_shape[1],
        n_cycles=n_cycles,
        n_tpoints=n_tpoints,
        n_zplanes=n_zplanes,
    )
