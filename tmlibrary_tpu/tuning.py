"""Machine-written tuning defaults (``tuning/TUNING.json``).

The hardware sweep (``scripts/tune_tpu.py``) writes its verdict —
``best_batch`` for the segment+measure chain and ``best_pipeline`` for the
fetch-amortization depth — into ``tuning/TUNING.json``.  This module is the
ONE runtime consumer shared by the production engine (the pipelined batch
executor's default depth, jterator's auto batch size) and ``bench.py``
(which re-exports these loaders so the watcher scripts keep one definition
of the artifact path).

Provenance gate: only a file ``tune_tpu.py write_results`` itself produced
counts.  Hand-seeded or dry-run (``SMOKE``) artifacts never set production
defaults — a tuned default the hardware never measured is worse than a
static one.  ``TMX_TUNING_JSON`` redirects the file (watcher rehearsal).
"""

from __future__ import annotations

import json
import os
from pathlib import Path


def tuning_json_path() -> str:
    """ONE definition of the tuning-results location (and its rehearsal
    redirect) — resolved at call time so env changes take effect without
    re-imports."""
    return os.environ.get(
        "TMX_TUNING_JSON",
        str(Path(__file__).resolve().parent.parent / "tuning" / "TUNING.json"),
    )


def load_tuning() -> dict | None:
    """The machine-written tuning verdict, or None when absent, unreadable,
    or failing the provenance gate (no ``written_by``, or a SMOKE dry-run
    methodology)."""
    try:
        with open(tuning_json_path()) as f:
            tuning = json.load(f)
    except (OSError, ValueError):
        return None
    if "SMOKE(" in str(tuning.get("timing_methodology", "")):
        return None  # dry-run sweep artifacts never set production defaults
    return tuning if "written_by" in tuning else None


def _positive_int(value) -> int | None:
    if isinstance(value, (int, float)) and int(value) > 0:
        return int(value)
    return None


def tuned_pipeline_depth() -> int | None:
    """The hardware-swept ``best_pipeline`` in-flight depth, or None."""
    tuning = load_tuning()
    return _positive_int(tuning.get("best_pipeline")) if tuning else None


def tuned_batch_size() -> int | None:
    """The hardware-swept ``best_batch`` site batch, or None."""
    tuning = load_tuning()
    return _positive_int(tuning.get("best_batch")) if tuning else None
