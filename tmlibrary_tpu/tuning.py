"""Machine-written tuning defaults (``tuning/TUNING.json``).

The hardware sweep (``scripts/tune_tpu.py``) writes its verdict —
``best_batch`` for the segment+measure chain and ``best_pipeline`` for the
fetch-amortization depth — into ``tuning/TUNING.json``.  This module is the
ONE runtime consumer shared by the production engine (the pipelined batch
executor's default depth, jterator's auto batch size) and ``bench.py``
(which re-exports these loaders so the watcher scripts keep one definition
of the artifact path).

Provenance gate: only a file ``tune_tpu.py write_results`` itself produced
counts.  Hand-seeded or dry-run (``SMOKE``) artifacts never set production
defaults — a tuned default the hardware never measured is worse than a
static one.  ``TMX_TUNING_JSON`` redirects the file (watcher rehearsal).
"""

from __future__ import annotations

import datetime
import json
import os
import time
from pathlib import Path

from tmlibrary_tpu.atomicio import atomic_write_text


def tuning_json_path() -> str:
    """ONE definition of the tuning-results location (and its rehearsal
    redirect) — resolved at call time so env changes take effect without
    re-imports."""
    return os.environ.get(
        "TMX_TUNING_JSON",
        str(Path(__file__).resolve().parent.parent / "tuning" / "TUNING.json"),
    )


def _tuning_dir() -> str:
    return os.path.dirname(os.path.abspath(tuning_json_path()))


def bench_cache_path() -> str:
    """The watcher-written cache of freshest on-hardware bench records
    (``tuning/BENCH_TPU.json``); ``BENCH_TPU_CACHE`` redirects it — same
    contract bench.py's CACHE_PATH has always had, now importable by the
    perf layer without importing bench."""
    return os.environ.get(
        "BENCH_TPU_CACHE", os.path.join(_tuning_dir(), "BENCH_TPU.json")
    )


def bench_history_path() -> str:
    """Append-only bench history (``tuning/BENCH_HISTORY.jsonl``) — one
    JSON line per emitted bench/sweep record, the regression sentinel's
    input.  ``BENCH_HISTORY`` redirects it (tests, CI smoke); with no
    redirect it follows ``TMX_TUNING_JSON``'s directory so watcher
    rehearsal redirects the whole artifact family at once."""
    return os.environ.get(
        "BENCH_HISTORY", os.path.join(_tuning_dir(), "BENCH_HISTORY.jsonl")
    )


def recapture_path() -> str:
    """Re-capture queue the regression sentinel writes and
    ``scripts/tpu_watch.py`` drains (``tuning/RECAPTURE.json``)."""
    return os.environ.get(
        "WATCH_RECAPTURE", os.path.join(_tuning_dir(), "RECAPTURE.json")
    )


def append_bench_history(record: dict, path: str | None = None) -> str | None:
    """Append one bench record to the history, stamped with the append
    time.  Returns the path written, or None on any failure — history is
    observability and must never break the bench stdout contract."""
    try:
        path = path or bench_history_path()
        now = time.time()
        line = {
            "recorded_at": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(timespec="seconds"),
            "recorded_at_unix": now,
            **record,
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(line, sort_keys=True) + "\n")
        return path
    except Exception:
        return None


def load_bench_history(path: str | None = None) -> list[dict]:
    """Parsed history lines, oldest first; corrupt lines are skipped (an
    interrupted append must not poison the whole history)."""
    path = path or bench_history_path()
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def load_tuning() -> dict | None:
    """The machine-written tuning verdict, or None when absent, unreadable,
    or failing the provenance gate (no ``written_by``, or a SMOKE dry-run
    methodology)."""
    try:
        with open(tuning_json_path()) as f:
            tuning = json.load(f)
    except (OSError, ValueError):
        return None
    if "SMOKE(" in str(tuning.get("timing_methodology", "")):
        return None  # dry-run sweep artifacts never set production defaults
    return tuning if "written_by" in tuning else None


def _positive_int(value) -> int | None:
    if isinstance(value, (int, float)) and int(value) > 0:
        return int(value)
    return None


def tuned_pipeline_depth() -> int | None:
    """The hardware-swept ``best_pipeline`` in-flight depth, or None."""
    tuning = load_tuning()
    return _positive_int(tuning.get("best_pipeline")) if tuning else None


def tuned_batch_size() -> int | None:
    """The hardware-swept ``best_batch`` site batch, or None."""
    tuning = load_tuning()
    return _positive_int(tuning.get("best_batch")) if tuning else None


_REDUCTION_STRATEGIES = ("onehot", "sort", "scatter", "fused")


def tuned_reduction_strategy(backend: str | None = None) -> str | None:
    """The swept grouped-reduction strategy verdict for ``backend``, or
    None.  Two shapes are accepted: a per-backend dict
    (``{"cpu": "scatter", "tpu": "onehot"}`` — what ``bench.py --sweep``
    writes via :func:`record_config_sweep`) or a plain string scoped by
    the file's top-level ``backend`` field.  A verdict measured on one
    backend never sets another backend's default, and malformed values
    degrade to None (the static default) rather than erroring."""
    tuning = load_tuning()
    if not tuning:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    entry = tuning.get("reduction_strategy")
    if isinstance(entry, dict):
        value = entry.get(backend)
    elif isinstance(entry, str) and tuning.get("backend") == backend:
        value = entry
    else:
        value = None
    return value if value in _REDUCTION_STRATEGIES else None


def tuned_object_capacity(backend: str | None = None) -> int | None:
    """The swept object-capacity bucket verdict for ``backend``, or None.

    ``bench.py --sweep`` records the winning capacity (``best_capacity``)
    when ``BENCH_SWEEP_CAPACITIES`` puts the bucket ladder on the grid;
    the jterator step uses it as the first-batch routing hint before any
    on-run object counts exist.  Same provenance and backend-scoping
    rules as :func:`tuned_reduction_strategy`."""
    tuning = load_tuning()
    if not tuning:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    entry = tuning.get("object_capacity")
    if isinstance(entry, dict):
        return _positive_int(entry.get(backend))
    if tuning.get("backend") == backend:
        return _positive_int(entry)
    return None


_SCHEDULE_MODES = ("pack", "off")


def tuned_schedule(backend: str | None = None) -> str | None:
    """The swept work-aware scheduling verdict for ``backend``
    (``"pack"`` | ``"off"``), or None.  ``bench.py --sweep`` records the
    winner (``best_schedule``) when ``BENCH_SWEEP_SCHEDULE`` puts the
    packing axis on the grid; the jterator dispatch plane consumes it
    through ``workflow.schedule.resolve_schedule``'s precedence chain.
    Same provenance and backend-scoping rules as
    :func:`tuned_reduction_strategy` — a verdict measured on one backend
    never sets another's default, and malformed values degrade to None
    (the default: packing on)."""
    tuning = load_tuning()
    if not tuning:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    entry = tuning.get("schedule")
    if isinstance(entry, dict):
        value = entry.get(backend)
    elif isinstance(entry, str) and tuning.get("backend") == backend:
        value = entry
    else:
        value = None
    return value if value in _SCHEDULE_MODES else None


_ANALYTICS_INDEX_MODES = ("ivf", "brute")


def tuned_analytics_index(backend: str | None = None) -> str | None:
    """The swept analytics kNN index verdict for ``backend``
    (``"ivf"`` | ``"brute"``), or None.  ``bench.py`` BENCH_CONFIG=
    analytics records the winner (``best_index``) when the sweep is
    asked to persist its verdict; same provenance and backend-scoping
    rules as :func:`tuned_reduction_strategy` — a verdict measured on
    one backend never sets another's default, and malformed values
    degrade to None (the auto size cutover)."""
    tuning = load_tuning()
    if not tuning:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    entry = tuning.get("analytics_index")
    if isinstance(entry, dict):
        value = entry.get(backend)
    elif isinstance(entry, str) and tuning.get("backend") == backend:
        value = entry
    else:
        value = None
    return value if value in _ANALYTICS_INDEX_MODES else None


def record_config_sweep(config: str, entry: dict) -> dict:
    """Merge one per-config sweep verdict into the tuning file.

    ``bench.py --sweep`` calls this once per ``BENCH_CONFIG`` with a row
    like ``{"backend": ..., "best_pipeline": N, "best_strategy": ...,
    "rows": [...]}``.  Existing keys written by ``tune_tpu.py`` (the
    top-level ``best_batch``/``best_pipeline`` and their provenance
    stamps) are preserved — the sweep only owns ``config_sweeps[config]``
    and the per-backend ``reduction_strategy`` verdict.  Returns the
    merged document."""
    path = tuning_json_path()
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    # provenance: only stamp authorship when this write creates the file;
    # never claim tune_tpu.py's measurements as our own
    data.setdefault("written_by", "bench.py --sweep")
    data.setdefault("config_sweeps", {})[str(config)] = entry
    backend = entry.get("backend")
    strategy = entry.get("best_strategy")
    if backend and strategy in _REDUCTION_STRATEGIES:
        verdicts = data.get("reduction_strategy")
        if not isinstance(verdicts, dict):
            # migrate a legacy plain-string verdict under its backend scope
            legacy = verdicts if verdicts in _REDUCTION_STRATEGIES else None
            verdicts = (
                {data["backend"]: legacy}
                if legacy and data.get("backend")
                else {}
            )
        verdicts[backend] = strategy
        data["reduction_strategy"] = verdicts
    capacity = _positive_int(entry.get("best_capacity"))
    if backend and capacity:
        caps = data.get("object_capacity")
        if not isinstance(caps, dict):
            caps = {}
        caps[backend] = capacity
        data["object_capacity"] = caps
    sched = entry.get("best_schedule")
    if backend and sched in _SCHEDULE_MODES:
        verdict = data.get("schedule")
        if not isinstance(verdict, dict):
            verdict = {}
        verdict[backend] = sched
        data["schedule"] = verdict
    index_mode = entry.get("best_index")
    if backend and index_mode in _ANALYTICS_INDEX_MODES:
        idx = data.get("analytics_index")
        if not isinstance(idx, dict):
            idx = {}
        idx[backend] = index_mode
        data["analytics_index"] = idx
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    atomic_write_text(
        path, json.dumps(data, indent=2, sort_keys=True) + "\n"
    )
    return data


def config_sweep(config: str, *, model_digest: str | None = None) -> dict | None:
    """The recorded sweep verdict for ``config``, or None.

    For model-backed configs (bench ``dl``), pass the current weight
    content digest: a sweep recorded against a DIFFERENT checkpoint is
    treated as absent rather than served — its depth/strategy/capacity
    verdicts were measured on different work (PR-8's QC-gate digest
    lesson, applied to tuning state).  An entry recorded without a
    digest never matches a digest-constrained read."""
    tuning = load_tuning()
    sweeps = tuning.get("config_sweeps") if tuning else None
    entry = sweeps.get(str(config)) if isinstance(sweeps, dict) else None
    if not isinstance(entry, dict):
        return None
    if model_digest is not None and entry.get("model_digest") != model_digest:
        return None
    return entry
