"""Clustering tool: k-means over object features.

Reference parity: ``tmlib/tools/clustering.py`` — sklearn k-means over the
selected features of one mapobject type, producing a categorical
``LabelLayer``.

TPU rebuild: Lloyd's algorithm in JAX (one jit: distance matmul on the MXU,
``segment_sum`` centroid update, fixed iteration count), deterministic
k-means++-style seeding with a fixed PRNG key.  This k-means is also the
IVF index's centroid trainer (``analytics/index.py``) — one definition of
the codebook for both consumers, which is why empty clusters get a
deterministic reseed instead of freezing in place: a dead cell in the
index is wasted probe budget on every query.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.tools.base import Tool, ToolResult, register_tool


def _reseed_empty(updated: jax.Array, counts: jax.Array, x: jax.Array,
                  d_assign: jax.Array) -> jax.Array:
    """Deterministic empty-cluster reseed: each dead centroid (zero
    members after a Lloyd assignment) is re-seeded from the farthest
    points — the rows with the largest distance to their assigned
    centroid, ranked by ``lax.top_k`` (value then lowest-index, so the
    choice is reproducible).  The i-th dead slot takes the i-th
    farthest point; live slots keep the Lloyd update.  Pure function of
    its inputs: unit-pinned directly in the test suite."""
    k = updated.shape[0]
    k_far = min(int(k), int(x.shape[0]))
    _, far_idx = jax.lax.top_k(d_assign, k_far)
    dead = counts <= 0
    rank = jnp.clip(jnp.cumsum(dead.astype(jnp.int32)) - 1, 0, k_far - 1)
    return jnp.where(dead[:, None], x[far_idx[rank]], updated)


def kmeans(
    x: jax.Array, k: int, n_iter: int = 50, seed: int = 0,
    init: str = "greedy"
) -> tuple[jax.Array, jax.Array]:
    """JAX k-means; returns (assignments (N,), centroids (k, F)).

    ``init`` picks the seeding: ``"greedy"`` (default) is the
    k-means++-style farthest-point loop — best quality, O(n·k²) — and
    ``"stride"`` seeds from evenly strided rows in one gather, the
    right trade for the IVF coarse quantizer where k ≈ √N makes the
    greedy loop quadratic in the cell count.  Both are deterministic.
    """
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    key = jax.random.PRNGKey(seed)

    if init == "stride":
        # evenly strided rows: deterministic, one gather, no O(k²) loop
        rows = jnp.linspace(0, n - 1, k).astype(jnp.int32)
        centroids = x[rows]
    else:
        # k-means++ style greedy seeding (deterministic given the key).
        # One fori_loop over a preallocated (k, F) buffer — the old
        # Python `for _ in range(k-1)` dispatched (and, unjitted,
        # synced) per centroid and unrolled to k programs under jit.
        # Unset rows are masked to +inf before the min, which is
        # exactly "min over the first i centroids", so assignments stay
        # bit-identical.
        first = jax.random.randint(key, (), 0, n)
        centroids = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])

        def seed_step(i, cent):
            d2 = jnp.sum((x[:, None, :] - cent[None]) ** 2, axis=-1)  # (n, k)
            d2 = jnp.where(jnp.arange(k)[None, :] < i, d2, jnp.inf)
            return cent.at[i].set(x[jnp.argmax(jnp.min(d2, axis=1))])

        centroids = jax.lax.fori_loop(1, k, seed_step, centroids)

    def step(carry, _):
        cent = carry
        # pairwise distances via the matmul expansion (MXU-friendly)
        d2 = (
            jnp.sum(x**2, axis=1, keepdims=True)
            - 2.0 * x @ cent.T
            + jnp.sum(cent**2, axis=1)[None]
        )
        assign = jnp.argmin(d2, axis=1)
        sums = jax.ops.segment_sum(x, assign, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), assign, num_segments=k)
        new_cent = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cent)
        # dead centroids re-seed from the farthest points instead of
        # freezing at their stale position (d2 is already in hand, so
        # the reseed costs one top_k + gather)
        new_cent = _reseed_empty(new_cent, counts, x, jnp.min(d2, axis=1))
        return new_cent, None

    centroids, _ = jax.lax.scan(step, centroids, None, length=n_iter)
    d2 = (
        jnp.sum(x**2, axis=1, keepdims=True)
        - 2.0 * x @ centroids.T
        + jnp.sum(centroids**2, axis=1)[None]
    )
    return jnp.argmin(d2, axis=1), centroids


@register_tool("clustering")
class Clustering(Tool):
    """k-means over object features (JAX Lloyd's, deterministic
    seeding).  Payload: ``objects_name``, optional ``k`` (default 3),
    ``features``, and ``index`` (``auto|ivf|brute``): on the ivf path
    the tool reuses the persisted IVF codebook at ``n_cells=k``
    (``analytics/index.IvfIndex``) — sampled training + one assignment
    pass instead of full-store Lloyd's, same trainer, provenance in the
    attributes.  Reports per-cluster sizes + inertia."""

    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        k = int(payload.get("k", 3))
        features = payload.get("features")
        ids, x, feat_cols = self.load_feature_matrix(objects_name, features)
        from tmlibrary_tpu.analytics.index import (
            IvfIndex, resolve_index_mode,
        )

        resolved, source = resolve_index_mode(
            payload.get("index"), n_objects=len(ids)
        )
        index_info: dict = {"index": resolved, "index_source": source}
        if resolved == "ivf":
            # reuse (or build) the persisted codebook at this k: the
            # index trains on a strided sample and assigns the full
            # store in one pass — sublinear, deterministic, same
            # `kmeans` trainer; NOT bit-identical to full-store Lloyd's
            fs = self.feature_store(objects_name)
            idx_obj = IvfIndex.ensure(fs, features, n_cells=k)
            assign_np = idx_obj.assignments().astype(np.int32)
            cent_np = np.asarray(idx_obj.centroids, np.float32)
            index_info["index_digest"] = idx_obj.digest
            index_info["index_cache"] = idx_obj.cache_state
        else:
            assign, centroids = jax.jit(kmeans, static_argnums=(1,))(
                jnp.asarray(x), k
            )
            assign_np = np.asarray(assign).astype(np.int32)
            cent_np = np.asarray(centroids)
        ids["value"] = assign_np
        # reported fit quality (same spirit as classification's training
        # metrics): per-cluster sizes + total within-cluster sum of
        # squares (sklearn's inertia_) over the standardized features
        sizes = np.bincount(assign_np, minlength=k)
        inertia = float(
            ((x - cent_np[assign_np]) ** 2).sum()
        ) if len(x) else 0.0
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="categorical",
            values=ids,
            attributes={
                "k": k,
                "features": feat_cols,
                "centroids": cent_np.tolist(),
                "cluster_sizes": {str(i): int(n) for i, n in
                                  enumerate(sizes)},
                "inertia": round(inertia, 4),
                **index_info,
            },
        )
