"""Clustering tool: k-means over object features.

Reference parity: ``tmlib/tools/clustering.py`` — sklearn k-means over the
selected features of one mapobject type, producing a categorical
``LabelLayer``.

TPU rebuild: Lloyd's algorithm in JAX (one jit: distance matmul on the MXU,
``segment_sum`` centroid update, fixed iteration count), deterministic
k-means++-style seeding with a fixed PRNG key.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.tools.base import Tool, ToolResult, register_tool


def kmeans(
    x: jax.Array, k: int, n_iter: int = 50, seed: int = 0
) -> tuple[jax.Array, jax.Array]:
    """JAX k-means; returns (assignments (N,), centroids (k, F))."""
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    key = jax.random.PRNGKey(seed)

    # k-means++ style greedy seeding (deterministic given the key).
    # One fori_loop over a preallocated (k, F) buffer — the old Python
    # `for _ in range(k-1)` dispatched (and, unjitted, synced) per
    # centroid and unrolled to k programs under jit.  Unset rows are
    # masked to +inf before the min, which is exactly "min over the
    # first i centroids", so assignments stay bit-identical.
    first = jax.random.randint(key, (), 0, n)
    centroids = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])

    def seed_step(i, cent):
        d2 = jnp.sum((x[:, None, :] - cent[None]) ** 2, axis=-1)  # (n, k)
        d2 = jnp.where(jnp.arange(k)[None, :] < i, d2, jnp.inf)
        return cent.at[i].set(x[jnp.argmax(jnp.min(d2, axis=1))])

    centroids = jax.lax.fori_loop(1, k, seed_step, centroids)

    def step(carry, _):
        cent = carry
        # pairwise distances via the matmul expansion (MXU-friendly)
        d2 = (
            jnp.sum(x**2, axis=1, keepdims=True)
            - 2.0 * x @ cent.T
            + jnp.sum(cent**2, axis=1)[None]
        )
        assign = jnp.argmin(d2, axis=1)
        sums = jax.ops.segment_sum(x, assign, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), assign, num_segments=k)
        new_cent = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cent)
        return new_cent, None

    centroids, _ = jax.lax.scan(step, centroids, None, length=n_iter)
    d2 = (
        jnp.sum(x**2, axis=1, keepdims=True)
        - 2.0 * x @ centroids.T
        + jnp.sum(centroids**2, axis=1)[None]
    )
    return jnp.argmin(d2, axis=1), centroids


@register_tool("clustering")
class Clustering(Tool):
    """k-means over object features (JAX Lloyd's, deterministic
    seeding).  Payload: ``objects_name``, optional ``k`` (default 3)
    and ``features``.  Reports per-cluster sizes + inertia."""

    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        k = int(payload.get("k", 3))
        features = payload.get("features")
        ids, x, feat_cols = self.load_feature_matrix(objects_name, features)
        assign, centroids = jax.jit(kmeans, static_argnums=(1,))(jnp.asarray(x), k)
        assign_np = np.asarray(assign).astype(np.int32)
        ids["value"] = assign_np
        cent_np = np.asarray(centroids)
        # reported fit quality (same spirit as classification's training
        # metrics): per-cluster sizes + total within-cluster sum of
        # squares (sklearn's inertia_) over the standardized features
        sizes = np.bincount(assign_np, minlength=k)
        inertia = float(
            ((x - cent_np[assign_np]) ** 2).sum()
        ) if len(x) else 0.0
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="categorical",
            values=ids,
            attributes={
                "k": k,
                "features": feat_cols,
                "centroids": cent_np.tolist(),
                "cluster_sizes": {str(i): int(n) for i, n in
                                  enumerate(sizes)},
                "inertia": round(inertia, 4),
            },
        )
