"""Classification tool: supervised per-object classification.

Reference parity: ``tmlib/tools/classification.py`` — trains an sklearn
SVM or random forest on user-labeled example objects, predicts a class for
every object of the type, and publishes a supervised ``LabelLayer``.

TPU rebuild: the default method is a JAX multinomial logistic regression
(one jitted Adam-free full-batch gradient loop — the feature matrices are
small, the matmuls land on the MXU); ``svm`` and ``randomforest`` keep the
reference's sklearn backends on the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.errors import NotSupportedError
from tmlibrary_tpu.tools.base import Tool, ToolResult, register_tool


def softmax_train(
    x: jax.Array,
    y: jax.Array,
    n_classes: int,
    n_iter: int = 300,
    lr: float = 0.1,
    l2: float = 1e-4,
) -> tuple[jax.Array, jax.Array]:
    """Full-batch multinomial logistic regression; returns (W, b)."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    n, f = x.shape
    w = jnp.zeros((f, n_classes), jnp.float32)
    b = jnp.zeros((n_classes,), jnp.float32)

    def loss_fn(params):
        w, b = params
        logits = x @ w + b
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.mean(logp[jnp.arange(n), y])
        return nll + l2 * jnp.sum(w * w)

    grad_fn = jax.grad(loss_fn)

    def step(params, _):
        g = grad_fn(params)
        return (params[0] - lr * g[0], params[1] - lr * g[1]), None

    (w, b), _ = jax.lax.scan(step, (w, b), None, length=n_iter)
    return w, b


def _kbest_anova(
    x_train: np.ndarray, y_train: np.ndarray, n_classes: int, k: int
) -> np.ndarray:
    """Indices of the ``k`` features with the highest one-way ANOVA
    F-score between the training classes (ties broken by column order;
    degenerate within-class variance scores 0)."""
    n, f = x_train.shape
    grand = x_train.mean(axis=0)
    between = np.zeros(f)
    within = np.zeros(f)
    for c in range(n_classes):
        grp = x_train[y_train == c]
        if not len(grp):
            continue
        between += len(grp) * (grp.mean(axis=0) - grand) ** 2
        within += ((grp - grp.mean(axis=0)) ** 2).sum(axis=0)
    df_b = max(n_classes - 1, 1)
    df_w = max(n - n_classes, 1)
    # zero within-class variance with NONZERO between-class variance is a
    # PERFECT separator (sklearn's f_classif scores it inf), not a
    # degenerate column — only a fully constant feature scores 0
    score = np.where(
        within > 1e-12,
        (between / df_b) / (within / df_w + 1e-12),
        np.where(between > 1e-12, np.inf, 0.0),
    )
    k = max(1, min(int(k), f))
    # stable top-k: sort by (-score, column index)
    order = np.lexsort((np.arange(f), -score))
    return np.sort(order[:k])


@register_tool("classification")
class Classification(Tool):
    """Supervised per-object classification (logreg on the MXU, or
    sklearn svm/randomforest).  Payload: ``objects_name``,
    ``training_examples`` ([{site_index, label, class}, ...]),
    optional ``method``, ``features``, ``select_k_best`` (ANOVA-F
    univariate selection).  Reports training_accuracy + per-class
    counts in the result attributes."""

    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        method = payload.get("method", "logreg")
        features = payload.get("features")
        # training examples: [{"site_index": .., "label": .., "class": ..}]
        examples = payload.get("training_examples") or []
        if not examples:
            raise NotSupportedError("classification needs training_examples")

        ids, x, feat_cols = self.load_feature_matrix(objects_name, features)
        key = ids.set_index(["site_index", "label"]).index
        lookup = {t: i for i, t in enumerate(key)}
        class_names = sorted({e["class"] for e in examples})
        cls_index = {c: i for i, c in enumerate(class_names)}

        rows, labels = [], []
        for e in examples:
            t = (e["site_index"], e["label"])
            if t not in lookup:
                raise NotSupportedError(f"training example {t} is not a known object")
            rows.append(lookup[t])
            labels.append(cls_index[e["class"]])
        x_train = x[np.asarray(rows)]
        y_train = np.asarray(labels, np.int32)

        # optional univariate selection BEFORE training (reference tools
        # pass a user-chosen feature subset; this automates it): rank by
        # ANOVA F-score between the training classes, keep the top k
        select_k = payload.get("select_k_best")
        if select_k:
            keep = _kbest_anova(x_train, y_train, len(class_names),
                                int(select_k))
            x, x_train = x[:, keep], x_train[:, keep]
            feat_cols = [feat_cols[i] for i in keep]

        index_info: dict = {}
        if method == "knn":
            # kNN label spreading over the STORE graph: each object's
            # class is the majority among the labeled objects inside
            # its k-neighborhood.  The neighbor sweep routes through
            # the analytics index dispatcher (``index`` / ``top_p``
            # payload knobs, same precedence chain as the knn tool), so
            # at store scale classification goes sublinear too.
            from tmlibrary_tpu.analytics.index import knn_search

            k_nn = int(payload.get("k", 10))
            fs = self.feature_store(objects_name)
            nn_idx, _, index_info = knn_search(
                fs, x, k_nn, mode=payload.get("index"),
                features=feat_cols, top_p=payload.get("top_p"),
            )
            index_info = {"k": k_nn, **index_info}
            n = len(x)
            seeded = np.full(n, -1, np.int64)
            seeded[np.asarray(rows)] = y_train
            neigh = seeded[nn_idx]  # (N, k) class per neighbor, -1 unlabeled
            votes = np.stack(
                [(neigh == c).sum(axis=1) for c in range(len(class_names))],
                axis=1,
            )
            pred = votes.argmax(axis=1)  # ties -> lowest class index
            # objects with no labeled neighbor in range: nearest
            # training example directly (the training matrix is tiny)
            bare = votes.sum(axis=1) == 0
            if bare.any():
                xb = x[bare]
                d2 = (
                    np.sum(xb * xb, axis=1, keepdims=True)
                    - 2.0 * xb @ x_train.T
                    + np.sum(x_train * x_train, axis=1)[None]
                )
                pred[bare] = y_train[np.argmin(d2, axis=1)]
            pred = pred.astype(np.int64)
            pred_train = pred[np.asarray(rows)]
        elif method == "logreg":
            w, b = jax.jit(softmax_train, static_argnums=(2,))(
                jnp.asarray(x_train), jnp.asarray(y_train), len(class_names)
            )
            pred = np.asarray(jnp.argmax(jnp.asarray(x) @ w + b, axis=1))
            pred_train = np.asarray(
                jnp.argmax(jnp.asarray(x_train) @ w + b, axis=1)
            )
        elif method == "svm":
            from sklearn.svm import SVC

            model = SVC(kernel="rbf", gamma="scale")
            model.fit(x_train, y_train)
            pred = model.predict(x)
            pred_train = model.predict(x_train)
        elif method == "randomforest":
            from sklearn.ensemble import RandomForestClassifier

            model = RandomForestClassifier(n_estimators=100, random_state=0)
            model.fit(x_train, y_train)
            pred = model.predict(x)
            pred_train = model.predict(x_train)
        else:
            raise NotSupportedError(f"unknown classification method '{method}'")

        ids["value"] = np.asarray(pred).astype(np.int32)
        # reported metrics (round-3 VERDICT next-step #8): training-set
        # accuracy + per-class counts, so a mislabeled or degenerate
        # training set is visible in the result instead of silently
        # producing a confident-looking layer
        train_counts = {
            c: int((y_train == i).sum()) for c, i in cls_index.items()
        }
        pred_counts = {
            c: int((np.asarray(pred) == i).sum())
            for c, i in cls_index.items()
        }
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="categorical",
            values=ids,
            attributes={
                "method": method,
                "classes": class_names,
                "features": feat_cols,
                "n_training": len(examples),
                "training_accuracy": round(
                    float((pred_train == y_train).mean()), 4
                ),
                "class_counts": {
                    "training": train_counts,
                    "predicted": pred_counts,
                },
                **index_info,
            },
        )
