"""Classification tool: supervised per-object classification.

Reference parity: ``tmlib/tools/classification.py`` — trains an sklearn
SVM or random forest on user-labeled example objects, predicts a class for
every object of the type, and publishes a supervised ``LabelLayer``.

TPU rebuild: the default method is a JAX multinomial logistic regression
(one jitted Adam-free full-batch gradient loop — the feature matrices are
small, the matmuls land on the MXU); ``svm`` and ``randomforest`` keep the
reference's sklearn backends on the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.errors import NotSupportedError
from tmlibrary_tpu.tools.base import Tool, ToolResult, register_tool


def softmax_train(
    x: jax.Array,
    y: jax.Array,
    n_classes: int,
    n_iter: int = 300,
    lr: float = 0.1,
    l2: float = 1e-4,
) -> tuple[jax.Array, jax.Array]:
    """Full-batch multinomial logistic regression; returns (W, b)."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    n, f = x.shape
    w = jnp.zeros((f, n_classes), jnp.float32)
    b = jnp.zeros((n_classes,), jnp.float32)

    def loss_fn(params):
        w, b = params
        logits = x @ w + b
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.mean(logp[jnp.arange(n), y])
        return nll + l2 * jnp.sum(w * w)

    grad_fn = jax.grad(loss_fn)

    def step(params, _):
        g = grad_fn(params)
        return (params[0] - lr * g[0], params[1] - lr * g[1]), None

    (w, b), _ = jax.lax.scan(step, (w, b), None, length=n_iter)
    return w, b


@register_tool("classification")
class Classification(Tool):
    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        method = payload.get("method", "logreg")
        features = payload.get("features")
        # training examples: [{"site_index": .., "label": .., "class": ..}]
        examples = payload.get("training_examples") or []
        if not examples:
            raise NotSupportedError("classification needs training_examples")

        ids, x, feat_cols = self.load_feature_matrix(objects_name, features)
        key = ids.set_index(["site_index", "label"]).index
        lookup = {t: i for i, t in enumerate(key)}
        class_names = sorted({e["class"] for e in examples})
        cls_index = {c: i for i, c in enumerate(class_names)}

        rows, labels = [], []
        for e in examples:
            t = (e["site_index"], e["label"])
            if t not in lookup:
                raise NotSupportedError(f"training example {t} is not a known object")
            rows.append(lookup[t])
            labels.append(cls_index[e["class"]])
        x_train = x[np.asarray(rows)]
        y_train = np.asarray(labels, np.int32)

        if method == "logreg":
            w, b = jax.jit(softmax_train, static_argnums=(2,))(
                jnp.asarray(x_train), jnp.asarray(y_train), len(class_names)
            )
            pred = np.asarray(jnp.argmax(jnp.asarray(x) @ w + b, axis=1))
        elif method == "svm":
            from sklearn.svm import SVC

            model = SVC(kernel="rbf", gamma="scale")
            model.fit(x_train, y_train)
            pred = model.predict(x)
        elif method == "randomforest":
            from sklearn.ensemble import RandomForestClassifier

            model = RandomForestClassifier(n_estimators=100, random_state=0)
            model.fit(x_train, y_train)
            pred = model.predict(x)
        else:
            raise NotSupportedError(f"unknown classification method '{method}'")

        ids["value"] = np.asarray(pred).astype(np.int32)
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="categorical",
            values=ids,
            attributes={
                "method": method,
                "classes": class_names,
                "features": feat_cols,
                "n_training": len(examples),
            },
        )
