"""Interactive analysis tools.

Reference parity: ``tmlib/tools/`` — the ``Tool`` registry
(``classification``, ``clustering``, ``heatmap``), each consuming the
per-object feature values of one mapobject type and producing a
``ToolResult`` with a per-object label layer (``tmlib/models/result.py``
``LabelLayer``/``ToolResult``), plus ``ToolRequestManager``
(``manager.py``) which the server uses to submit tool jobs via GC3Pie.

TPU rebuild: tools read the feature Parquet written by jterator, compute on
device where it pays (JAX k-means, JAX softmax classifier) or via sklearn
(SVM / random forest — CPU, matching the reference's sklearn backends), and
persist results as Parquet + JSON under the experiment's ``tools/`` dir.
The request manager is an in-process call — no job fan-out.
"""

from tmlibrary_tpu.tools.base import (
    Tool,
    ToolRequestManager,
    ToolResult,
    get_tool,
    list_tools,
    register_tool,
)
from tmlibrary_tpu.tools import classification, clustering, heatmap  # noqa: F401
from tmlibrary_tpu.analytics import tools as _analytics_tools  # noqa: F401,E402
# ^ registers knn/pca/embedding/spatial (analytics/tools.py) so every
#   consumer of the registry — tmx tool, tmx query, serve — sees them

__all__ = [
    "Tool",
    "ToolResult",
    "ToolRequestManager",
    "register_tool",
    "get_tool",
    "list_tools",
]
