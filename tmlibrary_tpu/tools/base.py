"""Tool base, registry and request manager.

Reference parity: ``tmlib/tools/base.py`` (``Tool`` ABC + registry),
``tmlib/tools/manager.py`` (``ToolRequestManager``), ``tmlib/tools/jobs.py``
(``ToolJob`` — here an in-process call), ``tmlib/models/result.py``
(``ToolResult``/``LabelLayer`` persisted per submission).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import time
from typing import Any, Type

import numpy as np
import pandas as pd

from tmlibrary_tpu.errors import RegistryError
from tmlibrary_tpu.models.store import ExperimentStore

_TOOLS: dict[str, Type["Tool"]] = {}


def register_tool(name: str):
    def deco(cls):
        cls.name = name
        _TOOLS[name] = cls
        return cls

    return deco


def get_tool(name: str) -> Type["Tool"]:
    try:
        return _TOOLS[name]
    except KeyError:
        raise RegistryError(
            f"no tool '{name}' registered (have: {sorted(_TOOLS)})"
        ) from None


def list_tools() -> list[str]:
    return sorted(_TOOLS)


@dataclasses.dataclass
class ToolResult:
    """Per-object result layer (reference ``ToolResult`` + ``LabelLayer``).

    ``values`` carries one row per object: the object identity columns
    (site_index, label) plus a ``value`` column (class id, cluster id, or
    continuous heatmap value).
    """

    tool: str
    objects_name: str
    layer_type: str  # "categorical" | "continuous"
    values: pd.DataFrame
    attributes: dict[str, Any] = dataclasses.field(default_factory=dict)
    plots: list["Plot"] = dataclasses.field(default_factory=list)

    def label_layer(self) -> "LabelLayer":
        """Materialize the viewer layer for this result (reference: each
        ``ToolResult`` owns a ``LabelLayer`` row)."""
        if self.layer_type == "continuous":
            return ContinuousLabelLayer(self.objects_name, self.values)
        classes = self.attributes.get("classes")
        if classes is not None:
            return SupervisedClassifierLabelLayer(self.objects_name, self.values, classes)
        return ScalarLabelLayer(self.objects_name, self.values)

    def save(self, directory) -> None:
        from pathlib import Path

        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        self.values.to_parquet(d / "values.parquet", index=False)
        (d / "result.json").write_text(
            json.dumps(
                {
                    "tool": self.tool,
                    "objects_name": self.objects_name,
                    "layer_type": self.layer_type,
                    "attributes": self.attributes,
                    "n_objects": int(len(self.values)),
                    "plots": [
                        {"type": p.type, "figure": p.figure} for p in self.plots
                    ],
                },
                default=str,
            )
        )


@dataclasses.dataclass(eq=False)
class LabelLayer:
    """Viewer overlay mapping each object to a display value (reference
    ``tmlib/models/result.py`` ``LabelLayer`` + subtypes).  ``mapping``
    is (site_index, label) → value; subclasses fix the value semantics."""

    objects_name: str
    mapping: pd.DataFrame  # columns: site_index, label, value
    type: str = "generic"

    def value_range(self) -> tuple[float, float]:
        v = self.mapping["value"]
        return float(v.min()), float(v.max())


class ScalarLabelLayer(LabelLayer):
    """Discrete per-object values (reference ``ScalarLabelLayer``)."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame):
        super().__init__(objects_name, mapping, type="scalar")

    def unique_values(self) -> list:
        return sorted(self.mapping["value"].unique().tolist())


class SupervisedClassifierLabelLayer(ScalarLabelLayer):
    """Predicted class per object (reference
    ``SupervisedClassifierLabelLayer``); carries the label→color hints."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame, classes: list[str]):
        super().__init__(objects_name, mapping)
        self.type = "supervised"
        self.classes = list(classes)


class ContinuousLabelLayer(LabelLayer):
    """Continuous per-object values, e.g. heatmap features (reference
    ``ContinuousLabelLayer``)."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame):
        super().__init__(objects_name, mapping, type="continuous")


@dataclasses.dataclass
class Plot:
    """A serializable figure attached to a tool result (reference
    ``tmlib/models/plot.py`` ``Plot``): plotly-style JSON spec + type tag."""

    type: str
    figure: dict[str, Any]

    def to_json(self) -> str:
        return json.dumps({"type": self.type, "figure": self.figure})

    @classmethod
    def from_json(cls, s: str) -> "Plot":
        d = json.loads(s)
        return cls(type=d["type"], figure=d["figure"])


class Tool(abc.ABC):
    """One analysis tool (reference ``tmlib.tools.base.Tool``)."""

    name: str = "tool"

    def __init__(self, store: ExperimentStore):
        self.store = store

    def load_feature_matrix(
        self, objects_name: str, features: list[str] | None = None
    ) -> tuple[pd.DataFrame, np.ndarray, list[str]]:
        """(identity frame, standardized (N, F) matrix, feature names)."""
        table = self.store.read_features(objects_name)
        id_cols = ["site_index", "label"]
        feat_cols = features or [
            c
            for c in table.columns
            if c not in id_cols
            and c not in ("plate", "well_row", "well_col", "site_y", "site_x")
            and np.issubdtype(table[c].dtype, np.number)
        ]
        missing = [c for c in feat_cols if c not in table.columns]
        if missing:
            raise RegistryError(
                f"features not found for '{objects_name}': {missing} "
                f"(have: {sorted(c for c in table.columns if c not in id_cols)})"
            )
        x = table[feat_cols].to_numpy(np.float32)
        # standardize (reference tools z-score before sklearn)
        mu = x.mean(axis=0, keepdims=True)
        sd = x.std(axis=0, keepdims=True)
        x = (x - mu) / np.where(sd > 1e-9, sd, 1.0)
        return table[id_cols + ["plate", "well_row", "well_col"]].copy(), x, feat_cols

    @abc.abstractmethod
    def process(self, payload: dict[str, Any]) -> ToolResult:
        """Handle one tool request (reference ``Tool.process_request``)."""


class ToolRequestManager:
    """Submit tool requests and persist results
    (reference ``tmlib/tools/manager.py``, minus GC3Pie job fan-out)."""

    def __init__(self, store: ExperimentStore):
        self.store = store

    def submit(self, tool_name: str, payload: dict[str, Any]) -> ToolResult:
        tool = get_tool(tool_name)(self.store)
        result = tool.process(payload)
        request_id = f"{tool_name}_{int(time.time() * 1000):x}"
        result.save(self.store.tools_dir / request_id)
        return result

    def list_results(self) -> list[dict]:
        out = []
        for d in sorted(self.store.tools_dir.iterdir()):
            meta = d / "result.json"
            if meta.exists():
                out.append({"request": d.name, **json.loads(meta.read_text())})
        return out
