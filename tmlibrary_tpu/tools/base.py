"""Tool base, registry and request manager.

Reference parity: ``tmlib/tools/base.py`` (``Tool`` ABC + registry),
``tmlib/tools/manager.py`` (``ToolRequestManager``), ``tmlib/tools/jobs.py``
(``ToolJob`` — here an in-process call), ``tmlib/models/result.py``
(``ToolResult``/``LabelLayer`` persisted per submission).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import time
from typing import Any, Type

import numpy as np
import pandas as pd

from tmlibrary_tpu.errors import RegistryError
from tmlibrary_tpu.models.store import ExperimentStore

_TOOLS: dict[str, Type["Tool"]] = {}


def register_tool(name: str):
    def deco(cls):
        cls.name = name
        _TOOLS[name] = cls
        return cls

    return deco


def get_tool(name: str) -> Type["Tool"]:
    try:
        return _TOOLS[name]
    except KeyError:
        raise RegistryError(
            f"no tool '{name}' registered (have: {sorted(_TOOLS)})"
        ) from None


def list_tools() -> list[str]:
    return sorted(_TOOLS)


@dataclasses.dataclass
class ToolResult:
    """Per-object result layer (reference ``ToolResult`` + ``LabelLayer``).

    ``values`` carries one row per object: the object identity columns
    (site_index, label) plus a ``value`` column (class id, cluster id, or
    continuous heatmap value).
    """

    tool: str
    objects_name: str
    layer_type: str  # "categorical" | "continuous"
    values: pd.DataFrame
    attributes: dict[str, Any] = dataclasses.field(default_factory=dict)
    plots: list["Plot"] = dataclasses.field(default_factory=list)

    def label_layer(self) -> "LabelLayer":
        """Materialize the viewer layer for this result (reference: each
        ``ToolResult`` owns a ``LabelLayer`` row)."""
        if self.layer_type == "continuous":
            return ContinuousLabelLayer(self.objects_name, self.values)
        classes = self.attributes.get("classes")
        if classes is not None:
            return SupervisedClassifierLabelLayer(self.objects_name, self.values, classes)
        return ScalarLabelLayer(self.objects_name, self.values)

    def save(self, directory) -> None:
        from pathlib import Path

        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        self.values.to_parquet(d / "values.parquet", index=False)
        (d / "result.json").write_text(
            json.dumps(
                {
                    "tool": self.tool,
                    "objects_name": self.objects_name,
                    "layer_type": self.layer_type,
                    "attributes": self.attributes,
                    "n_objects": int(len(self.values)),
                    "plots": [
                        {"type": p.type, "figure": p.figure} for p in self.plots
                    ],
                },
                default=str,
            )
        )

    @classmethod
    def load(cls, directory) -> "ToolResult":
        """Inverse of :meth:`save`: rebuild the result from a saved
        directory (the serving path for cached query results)."""
        from pathlib import Path

        d = Path(directory)
        meta = json.loads((d / "result.json").read_text())
        return cls(
            tool=meta["tool"],
            objects_name=meta["objects_name"],
            layer_type=meta["layer_type"],
            values=pd.read_parquet(d / "values.parquet"),
            attributes=meta.get("attributes", {}),
            plots=[Plot(type=p["type"], figure=p["figure"])
                   for p in meta.get("plots", [])],
        )


@dataclasses.dataclass(eq=False)
class LabelLayer:
    """Viewer overlay mapping each object to a display value (reference
    ``tmlib/models/result.py`` ``LabelLayer`` + subtypes).  ``mapping``
    is (site_index, label) → value; subclasses fix the value semantics."""

    objects_name: str
    mapping: pd.DataFrame  # columns: site_index, label, value
    type: str = "generic"

    def value_range(self) -> tuple[float, float]:
        v = self.mapping["value"]
        return float(v.min()), float(v.max())

    def export_site_values(
        self, store, directory, tpoint: int = 0, zplane: int = 0
    ) -> "list":
        """Viewer-style per-site export (round-3 VERDICT next-step #8).

        For every site holding mapped objects, writes
        ``<directory>/site_<n>.npz`` with two arrays: ``labels`` — the
        site's segmented label image (int32, as persisted by jterator) —
        and ``values`` — float32, each object's pixels carrying the
        layer's mapped value; background and unmapped objects are NaN
        (NOT 0: class/cluster id 0 is a legitimate mapped value, and a
        0 background would render the first class invisible).  A
        consumer colormaps ``values`` with NaN masked; the reference
        serves the same mapping through ``LabelLayer`` DB tiles.
        Returns the written paths.
        """
        from pathlib import Path

        import numpy as np

        out_dir = Path(directory)
        out_dir.mkdir(parents=True, exist_ok=True)
        written = []
        for site_index, grp in self.mapping.groupby("site_index"):
            if site_index < 0:
                continue  # spatial-layout mosaic rows have no site frame
            labels = store.read_labels(
                [int(site_index)], self.objects_name,
                tpoint=tpoint, zplane=zplane,
            )[0]
            lut = np.full(
                max(int(labels.max()), int(grp["label"].max())) + 1,
                np.nan, np.float32,
            )
            lut[grp["label"].to_numpy(np.int64)] = grp["value"].to_numpy(
                np.float32
            )
            path = out_dir / f"site_{int(site_index):05d}.npz"
            np.savez_compressed(
                path, labels=np.asarray(labels, np.int32), values=lut[labels]
            )
            written.append(path)
        return written


class ScalarLabelLayer(LabelLayer):
    """Discrete per-object values (reference ``ScalarLabelLayer``)."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame):
        super().__init__(objects_name, mapping, type="scalar")

    def unique_values(self) -> list:
        return sorted(self.mapping["value"].unique().tolist())


class SupervisedClassifierLabelLayer(ScalarLabelLayer):
    """Predicted class per object (reference
    ``SupervisedClassifierLabelLayer``); carries the label→color hints."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame, classes: list[str]):
        super().__init__(objects_name, mapping)
        self.type = "supervised"
        self.classes = list(classes)


class ContinuousLabelLayer(LabelLayer):
    """Continuous per-object values, e.g. heatmap features (reference
    ``ContinuousLabelLayer``)."""

    def __init__(self, objects_name: str, mapping: pd.DataFrame):
        super().__init__(objects_name, mapping, type="continuous")


@dataclasses.dataclass
class Plot:
    """A serializable figure attached to a tool result (reference
    ``tmlib/models/plot.py`` ``Plot``): plotly-style JSON spec + type tag."""

    type: str
    figure: dict[str, Any]

    def to_json(self) -> str:
        return json.dumps({"type": self.type, "figure": self.figure})

    @classmethod
    def from_json(cls, s: str) -> "Plot":
        d = json.loads(s)
        return cls(type=d["type"], figure=d["figure"])


class Tool(abc.ABC):
    """One analysis tool (reference ``tmlib.tools.base.Tool``)."""

    name: str = "tool"

    def __init__(self, store: ExperimentStore):
        self.store = store

    def feature_store(self, objects_name: str):
        """The experiment's columnar feature store for ``objects_name``
        (built on first touch, rebuilt when the source shards change)."""
        from tmlibrary_tpu.analytics.store import FeatureStore

        return FeatureStore.ensure(self.store, objects_name)

    def load_feature_matrix(
        self, objects_name: str, features: list[str] | None = None
    ) -> tuple[pd.DataFrame, np.ndarray, list[str]]:
        """(identity frame, standardized (N, F) matrix, feature names).

        Reads through the columnar feature store (``analytics/store.py``)
        rather than re-concatenating Parquet shards per request; the
        standardization contract is unchanged — z-score with finite-mean
        NaN imputation, float32 — so results are identical to the
        pre-store path."""
        return self.feature_store(objects_name).standardized(features)

    @abc.abstractmethod
    def process(self, payload: dict[str, Any]) -> ToolResult:
        """Handle one tool request (reference ``Tool.process_request``)."""


class ToolRequestManager:
    """Submit tool requests with a persisted lifecycle
    (reference ``tmlib/tools/manager.py`` ``ToolRequestManager``: submits
    ``ToolJob``s via GC3Pie and records request state in the DB — here
    the job fan-out is a detached subprocess and the state lives in
    ``<store>/tools/<request>/request.json``).

    States: ``submitted`` → ``running`` → ``done`` | ``failed``.
    """

    def __init__(self, store: ExperimentStore):
        self.store = store

    # ------------------------------------------------------------ lifecycle
    def _request_dir(self, request_id: str) -> "Path":
        return self.store.tools_dir / request_id

    def _write_state(self, request_id: str, **updates: Any) -> dict:
        path = self._request_dir(request_id) / "request.json"
        state = json.loads(path.read_text()) if path.exists() else {}
        state.update(updates)
        path.write_text(json.dumps(state, default=str, sort_keys=True))
        return state

    def create_request(self, tool_name: str, payload: dict[str, Any]) -> str:
        get_tool(tool_name)  # unknown tools fail at submit, not in the job
        base = f"{tool_name}_{int(time.time() * 1000):x}"
        request_id = base
        for attempt in range(1, 1000):
            try:  # same-millisecond submissions must not share a dir
                self._request_dir(request_id).mkdir(parents=True, exist_ok=False)
                break
            except FileExistsError:
                request_id = f"{base}_{attempt}"
        self._write_state(
            request_id,
            tool=tool_name,
            payload=payload,
            state="submitted",
            submitted_at=time.time(),
        )
        return request_id

    def submit(self, tool_name: str, payload: dict[str, Any]) -> ToolResult:
        """Synchronous submit: create the request, run it, return the
        result (the request lifecycle is recorded either way)."""
        return self.run_request(self.create_request(tool_name, payload))

    def submit_async(self, tool_name: str, payload: dict[str, Any]) -> str:
        """Detached submit (reference ``ToolJob`` fan-out): spawns
        ``tmx tool run-request`` as its own session with stdout/stderr
        captured to ``<request>/tool.log`` and returns the request id
        immediately.  Poll with :meth:`status` / ``tmx tool list``."""
        import subprocess
        import sys

        request_id = self.create_request(tool_name, payload)
        log = open(self._request_dir(request_id) / "tool.log", "w")
        subprocess.Popen(
            [
                sys.executable, "-m", "tmlibrary_tpu.cli", "tool",
                "run-request", "--root", str(self.store.root),
                "--request", request_id,
            ],
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        log.close()
        return request_id

    def run_request(self, request_id: str) -> ToolResult:
        """Execute one submitted request, updating its persisted state."""
        req = json.loads(
            (self._request_dir(request_id) / "request.json").read_text()
        )
        self._write_state(request_id, state="running", started_at=time.time())
        try:
            tool = get_tool(req["tool"])(self.store)
            result = tool.process(req["payload"])
            result.save(self._request_dir(request_id))
        except Exception as exc:
            self._write_state(
                request_id, state="failed", finished_at=time.time(),
                error=f"{type(exc).__name__}: {exc}",
            )
            raise
        self._write_state(
            request_id, state="done", finished_at=time.time(),
            layer_type=result.layer_type, n_objects=int(len(result.values)),
        )
        return result

    def status(self, request_id: str) -> dict:
        path = self._request_dir(request_id) / "request.json"
        if not path.exists():
            # pre-ledger request dirs hold only result.json; report them
            # exactly the way list_requests() does
            if (self._request_dir(request_id) / "result.json").exists():
                return {"request": request_id, "state": "done"}
            raise RegistryError(f"no tool request '{request_id}'")
        return {"request": request_id, **json.loads(path.read_text())}

    def list_requests(self) -> list[dict]:
        """Every request with its lifecycle state, newest last.  Requests
        predating the lifecycle ledger (bare result dirs) appear as
        ``done`` with no timing."""
        out = []
        for d in sorted(self.store.tools_dir.iterdir()):
            meta = d / "request.json"
            if meta.exists():
                entry = {"request": d.name, **json.loads(meta.read_text())}
                entry.pop("payload", None)  # keep the listing line compact
                out.append(entry)
            elif (d / "result.json").exists():
                out.append({"request": d.name, "state": "done"})
        return out

    def list_results(self) -> list[dict]:
        out = []
        for d in sorted(self.store.tools_dir.iterdir()):
            meta = d / "result.json"
            if meta.exists():
                out.append({"request": d.name, **json.loads(meta.read_text())})
        return out
