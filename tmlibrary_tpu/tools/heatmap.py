"""Heatmap tool: one feature as a continuous per-object layer.

Reference parity: ``tmlib/tools/heatmap.py`` — selects a single feature of
a mapobject type and publishes it as a continuous ``LabelLayer`` (the UI
colors objects by value).
"""

from __future__ import annotations

import numpy as np

from tmlibrary_tpu.errors import NotSupportedError
from tmlibrary_tpu.tools.base import Plot, Tool, ToolResult, register_tool


@register_tool("heatmap")
class Heatmap(Tool):
    """One feature as a continuous per-object layer plus a per-well
    plate_heatmap Plot.  Payload: ``objects_name``, ``feature``.
    Attributes carry min/max and the robust p01/p99 display window."""

    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        feature = payload.get("feature")
        if not feature:
            raise NotSupportedError("heatmap needs a 'feature'")
        fs = self.feature_store(objects_name)
        if feature not in fs.features:
            raise NotSupportedError(
                f"feature '{feature}' not found (have: "
                f"{sorted(c for c in fs.features if c.startswith(('Intensity', 'Morphology', 'Texture', 'Zernike')))})"
            )
        ids = fs.identity()
        vals = fs.column(feature).astype(np.float64)
        ids["value"] = vals

        # the classic plate heatmap: per-well mean of the feature, as a
        # serializable Plot (reference heatmap results feed the UI's
        # plate view) + robust display window in the attributes
        plots = []
        if len(vals):
            # finite-only means: an all-NaN well (degenerate-object
            # features) must not leak literal NaN through json.dumps
            # into result.json; such wells carry mean null instead.
            # Group the UNFILTERED ids so every observed well stays in
            # the list — a consumer must be able to tell an all-NaN well
            # from one outside the plate.
            keys = ["plate", "well_row", "well_col"]
            finite_ids = ids[np.isfinite(vals)]
            well_mean = (
                ids[keys].drop_duplicates()
                .merge(
                    finite_ids.groupby(keys)["value"].mean().reset_index(),
                    on=keys, how="left",
                )
                .sort_values(keys)
            )
            plots.append(Plot(
                type="plate_heatmap",
                figure={
                    "feature": feature,
                    "wells": [
                        {
                            "plate": r.plate,
                            "well_row": int(r.well_row),
                            "well_col": int(r.well_col),
                            "mean": (
                                float(r.value)
                                if np.isfinite(r.value) else None
                            ),
                        }
                        for r in well_mean.itertuples()
                    ],
                },
            ))
        finite = vals[np.isfinite(vals)]
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="continuous",
            values=ids,
            attributes={
                "feature": feature,
                "min": float(finite.min()) if len(finite) else 0.0,
                "max": float(finite.max()) if len(finite) else 0.0,
                # robust window: the UI stretch the reference applies
                "p01": float(np.percentile(finite, 1)) if len(finite) else 0.0,
                "p99": float(np.percentile(finite, 99)) if len(finite) else 0.0,
                "n_objects": int(len(vals)),
            },
            plots=plots,
        )
