"""Heatmap tool: one feature as a continuous per-object layer.

Reference parity: ``tmlib/tools/heatmap.py`` — selects a single feature of
a mapobject type and publishes it as a continuous ``LabelLayer`` (the UI
colors objects by value).
"""

from __future__ import annotations

import numpy as np

from tmlibrary_tpu.errors import NotSupportedError
from tmlibrary_tpu.tools.base import Tool, ToolResult, register_tool


@register_tool("heatmap")
class Heatmap(Tool):
    def process(self, payload: dict) -> ToolResult:
        objects_name = payload["objects_name"]
        feature = payload.get("feature")
        if not feature:
            raise NotSupportedError("heatmap needs a 'feature'")
        table = self.store.read_features(objects_name)
        if feature not in table.columns:
            raise NotSupportedError(
                f"feature '{feature}' not found (have: "
                f"{sorted(c for c in table.columns if c.startswith(('Intensity', 'Morphology', 'Texture', 'Zernike')))})"
            )
        ids = table[["site_index", "label", "plate", "well_row", "well_col"]].copy()
        vals = table[feature].to_numpy(np.float64)
        ids["value"] = vals
        return ToolResult(
            tool=self.name,
            objects_name=objects_name,
            layer_type="continuous",
            values=ids,
            attributes={
                "feature": feature,
                "min": float(np.nanmin(vals)) if len(vals) else 0.0,
                "max": float(np.nanmax(vals)) if len(vals) else 0.0,
            },
        )
