"""Profiling and tracing.

Reference parity: the reference has no built-in profiler (SURVEY.md §6 —
GC3Pie records per-job wall/cpu time in task state; per-job timing lands in
the submission tables).  The TPU rebuild does better: the run ledger already
records per-step/per-batch wall time (``workflow/engine.py``), and this
module adds device-level tracing via ``jax.profiler`` so kernel time on the
TPU can be inspected with TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
from pathlib import Path


@contextlib.contextmanager
def device_trace(log_dir: str | Path | None):
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set.

    No-op when ``log_dir`` is None so call sites can pass the CLI flag
    straight through.  The trace directory is TensorBoard-compatible
    (``tensorboard --logdir <dir>`` → Profile tab / xprof).
    """
    if log_dir is None:
        yield
        return
    import jax

    path = Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path)):
        yield


