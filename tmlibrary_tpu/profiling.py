"""Profiling and tracing.

Reference parity: the reference has no built-in profiler (SURVEY.md §6 —
GC3Pie records per-job wall/cpu time in task state; per-job timing lands in
the submission tables).  The TPU rebuild does better: the run ledger already
records per-step/per-batch wall time (``workflow/engine.py``), and this
module adds device-level tracing via ``jax.profiler`` so kernel time on the
TPU can be inspected with TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
from pathlib import Path

#: pipeline phases in execution order; keys of ``PipelineStats.summary()``
PIPELINE_PHASES = ("prefetch_wait", "dispatch", "device_block", "persist")


class PipelineStats:
    """Per-batch phase timers for the pipelined batch executor.

    Each batch flows through up to four phases — waiting on the prefetch
    worker (``prefetch_wait``), async device dispatch on the main thread
    (``dispatch``), blocking on device arrays (``device_block``) and
    host-side writes (``persist``) — and the executor records each
    duration here.  The summary lands in the ``step_done`` ledger event
    as ``pipeline_stats`` and in ``tmx … status``, so a stalled pipeline
    (device starved on prefetch, or persist eating the window) is
    diagnosable from the ledger alone, without an XProf trace.

    Thread-safe: dispatch timings come from the main thread while
    device-block/persist timings come from persist workers.
    """

    def __init__(self, depth: int, source: str = "explicit"):
        self.depth = int(depth)
        self.source = source
        self._lock = threading.Lock()
        self._total = {phase: 0.0 for phase in PIPELINE_PHASES}
        self._max = {phase: 0.0 for phase in PIPELINE_PHASES}
        self._count = {phase: 0 for phase in PIPELINE_PHASES}
        self._batches = 0
        self._clamps: list[dict] = []

    def record(self, phase: str, seconds: float) -> None:
        with self._lock:
            self._total[phase] += seconds
            self._count[phase] += 1
            if seconds > self._max[phase]:
                self._max[phase] = seconds

    def batch_done(self) -> None:
        with self._lock:
            self._batches += 1

    def record_clamp(self, from_depth: int, to_depth: int) -> None:
        with self._lock:
            self._clamps.append({"from": int(from_depth), "to": int(to_depth)})
            self.depth = int(to_depth)

    def summary(self) -> dict:
        """JSON-ready roll-up for the run ledger."""
        with self._lock:
            out = {
                "depth": self.depth,
                "source": self.source,
                "n_batches": self._batches,
                "phases": {
                    phase: {
                        "total_s": round(self._total[phase], 4),
                        "max_s": round(self._max[phase], 4),
                    }
                    for phase in PIPELINE_PHASES
                    if self._count[phase]
                },
            }
            if self._clamps:
                out["depth_clamps"] = list(self._clamps)
            return out


@contextlib.contextmanager
def device_trace(log_dir: str | Path | None):
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set.

    No-op when ``log_dir`` is None so call sites can pass the CLI flag
    straight through.  The trace directory is TensorBoard-compatible
    (``tensorboard --logdir <dir>`` → Profile tab / xprof).
    """
    if log_dir is None:
        yield
        return
    import jax

    path = Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path)):
        yield


