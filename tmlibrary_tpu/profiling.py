"""Profiling and tracing.

Reference parity: the reference has no built-in profiler (SURVEY.md §6 —
GC3Pie records per-job wall/cpu time in task state; per-job timing lands in
the submission tables).  The TPU rebuild does better: the run ledger already
records per-step/per-batch wall time (``workflow/engine.py``), and this
module adds device-level tracing via ``jax.profiler`` so kernel time on the
TPU can be inspected with TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
from pathlib import Path

from tmlibrary_tpu import telemetry

#: pipeline phases in execution order; keys of ``PipelineStats.summary()``
PIPELINE_PHASES = ("prefetch_wait", "dispatch", "device_block", "persist")

#: which resource each phase spends — the basis of the device/host time
#: split in ``tmx perf`` and the ``tmx_perf_{device,host}_seconds_total``
#: gauges.  ``dispatch`` is async launch work attributable to keeping the
#: device fed; ``device_block`` is literal device wait; prefetch/persist
#: are pure host IO.
PHASE_RESOURCE = {
    "prefetch_wait": "host",
    "dispatch": "device",
    "device_block": "device",
    "persist": "host",
}


class PipelineStats:
    """Per-batch phase timers for the pipelined batch executor.

    Each batch flows through up to four phases — waiting on the prefetch
    worker (``prefetch_wait``), async device dispatch on the main thread
    (``dispatch``), blocking on device arrays (``device_block``) and
    host-side writes (``persist``) — and the executor records each
    duration here.  The summary lands in the ``step_done`` ledger event
    as ``pipeline_stats`` and in ``tmx … status``, so a stalled pipeline
    (device starved on prefetch, or persist eating the window) is
    diagnosable from the ledger alone, without an XProf trace.

    Phase timings are held in bounded-reservoir histograms
    (``telemetry.Histogram``), so the summary carries p50/p95 alongside
    the original ``total_s``/``max_s`` keys (ledger shape stays
    backward-compatible).  When the telemetry registry is enabled the
    same observations are mirrored into ``tmx_pipeline_phase_seconds``
    registry histograms, and per-batch (phase, seconds, t0) records are
    buffered for the executor to flush as ``span`` ledger events.

    Thread-safe: dispatch timings come from the main thread while
    device-block/persist timings come from persist workers.
    """

    def __init__(self, depth: int, source: str = "explicit", step: str = ""):
        self.depth = int(depth)
        self.source = source
        self.step = step
        self._lock = threading.Lock()
        self._hist = {
            phase: telemetry.Histogram(phase, {}) for phase in PIPELINE_PHASES
        }
        reg = telemetry.get_registry()
        self._reg_hist = {
            phase: reg.histogram(
                "tmx_pipeline_phase_seconds", step=step or "unknown",
                phase=phase,
            )
            for phase in PIPELINE_PHASES
        }
        self._batches = 0
        self._clamps: list[dict] = []
        #: batch index → [(phase, seconds, wall t0)], drained by the
        #: executor on the calling thread to emit ``span`` ledger events
        self._batch_spans: dict[int, list[tuple[str, float, float]]] = {}

    def record(self, phase: str, seconds: float,
               batch: int | None = None, t0: float | None = None) -> None:
        self._hist[phase].observe(seconds)
        self._reg_hist[phase].observe(seconds)
        if batch is not None and telemetry.enabled():
            with self._lock:
                self._batch_spans.setdefault(batch, []).append(
                    (phase, seconds, t0 if t0 is not None else 0.0)
                )

    def pop_batch_spans(self, batch: int) -> list[tuple[str, float, float]]:
        """Drain the buffered phase records for ``batch`` (span emission)."""
        with self._lock:
            return self._batch_spans.pop(batch, [])

    def batch_done(self) -> None:
        with self._lock:
            self._batches += 1

    def record_clamp(self, from_depth: int, to_depth: int) -> None:
        with self._lock:
            self._clamps.append({"from": int(from_depth), "to": int(to_depth)})
            self.depth = int(to_depth)

    def summary(self) -> dict:
        """JSON-ready roll-up for the run ledger.

        ``total_s``/``max_s`` keys are load-bearing (pinned by
        ``tests/test_pipelined.py`` and rendered by ``tmx … status``);
        ``p50_s``/``p95_s``/``count`` are additive.
        """
        with self._lock:
            batches = self._batches
            clamps = list(self._clamps)
        phases = {}
        for phase in PIPELINE_PHASES:
            hist = self._hist[phase]
            if not hist.count:
                continue
            phases[phase] = {
                "total_s": round(hist.sum, 4),
                "max_s": round(hist.max, 4),
                "p50_s": round(hist.quantile(0.5), 4),
                "p95_s": round(hist.quantile(0.95), 4),
                "count": hist.count,
            }
        out = {
            "depth": self.depth,
            "source": self.source,
            "n_batches": batches,
            "phases": phases,
        }
        device_s = sum(
            p["total_s"] for ph, p in phases.items()
            if PHASE_RESOURCE.get(ph) == "device"
        )
        host_s = sum(
            p["total_s"] for ph, p in phases.items()
            if PHASE_RESOURCE.get(ph) == "host"
        )
        if phases:
            # additive (ledger shape stays backward-compatible): the
            # device/host attribution consumed by `tmx perf`
            out["device_s"] = round(device_s, 4)
            out["host_s"] = round(host_s, 4)
            if telemetry.enabled():
                reg = telemetry.get_registry()
                label = self.step or "unknown"
                reg.gauge(
                    "tmx_perf_device_seconds_total", step=label
                ).set(round(device_s, 4))
                reg.gauge(
                    "tmx_perf_host_seconds_total", step=label
                ).set(round(host_s, 4))
                if device_s + host_s > 0:
                    reg.gauge("tmx_perf_device_frac", step=label).set(
                        round(device_s / (device_s + host_s), 4)
                    )
        if clamps:
            out["depth_clamps"] = clamps
        return out


@contextlib.contextmanager
def device_trace(log_dir: str | Path | None):
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set.

    No-op when ``log_dir`` is None so call sites can pass the CLI flag
    straight through.  The trace directory is TensorBoard-compatible
    (``tensorboard --logdir <dir>`` → Profile tab / xprof).  While the
    trace is active, telemetry spans double as
    ``jax.profiler.TraceAnnotation`` scopes so host spans line up with
    device timelines in XProf.
    """
    if log_dir is None:
        yield
        return
    import jax

    path = Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    telemetry.set_trace_bridge(True)
    try:
        with jax.profiler.trace(str(path)):
            yield
    finally:
        telemetry.set_trace_bridge(False)
