"""Exception hierarchy.

Reference parity: ``tmlib/errors.py`` — the reference defines a small tree of
library-specific errors (``MetadataError``, ``PipelineError``,
``JobDescriptionError``, ``NotSupportedError``, ``RegistryError``).  We keep
the same names so error-handling code written against the reference maps
directly, and add TPU-rebuild-specific errors for the store and mesh layers.
"""


class TmError(Exception):
    """Base class for all framework errors."""


class MetadataError(TmError):
    """Error in experiment/image metadata handling."""


class VendorConflictError(MetadataError):
    """Vendor files make mutually-exclusive claims (e.g. two containers on
    one well).  Unlike an unparseable sidecar, this is a data-integrity
    problem: metaconfig's ``auto`` handler loop re-raises it instead of
    falling through to the next handler."""


class PipelineError(TmError):
    """Error in the jterator pipeline description or execution."""


class PipelineDescriptionError(PipelineError):
    """Invalid ``.pipe`` pipeline description."""


class HandleError(PipelineError):
    """Invalid module handle description or binding."""


class JobDescriptionError(TmError):
    """Error in a batch/job description."""


class NotSupportedError(TmError):
    """Requested feature is not supported."""


class RegistryError(TmError):
    """Error looking up a registered step/module/tool."""


class StoreError(TmError):
    """Error in the array/feature store layer."""


class WorkflowError(TmError):
    """Error in workflow orchestration (stage/step DAG, ledger, resume)."""


class ShardingError(TmError):
    """Error constructing or using a device mesh / sharding."""
