"""Exception hierarchy.

Reference parity: ``tmlib/errors.py`` — the reference defines a small tree of
library-specific errors (``MetadataError``, ``PipelineError``,
``JobDescriptionError``, ``NotSupportedError``, ``RegistryError``).  We keep
the same names so error-handling code written against the reference maps
directly, and add TPU-rebuild-specific errors for the store and mesh layers.
"""


class TmError(Exception):
    """Base class for all framework errors."""


class MetadataError(TmError):
    """Error in experiment/image metadata handling."""


class VendorConflictError(MetadataError):
    """Vendor files make mutually-exclusive claims (e.g. two containers on
    one well).  Unlike an unparseable sidecar, this is a data-integrity
    problem: metaconfig's ``auto`` handler loop re-raises it instead of
    falling through to the next handler."""


class PipelineError(TmError):
    """Error in the jterator pipeline description or execution."""


class PipelineDescriptionError(PipelineError):
    """Invalid ``.pipe`` pipeline description."""


class HandleError(PipelineError):
    """Invalid module handle description or binding."""


class JobDescriptionError(TmError):
    """Error in a batch/job description."""


class NotSupportedError(TmError):
    """Requested feature is not supported."""


class RegistryError(TmError):
    """Error looking up a registered step/module/tool."""


class StoreError(TmError):
    """Error in the array/feature store layer."""


class WorkflowError(TmError):
    """Error in workflow orchestration (stage/step DAG, ledger, resume)."""


class ShardingError(TmError):
    """Error constructing or using a device mesh / sharding."""


class TransientDeviceError(TmError):
    """A device-side fault that is expected to clear on its own: the TPU
    relay dropped, a device probe timed out, a collective was preempted,
    or the backend reported UNAVAILABLE/DEADLINE_EXCEEDED.  The retry
    policy treats this class (and look-alike messages from the runtime)
    as retryable; everything data-shaped stays permanent."""


class ProbeTimeoutError(TransientDeviceError):
    """A device health probe did not answer within its deadline — the
    signature of a down relay, which *hangs* instead of erroring.  Raised
    by ``resilience.call_with_timeout``; trips the circuit breaker."""


class WatchdogTimeout(TransientDeviceError):
    """A pipeline phase (launch / device block / persist) overran its
    watchdog deadline (``resilience.PhaseWatchdog``).  Subclasses
    :class:`TransientDeviceError` so the classifier treats a hung
    ``block_until_ready`` exactly like a dropped relay: retryable, and
    breaker-visible."""


class PreemptedError(TmError):
    """The run was asked to stop (SIGTERM/SIGINT preemption) and has
    finished draining: every in-flight batch either persisted with its
    ledger event or was abandoned un-launched.  Deliberately NOT a
    :class:`WorkflowError` — the engine's step-failure handlers must not
    record a drained run as a failed step (the ledger boundary is clean
    and ``resume`` continues from it).

    ``in_flight`` is the pipelined window size when the drain began,
    ``drained`` how many of those persisted during the drain, and
    ``abandoned`` how many planned batches were never launched."""

    def __init__(self, message: str, step: str | None = None,
                 in_flight: int = 0, drained: int = 0, abandoned: int = 0,
                 reason: str = "signal"):
        super().__init__(message)
        self.step = step
        self.in_flight = in_flight
        self.drained = drained
        self.abandoned = abandoned
        self.reason = reason


class FaultInjected(TmError):
    """An artificial fault raised by the deterministic fault-injection
    harness (``tmlibrary_tpu.faults``).  Never raised in production —
    only when a fault plan is installed.  ``transient`` mirrors how the
    error classifier should treat it; ``fatal=True`` simulates a hard
    process crash the engine must NOT absorb into batch quarantine."""

    def __init__(self, message: str, kind: str = "injected",
                 transient: bool = True, fatal: bool = False):
        super().__init__(message)
        self.kind = kind
        self.transient = transient
        self.fatal = fatal
