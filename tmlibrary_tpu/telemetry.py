"""Unified telemetry: metrics registry, span tracing, resource sampling.

Reference parity: the reference stack has no first-class telemetry — GC3Pie
keeps per-job wall/cpu time in submission tables and everything else is
hand-read from logs (SURVEY.md §6).  The TPU rebuild's run ledger already
captures per-batch wall time; this module aggregates it into queryable
metrics and adds what the ledger alone cannot show:

* a process-wide :class:`MetricsRegistry` — counters, gauges and
  bounded-reservoir histograms (p50/p95/max) — fed by the workflow engine,
  the pipelined executor, ``resilience.py`` and the throughput-critical
  steps (corilla/illuminati/jterator);
* lightweight nested **spans** (run → step → batch → phase) recorded as
  ``span`` events in the run ledger and, while ``profiling.device_trace``
  is active, bridged into ``jax.profiler.TraceAnnotation`` so host spans
  line up with device traces in XProf;
* a :class:`ResourceSampler` daemon thread (RSS, open file handles, jax
  device memory when available) that also maintains a heartbeat timestamp
  file consumed by ``tmx workflow status`` and ``scripts/tpu_watch.py``;
* export surfaces: Prometheus textfile format and JSON, renderable from
  the live registry or derived post-hoc from any ledger
  (:func:`registry_from_ledger`), plus a span-tree builder with
  critical-path annotation for ``tmx trace``.

Telemetry is zero-cost-when-disabled: a disabled registry hands out shared
null instruments whose methods are no-ops, and :func:`span` yields without
touching clocks.  Nothing here may perturb numeric results — a
telemetry-on run stays bit-identical to telemetry-off (pinned by
``tests/test_telemetry.py``).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator

from tmlibrary_tpu.errors import FaultInjected
from tmlibrary_tpu.log import warn_once

logger = logging.getLogger(__name__)

#: cap on per-histogram reservoir samples; bounds memory for long runs
RESERVOIR_SIZE = 512

HEARTBEAT_FILENAME = "heartbeat.json"


# ---------------------------------------------------------------------------
# instruments


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-written instantaneous value."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded-reservoir distribution: exact count/sum/max, sampled quantiles.

    The reservoir keeps the most recent :data:`RESERVOIR_SIZE` observations
    (ring buffer) — enough for stable p50/p95 on per-batch timings while
    bounding memory on runs with hundreds of thousands of batches.
    """

    __slots__ = ("name", "labels", "_lock", "_count", "_sum", "_max",
                 "_reservoir", "_next")

    def __init__(self, name: str, labels: dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._reservoir: list[float] = []
        self._next = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value
            if len(self._reservoir) < RESERVOIR_SIZE:
                self._reservoir.append(value)
            else:
                self._reservoir[self._next] = value
                self._next = (self._next + 1) % RESERVOIR_SIZE

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def quantile(self, q: float) -> float:
        with self._lock:
            sample = sorted(self._reservoir)
        if not sample:
            return 0.0
        idx = min(len(sample) - 1, max(0, int(round(q * (len(sample) - 1)))))
        return sample[idx]

    def summary(self) -> dict:
        with self._lock:
            sample = sorted(self._reservoir)
            count, total, vmax = self._count, self._sum, self._max
        out = {"count": count, "sum": round(total, 6), "max": round(vmax, 6)}
        if sample:
            def _q(q: float) -> float:
                idx = min(len(sample) - 1,
                          max(0, int(round(q * (len(sample) - 1)))))
                return round(sample[idx], 6)
            out["p50"] = _q(0.5)
            out["p95"] = _q(0.95)
        return out


class ThroughputTracker:
    """Units/sec gauge using the same wall-clock math as ``bench.py``.

    ``bench.py`` divides units of work by ``time.perf_counter`` wall time;
    call sites here do the same per batch — measure the batch with
    ``perf_counter`` and :meth:`add` ``(units, seconds)`` — so the gauge
    (cumulative units / cumulative seconds) converges to the bench figure
    for the same workload.
    """

    __slots__ = ("_gauge", "_counter", "_lock", "_seconds", "_units")

    def __init__(self, gauge: "Gauge | _NullGauge",
                 counter: "Counter | _NullCounter"):
        self._gauge = gauge
        self._counter = counter
        self._lock = threading.Lock()
        self._seconds = 0.0
        self._units = 0.0

    def add(self, units: float, seconds: float) -> None:
        with self._lock:
            self._units += units
            self._seconds += seconds
            rate = self._units / self._seconds if self._seconds > 0 else 0.0
        self._counter.inc(units)
        self._gauge.set(rate)


class _NullInstrument:
    """Shared no-op instrument for the disabled registry."""

    __slots__ = ()
    name = ""
    labels: dict[str, str] = {}
    value = 0.0
    count = 0
    sum = 0.0
    max = 0.0

    def inc(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def add(self, units: float, seconds: float = 0.0) -> None:
        pass

    def quantile(self, q: float) -> float:
        return 0.0

    def summary(self) -> dict:
        return {"count": 0, "sum": 0.0, "max": 0.0}


_NullCounter = _NullGauge = _NullHistogram = _NullInstrument
_NULL = _NullInstrument()


class MetricsRegistry:
    """Thread-safe, process-wide instrument store.

    When ``enabled`` is False every accessor returns the shared null
    instrument, so instrumented call sites cost one attribute lookup and a
    no-op method call — nothing allocates and no lock is taken.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._instruments: dict[tuple, Any] = {}
        self._trackers: dict[str, ThroughputTracker] = {}
        #: monotonic snapshot counter — with ``captured_at`` it makes
        #: every snapshot self-describing about its age, so the fleet
        #: merge can prefer the newer capture on gauge collisions
        self._sequence = 0

    def _get(self, cls, name: str, labels: dict[str, str]):
        if not self.enabled:
            return _NULL
        key = (cls.__name__, name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, labels)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get(Histogram, name, labels)

    def throughput(self, name: str, **labels: str) -> ThroughputTracker:
        """Units/sec gauge ``<name>`` backed by counter ``<name>_units_total``."""
        if not self.enabled:
            return _NULL
        key = f"{name}|{_label_key(labels)}"
        with self._lock:
            tracker = self._trackers.get(key)
        if tracker is None:
            tracker = ThroughputTracker(
                self.gauge(name, **labels),
                self.counter(name + "_units_total", **labels),
            )
            with self._lock:
                tracker = self._trackers.setdefault(key, tracker)
        return tracker

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._trackers.clear()

    def snapshot(self) -> dict:
        """JSON-ready dump of every instrument, stable ordering.

        Stamped with ``captured_at`` (wall time) and a monotonic
        ``sequence`` so downstream consumers — the fleet merge's
        newer-capture-wins gauge fold, the time-series flush hook — can
        order captures without trusting file mtimes.  A disabled
        registry keeps the bare unstamped shape: it records nothing, so
        there is no capture to order."""
        out: dict[str, Any] = {"counters": [], "gauges": [], "histograms": []}
        if not self.enabled:
            return out
        with self._lock:
            instruments = sorted(self._instruments.items())
            self._sequence += 1
            seq = self._sequence
        out["captured_at"] = round(time.time(), 6)
        out["sequence"] = seq
        for (kind, _name, _labels), inst in instruments:
            entry = {"name": inst.name, "labels": dict(inst.labels)}
            if kind == "Counter":
                entry["value"] = inst.value
                out["counters"].append(entry)
            elif kind == "Gauge":
                entry["value"] = round(inst.value, 6)
                out["gauges"].append(entry)
            else:
                entry.update(inst.summary())
                out["histograms"].append(entry)
        return out


# ---------------------------------------------------------------------------
# module-level registry

_registry: MetricsRegistry | None = None
_registry_lock = threading.Lock()


def _default_enabled() -> bool:
    from tmlibrary_tpu.config import cfg

    return bool(getattr(cfg, "telemetry", True))


def get_registry() -> MetricsRegistry:
    global _registry
    reg = _registry
    if reg is None:
        with _registry_lock:
            reg = _registry
            if reg is None:
                reg = _registry = MetricsRegistry(enabled=_default_enabled())
    return reg


def enabled() -> bool:
    return get_registry().enabled


def set_enabled(flag: bool) -> None:
    get_registry().enabled = bool(flag)


def reset_registry(enabled: bool | None = None) -> MetricsRegistry:
    """Replace the process registry (tests, fresh CLI runs)."""
    global _registry
    with _registry_lock:
        _registry = MetricsRegistry(
            enabled=_default_enabled() if enabled is None else enabled
        )
    return _registry


# ---------------------------------------------------------------------------
# fleet identity (multi-host label semantics)
#
# Label conventions for fleet-scope series (DESIGN.md §17):
#   host   — one value per process in the run ("host0", "host1", ...)
#   device — a local device id within a host ("0".."7")
#   step   — the workflow step that produced the observation
# The labels ride the existing instrument kwargs, so a disabled registry
# still hands out the shared null instrument: labeled metrics cost nothing
# when telemetry is off.


def host_id() -> str:
    """Stable identity of this process within a (possibly multi-host) run.

    Resolution order: explicit ``TMX_HOST_ID`` (the simulated-fleet knob
    CI uses), the standard ``JAX_PROCESS_ID`` a pod launcher exports
    (``parallel.distributed.initialize`` mirrors its resolved process id
    into the env), else ``host0``.  Env-only on purpose: querying jax for
    ``process_index`` would initialize a backend, and telemetry must
    never be the thing that does that.
    """
    explicit = os.environ.get("TMX_HOST_ID")
    if explicit:
        return explicit
    pid = os.environ.get("JAX_PROCESS_ID")
    if pid is not None:
        try:
            return f"host{int(pid)}"
        except ValueError:
            return f"host-{pid}"
    return "host0"


def fleet_active() -> bool:
    """True when this process is one of several in a fleet — a real
    multi-host launch (``JAX_NUM_PROCESSES`` > 1) or a simulated one
    (``TMX_HOST_ID`` set).  Gates the per-event ``host`` field in the run
    ledger so single-host ledgers keep their seed-era shape."""
    if os.environ.get("TMX_HOST_ID"):
        return True
    try:
        return int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1) > 1
    except ValueError:
        return False


@contextlib.contextmanager
def collective_span(name: str, **labels: str) -> Iterator[None]:
    """Bracket the host-side donated call that launches a collective
    (psum/all_gather/all_to_all/ppermute halo exchange/reshard).

    Dispatch is async, so this times what the host actually pays to get
    the collective in flight — observed into
    ``tmx_collective_seconds{collective=...,host=...}``.  Zero-cost when
    telemetry is disabled: no clock is read and no instrument allocated.
    """
    if not enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        get_registry().histogram(
            "tmx_collective_seconds", collective=name, host=host_id(),
            **labels,
        ).observe(time.perf_counter() - t0)


def device_wall_times(outputs: Any, t0: float) -> list[tuple[str, float]]:
    """Per-device wall time (seconds since ``t0``, a ``perf_counter``
    reading taken at launch) until each device's shard of a dispatched
    computation is ready.

    Picks the first leaf of ``outputs`` sharded over more than one device
    and blocks its addressable shards in device-id order, stamping the
    clock as each completes — a host-visible per-device completion
    profile of the shard_map program (the straggler is the device whose
    shard is ready last).  Returns ``[]`` when nothing is sharded or
    shard introspection is unavailable, so call sites can gate on
    ``telemetry.enabled()`` and fall through to a plain block.
    """
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(outputs)
    except Exception:
        return []
    for leaf in leaves:
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        try:
            shards = sorted(shards, key=lambda s: s.device.id)
        except Exception:
            continue
        if len(shards) < 2:
            continue
        times: list[tuple[str, float]] = []
        try:
            for shard in shards:
                shard.data.block_until_ready()
                times.append(
                    (str(shard.device.id), time.perf_counter() - t0)
                )
        except Exception:
            return []
        return times
    return []


def straggler_threshold(slowest: float) -> float:
    """Skew above which a batch counts as straggling: the larger of an
    absolute floor (``TMX_STRAGGLER_MIN_S``, default 0.05 s — CPU-sim
    noise stays below it) and a fraction of the slowest device's wall
    time (``TMX_STRAGGLER_REL``, default 0.25)."""
    try:
        floor = float(os.environ.get("TMX_STRAGGLER_MIN_S", "0.05"))
    except ValueError:
        floor = 0.05
    try:
        rel = float(os.environ.get("TMX_STRAGGLER_REL", "0.25"))
    except ValueError:
        rel = 0.25
    return max(floor, rel * float(slowest))


def record_device_times(times: list[tuple[str, float]], step: str = "",
                        batch: Any = None,
                        predicted: "list[float] | None" = None) -> float:
    """Feed per-device batch wall times into the labeled registry series
    and return the straggler skew (max − min over devices).

    Sets ``tmx_device_batch_seconds{device=,host=,step=}`` per device
    (plus a ``_hist`` histogram so p50/p95 survive the last-write gauge)
    and ``tmx_straggler_skew_seconds{host=,step=}``; bumps
    ``tmx_stragglers_total`` when the skew clears
    :func:`straggler_threshold`.  When the scheduler's ``predicted``
    per-shard work rides along (same order as ``times``), each device's
    prediction is published as
    ``tmx_device_predicted_work{device=,host=,step=}`` plus a predicted
    skew gauge — the pair lets the anomaly plane tell data skew
    (predicted AND actual both skewed) from a slow device (actual only).
    The *ledger* ``straggler`` event is the caller's job (the engine
    appends it on its own thread from the batch summary) — this function
    only touches the thread-safe registry, so it is safe from executor
    worker threads.
    """
    if not enabled() or not times:
        return 0.0
    reg = get_registry()
    h = host_id()
    step = step or "unknown"
    vals = [float(t) for _, t in times]
    skew = max(vals) - min(vals)
    pred = None
    if predicted is not None and len(predicted) == len(times):
        pred = [float(p) for p in predicted]
    for i, (dev, t) in enumerate(times):
        reg.gauge("tmx_device_batch_seconds", device=str(dev), host=h,
                  step=step).set(float(t))
        reg.histogram("tmx_device_batch_seconds_hist", device=str(dev),
                      host=h, step=step).observe(float(t))
        if pred is not None:
            reg.gauge("tmx_device_predicted_work", device=str(dev), host=h,
                      step=step).set(pred[i])
    reg.gauge("tmx_straggler_skew_seconds", host=h, step=step).set(skew)
    if pred is not None:
        reg.gauge("tmx_predicted_work_skew", host=h, step=step).set(
            max(pred) - min(pred)
        )
    if skew > straggler_threshold(max(vals)):
        reg.counter("tmx_stragglers_total", host=h, step=step).inc()
    return skew


# ---------------------------------------------------------------------------
# span tracing

_trace_bridge = threading.Event()


def set_trace_bridge(active: bool) -> None:
    """Toggled by ``profiling.device_trace`` so spans double as
    ``jax.profiler.TraceAnnotation`` scopes only while a device trace is
    being captured (TraceAnnotation outside a trace is wasted work)."""
    if active:
        _trace_bridge.set()
    else:
        _trace_bridge.clear()


_span_local = threading.local()


def _span_stack() -> list[str]:
    stack = getattr(_span_local, "stack", None)
    if stack is None:
        stack = _span_local.stack = []
    return stack


@contextlib.contextmanager
def span(name: str, emit: Callable[..., Any] | None = None,
         **attrs: Any) -> Iterator[None]:
    """Nested host span; records a ``span`` ledger event via ``emit``.

    ``emit`` is typically ``RunLedger.append`` partial-applied with the
    step/batch context.  Zero-cost when telemetry is disabled.
    """
    if not enabled():
        yield
        return
    stack = _span_stack()
    stack.append(name)
    path = "/".join(stack)
    annotation = None
    if _trace_bridge.is_set():
        try:
            import jax

            annotation = jax.profiler.TraceAnnotation(path)
            annotation.__enter__()
        except Exception:  # pragma: no cover - profiler unavailable
            annotation = None
    t0 = time.time()
    p0 = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - p0
        if annotation is not None:
            with contextlib.suppress(Exception):
                annotation.__exit__(None, None, None)
        stack.pop()
        # a fatal injected fault simulates hard process death — a dead
        # process writes nothing, so the span must not land either (the
        # chaos suite pins that the torn ledger line stays trailing)
        exc = sys.exc_info()[1]
        if isinstance(exc, FaultInjected) and exc.fatal:
            emit = None
        if emit is not None:
            try:
                emit(event="span", span=name, path=path, t0=round(t0, 6),
                     elapsed=round(elapsed, 6), **attrs)
            except Exception:
                logger.debug("span emit failed for %s", path, exc_info=True)


# ---------------------------------------------------------------------------
# trace context (request-scoped labels for the serving path)
#
# `tmx enqueue` stamps a trace_id into the job spec; the serve daemon opens
# a trace scope around each job execution, and RunLedger.append stamps the
# scope's labels onto every event it seals — so one trace id covers
# enqueue → admission → queue wait → run → step → batch → phase without
# threading job identity through every engine call site.  Process-level on
# purpose (not thread-local): the daemon executes one job at a time, while
# span events surface from executor worker threads that must inherit the
# job's identity.

_trace_ctx: dict[str, Any] = {}


def trace_context() -> dict[str, Any]:
    """The active trace labels (``trace_id``/``job``/``tenant``); empty
    outside a job scope."""
    return dict(_trace_ctx)


def set_trace_context(**labels: Any) -> None:
    """Replace the process trace labels (None values dropped; no labels
    clears the context)."""
    global _trace_ctx
    _trace_ctx = {k: v for k, v in labels.items() if v is not None}


@contextlib.contextmanager
def trace_scope(**labels: Any) -> Iterator[None]:
    """Install trace labels for the duration of one job execution,
    restoring the previous scope on exit (exception-safe)."""
    global _trace_ctx
    prev = _trace_ctx
    _trace_ctx = {**prev,
                  **{k: v for k, v in labels.items() if v is not None}}
    try:
        yield
    finally:
        _trace_ctx = prev


# ---------------------------------------------------------------------------
# flight recorder (bounded ring of the last N ledger events per process)
#
# Fed by RunLedger.append, dumped on watchdog fire / preemption drain /
# shed storm / unhandled crash so a post-mortem sees the exact event tail
# that preceded the incident even when the process died before sealing a
# snapshot.  Zero-cost when telemetry is disabled: no ring is allocated,
# no event is copied (shared null-instrument discipline).

_FLIGHT_DEFAULT_N = 256
_flight: "Any | None" = None  # collections.deque, lazily allocated
_flight_lock = threading.Lock()


def _flight_capacity() -> int:
    try:
        n = int(os.environ.get("TMX_FLIGHTREC_N", "") or _FLIGHT_DEFAULT_N)
    except ValueError:
        return _FLIGHT_DEFAULT_N
    return max(8, n)


def flight_record(event: dict) -> None:
    """Append one event to the flight-recorder ring (no-op when telemetry
    is disabled)."""
    if not enabled():
        return
    global _flight
    ring = _flight
    if ring is None:
        with _flight_lock:
            ring = _flight
            if ring is None:
                import collections

                ring = _flight = collections.deque(
                    maxlen=_flight_capacity()
                )
    ring.append(event)


def flight_events() -> list[dict]:
    """The ring's current contents, oldest first (tests/inspection)."""
    ring = _flight
    return list(ring) if ring else []


def reset_flight_recorder() -> None:
    """Drop the ring (tests, fresh daemon starts)."""
    global _flight
    with _flight_lock:
        _flight = None


def flight_dump(path: Path | str, reason: str = "",
                extra: dict | None = None) -> str | None:
    """Dump the ring to ``path`` via an atomic write; returns the path, or
    None when the ring is empty/unallocated or the write failed.  Never
    raises — the flight recorder is a post-mortem aid, not a failure
    source."""
    ring = _flight
    if not ring:
        return None
    payload = {
        "host": host_id(),
        "pid": os.getpid(),
        "reason": reason or "manual",
        "dumped_at": round(time.time(), 6),
        "capacity": ring.maxlen,
        "events": list(ring),
    }
    if extra:
        payload.update(extra)
    try:
        from tmlibrary_tpu.atomicio import atomic_write_json

        atomic_write_json(Path(path), payload)
    except Exception:
        logger.debug("flight-recorder dump to %s failed", path,
                     exc_info=True)
        return None
    return str(path)


def flightrec_path(directory: Path | str) -> Path:
    """Canonical per-host dump location under a workflow/serve dir."""
    return Path(directory) / f"flightrec.{host_id()}.json"


# ---------------------------------------------------------------------------
# resource sampler


def _rss_bytes() -> int | None:
    try:
        with open("/proc/self/statm") as fh:
            fields = fh.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # pragma: no cover - non-POSIX
            return None


def _open_fds() -> int | None:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # pragma: no cover - non-Linux
        return None


def _device_memory_bytes() -> int | None:
    """Sum of ``bytes_in_use`` across local devices, None when unknown.

    Only consulted when jax is already imported — the sampler must never
    be the thing that initialises a backend.
    """
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        total = 0
        seen = False
        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                seen = True
        return total if seen else None
    except Exception:
        return None


def heartbeat_path(workflow_dir: Path, host: str | None = None) -> Path:
    """Where this host's heartbeat lives: the legacy single-host name for
    ``host0`` (so existing status/watcher consumers keep working), a
    per-host ``heartbeat.<host>.json`` for every other fleet member."""
    h = host or host_id()
    if h == "host0":
        return Path(workflow_dir) / HEARTBEAT_FILENAME
    return Path(workflow_dir) / f"heartbeat.{h}.json"


def snapshot_path(workflow_dir: Path, host: str | None = None) -> Path:
    """This host's registry-snapshot file (``metrics.<host>.json``)."""
    return Path(workflow_dir) / f"metrics.{host or host_id()}.json"


def write_heartbeat(path: Path, period: float,
                    extra: dict | None = None) -> None:
    """Atomically write the heartbeat timestamp file (``atomicio`` —
    the PID-suffixed tmp name keeps concurrent writers from clobbering
    each other's staging file)."""
    from tmlibrary_tpu.atomicio import atomic_write_json

    payload = {"ts": time.time(), "pid": os.getpid(), "period": period,
               "host": host_id()}
    if extra:
        payload.update(extra)
    atomic_write_json(path, payload)


def read_heartbeat(path: Path) -> dict | None:
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def heartbeat_age(path: Path, now: float | None = None) -> float | None:
    """Seconds since the heartbeat was last refreshed.

    Uses the fresher of the embedded writer timestamp and the file's
    mtime: on a shared filesystem the mtime comes from one clock while
    the embedded ``ts`` comes from the writing host's, so cross-host
    clock skew can make either look stale on its own — a LIVE run must
    never be flagged hung because two clocks disagree.  Both stale means
    genuinely stale.  Clamped at zero (a writer clock ahead of the
    reader's would otherwise go negative)."""
    hb = read_heartbeat(path)
    if hb is None or "ts" not in hb:
        return None
    now = time.time() if now is None else now
    age = now - float(hb["ts"])
    try:
        age = min(age, now - Path(path).stat().st_mtime)
    except OSError:
        pass
    return max(0.0, age)


class ResourceSampler:
    """Daemon thread sampling process/device resources on a fixed period.

    Each tick sets gauges (``tmx_process_rss_bytes``,
    ``tmx_process_open_fds``, ``tmx_device_bytes_in_use``) and refreshes the
    heartbeat file so ``tmx workflow status`` and ``scripts/tpu_watch.py``
    can tell a hung run from a slow one.
    """

    def __init__(self, period: float, heartbeat_path: Path | None = None,
                 registry: MetricsRegistry | None = None):
        self.period = max(float(period), 0.1)
        self.heartbeat_path = (
            Path(heartbeat_path) if heartbeat_path is not None else None
        )
        self.registry = registry if registry is not None else get_registry()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample_once(self) -> dict:
        sample: dict[str, Any] = {}
        rss = _rss_bytes()
        if rss is not None:
            sample["rss_bytes"] = rss
            self.registry.gauge("tmx_process_rss_bytes").set(rss)
        fds = _open_fds()
        if fds is not None:
            sample["open_fds"] = fds
            self.registry.gauge("tmx_process_open_fds").set(fds)
        dev = _device_memory_bytes()
        if dev is not None:
            sample["device_bytes_in_use"] = dev
            self.registry.gauge("tmx_device_bytes_in_use").set(dev)
        elif "jax" in sys.modules:
            # CPU-only hosts have a backend but no memory stats — say so
            # once, not every sample period (log.reset_warned clears the
            # suppression between tests)
            warn_once(
                logger, "resource-sampler-device-memory",
                "resource sampler: device memory stats unavailable on "
                "this host (CPU-only backend?) — tmx_device_bytes_in_use "
                "will not be exported",
            )
        if self.heartbeat_path is not None:
            try:
                write_heartbeat(self.heartbeat_path, self.period, extra=sample)
            except OSError:
                logger.debug("heartbeat write failed", exc_info=True)
        return sample

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # pragma: no cover - defensive
                logger.debug("resource sample failed", exc_info=True)
            self._stop.wait(self.period)

    def start(self) -> "ResourceSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tmx-resource-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self) -> "ResourceSampler":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# export: Prometheus textfile + JSON


def _prom_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_line(name: str, labels: dict[str, str], value: float,
               extra_labels: dict[str, str] | None = None) -> str:
    merged = dict(labels)
    if extra_labels:
        merged.update(extra_labels)
    if merged:
        inner = ",".join(
            f'{k}="{_prom_escape(str(v))}"' for k, v in sorted(merged.items())
        )
        return f"{name}{{{inner}}} {value:g}"
    return f"{name} {value:g}"


def render_prometheus(snapshot: dict) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` as Prometheus textfile
    exposition format (counters, gauges, histograms-as-summaries)."""
    lines: list[str] = []
    seen_types: set[str] = set()

    def _header(name: str, kind: str) -> None:
        if name not in seen_types:
            seen_types.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for entry in snapshot.get("counters", []):
        _header(entry["name"], "counter")
        lines.append(_prom_line(entry["name"], entry["labels"], entry["value"]))
    for entry in snapshot.get("gauges", []):
        _header(entry["name"], "gauge")
        lines.append(_prom_line(entry["name"], entry["labels"], entry["value"]))
    for entry in snapshot.get("histograms", []):
        name = entry["name"]
        _header(name, "summary")
        labels = entry["labels"]
        for q_key, q in (("p50", "0.5"), ("p95", "0.95")):
            if q_key in entry:
                lines.append(
                    _prom_line(name, labels, entry[q_key], {"quantile": q})
                )
        lines.append(_prom_line(name + "_sum", labels, entry["sum"]))
        lines.append(_prom_line(name + "_count", labels, entry["count"]))
        lines.append(_prom_line(name + "_max", labels, entry["max"]))
    return "\n".join(lines) + "\n"


def render_json(snapshot: dict) -> str:
    return json.dumps(snapshot, indent=2, sort_keys=True)


def _prom_unescape(value: str) -> str:
    """Inverse of :func:`_prom_escape` (``\\\\``, ``\\"``, ``\\n``)."""
    out: list[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_label_body(body: str, lineno: int) -> dict[str, str]:
    """Escape-aware label-body scanner for :func:`parse_prometheus`.

    A naive ``split(",")`` mis-tokenizes any label *value* containing a
    comma, ``=`` or an escaped quote — all of which :func:`_prom_escape`
    legitimately produces — so rendered output would fail its own
    parser.  This scanner walks the quoted strings honoring the text
    format's three escapes (``\\\\``, ``\\"``, ``\\n``), making
    every rendered exposition round-trip exactly."""
    labels: dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        if body[i] == ",":
            i += 1
            continue
        eq = body.find("=", i)
        if eq < 0:
            raise ValueError(f"line {lineno}: bad label body {body!r}")
        key = body[i:eq].strip()
        if not key:
            raise ValueError(f"line {lineno}: empty label name in {body!r}")
        i = eq + 1
        if i >= n or body[i] != '"':
            raise ValueError(f"line {lineno}: unquoted value for {key!r}")
        i += 1
        buf: list[str] = []
        closed = False
        while i < n:
            ch = body[i]
            if ch == "\\" and i + 1 < n:
                nxt = body[i + 1]
                if nxt == "n":
                    buf.append("\n")
                    i += 2
                    continue
                if nxt in ('"', "\\"):
                    buf.append(nxt)
                    i += 2
                    continue
                buf.append(ch)
                i += 1
                continue
            if ch == '"':
                closed = True
                i += 1
                break
            buf.append(ch)
            i += 1
        if not closed:
            raise ValueError(
                f"line {lineno}: unterminated value for {key!r}")
        if i < n and body[i] != ",":
            raise ValueError(
                f"line {lineno}: junk after value for {key!r}")
        labels[key] = "".join(buf)
    return labels


def parse_prometheus(text: str) -> list[tuple[str, dict[str, str], float]]:
    """Minimal exposition-format parser (used by tests to validate output).

    Returns ``(name, labels, value)`` samples; raises ``ValueError`` on any
    malformed line so tests can assert validity of the rendered output.
    """
    samples: list[tuple[str, dict[str, str], float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) < 4 or parts[1] not in ("TYPE", "HELP"):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            continue
        name, labels, rest = line, {}, None
        if "{" in line:
            name, _, tail = line.partition("{")
            body, _, rest = tail.rpartition("}")
            if not rest or not rest.strip():
                raise ValueError(f"line {lineno}: bad sample {line!r}")
            labels = _parse_label_body(body, lineno)
        else:
            name, _, rest = line.partition(" ")
        if not name or not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"line {lineno}: bad metric name {name!r}")
        try:
            value = float(rest.strip().split()[0])
        except (ValueError, IndexError, AttributeError):
            raise ValueError(f"line {lineno}: bad value in {line!r}")
        samples.append((name, labels, value))
    return samples


# ---------------------------------------------------------------------------
# multi-host aggregation: per-host snapshots → one fleet view


def load_fleet_snapshots(run_root: Path) -> list[tuple[str, dict]]:
    """Discover per-host registry snapshots under a run root.

    Accepts the experiment-store root or its ``workflow/`` directory and
    returns sorted ``(host, snapshot)`` pairs from every readable
    ``metrics.<host>.json``.  The legacy single-host ``metrics.json``
    maps to ``host0`` and is skipped when a per-host host0 snapshot also
    exists (each host0 run writes both with identical content)."""
    root = Path(run_root)
    if (root / "workflow").is_dir():
        root = root / "workflow"
    hosts: dict[str, dict] = {}
    legacy: dict | None = None
    for path in sorted(root.glob("metrics*.json")):
        stem = path.name[len("metrics"):-len(".json")].strip(".")
        try:
            snap = json.loads(path.read_text())
        except (OSError, ValueError):
            logger.warning("skipping unreadable snapshot %s", path)
            continue
        if not isinstance(snap, dict):
            continue
        if stem:
            hosts[stem] = snap
        else:
            legacy = snap
    if legacy is not None and "host0" not in hosts:
        hosts["host0"] = legacy
    return sorted(hosts.items())


def merge_snapshots(
    host_snapshots: Iterable[tuple[str, dict]]
) -> dict:
    """Merge per-host :meth:`MetricsRegistry.snapshot` dumps into one
    fleet view.

    Every series gains a ``host`` label (a host label the series already
    carries wins, so device series recorded with explicit host labels
    are not re-tagged).  Series that still collide on (kind, name,
    labels) — the same host contributing twice — are folded: counters
    and histogram count/sum add, gauges keep the last value, max keeps
    the max, and histogram quantiles follow the larger sample.  The
    result renders through :func:`render_prometheus` /
    :func:`render_json` unchanged.

    Gauge collisions resolve by capture recency: snapshots stamp
    ``captured_at``/``sequence`` (:meth:`MetricsRegistry.snapshot`), and
    the newer capture's value wins regardless of the order the snapshot
    files were globbed in.  Un-stamped (pre-stamp-era) snapshots fall
    back to the old last-write-wins behavior."""
    out: dict[str, list] = {"counters": [], "gauges": [], "histograms": []}
    index: dict[tuple, dict] = {}
    stamps: dict[tuple, tuple] = {}
    for host, snap in host_snapshots:
        stamp = None
        if snap.get("captured_at") is not None:
            try:
                stamp = (float(snap["captured_at"]),
                         float(snap.get("sequence", 0) or 0))
            except (TypeError, ValueError):
                stamp = None
        for kind in ("counters", "gauges", "histograms"):
            for entry in snap.get(kind, []) or []:
                labels = dict(entry.get("labels") or {})
                labels.setdefault("host", str(host))
                key = (kind, entry.get("name"), _label_key(labels))
                merged = index.get(key)
                if merged is None:
                    merged = dict(entry)
                    merged["labels"] = labels
                    index[key] = merged
                    out[kind].append(merged)
                    if stamp is not None:
                        stamps[key] = stamp
                elif kind == "counters":
                    merged["value"] = (merged.get("value", 0.0)
                                       + entry.get("value", 0.0))
                elif kind == "gauges":
                    prev = stamps.get(key)
                    if stamp is None or prev is None or stamp >= prev:
                        merged["value"] = entry.get(
                            "value", merged.get("value", 0.0))
                        if stamp is None:
                            stamps.pop(key, None)
                        else:
                            stamps[key] = stamp
                else:
                    if entry.get("count", 0) > merged.get("count", 0):
                        for q in ("p50", "p95"):
                            if q in entry:
                                merged[q] = entry[q]
                    merged["count"] = (merged.get("count", 0)
                                       + entry.get("count", 0))
                    merged["sum"] = round(
                        merged.get("sum", 0.0) + entry.get("sum", 0.0), 6
                    )
                    merged["max"] = max(merged.get("max", 0.0),
                                        entry.get("max", 0.0))
    for kind in out:
        out[kind].sort(
            key=lambda e: (e.get("name", ""), sorted(e["labels"].items()))
        )
    return out


# ---------------------------------------------------------------------------
# ledger → metrics derivation (post-hoc inspection of any run, incl. seed-era)


def _observe_slo(reg: MetricsRegistry, tenant: str, outcome: str,
                 elapsed_s, hl: dict) -> None:
    """Feed the ``tmx_slo_*`` series from one job-completion event — the
    single definition both the live daemon and ledger replay use, so a
    replayed registry matches what the daemon showed (slo.py owns the
    objective/burn math; these are just the raw series)."""
    from tmlibrary_tpu import slo

    slo.observe_job(reg, tenant, outcome, elapsed_s, **hl)


def registry_from_ledger(events: Iterable[dict]) -> MetricsRegistry:
    """Derive a metrics registry from run-ledger events.

    Works on seed-era ledgers (``batch_done``/``step_done`` only) as well
    as telemetry-era ledgers carrying ``span`` events — old runs stay
    inspectable with the same ``tmx metrics`` surface.  Fleet-era events
    carry a ``host`` field; those series gain a ``host`` label so
    interleaved multi-host ledgers aggregate without collisions, and
    exact-duplicate records (the same host's ledger read twice, or one
    physical event copied into several per-host ledgers) are dropped.
    """
    reg = MetricsRegistry(enabled=True)
    step_units: dict[tuple[str, str], dict[str, float]] = {}
    occ_acc = [0.0, 0.0]  # running (sum, n) of per-batch slot occupancy
    # running (routed capacity, ladder ceiling) sums: per batch the slot
    # ratio cap/ceiling is the padded-work fraction kept, so the sums
    # reconstruct padded-FLOPs-avoided from the ledger alone (batches
    # predating the bucket_ceiling field simply don't contribute)
    pad_acc = [0.0, 0.0]
    seen: set[tuple] = set()
    for ev in events:
        kind = ev.get("event")
        step = str(ev.get("step", "")) or "unknown"
        host = str(ev.get("host", "")) if ev.get("host") else ""
        if host:
            # dedup only host-attributed events: seed-era ledgers have no
            # host field and legitimately repeat (event, step) shapes
            fp = (host, ev.get("ts"), kind, step, ev.get("batch"),
                  ev.get("span"), ev.get("job"))
            if fp in seen:
                continue
            seen.add(fp)
        hl = {"host": host} if host else {}
        if kind == "run_started":
            reg.counter("tmx_runs_total", **hl).inc()
        elif kind == "batch_done":
            reg.counter("tmx_batches_done_total", step=step, **hl).inc()
            if "elapsed" in ev:
                reg.histogram("tmx_batch_seconds", step=step, **hl).observe(
                    float(ev["elapsed"])
                )
            attempts = int(ev.get("attempts", 1) or 1)
            if attempts > 1:
                reg.counter("tmx_batch_retries_total", step=step, **hl).inc(
                    attempts - 1
                )
            result = ev.get("result") or {}
            if isinstance(result, dict):
                acc = step_units.setdefault(
                    (step, host), {"units": 0.0, "seconds": 0.0}
                )
                acc["seconds"] += float(ev.get("elapsed", 0.0) or 0.0)
                for key in ("n_sites", "n_tiles"):
                    if key in result:
                        acc["units"] += float(result[key])
                        break
                else:
                    acc["units"] += 1.0
                # object-capacity bucket routing (capacity.py): batch
                # summaries self-describe their routed capacity + slot
                # occupancy, so ledger-derived metrics expose the same
                # gauges the live registry does
                cap = result.get("bucket_capacity")
                if cap is not None:
                    reg.counter(
                        "tmx_jterator_bucket_routed_total",
                        capacity=str(cap),
                    ).inc()
                    esc = int(result.get("bucket_escalations", 0) or 0)
                    if esc:
                        reg.counter(
                            "tmx_jterator_bucket_saturated_total"
                        ).inc(esc)
                    occ = result.get("slot_occupancy")
                    if occ is not None:
                        occ_acc[0] += float(occ)
                        occ_acc[1] += 1.0
                        reg.gauge("tmx_jterator_slot_occupancy").set(
                            occ_acc[0] / occ_acc[1]
                        )
                    ceiling = result.get("bucket_ceiling")
                    if ceiling:
                        pad_acc[0] += float(cap)
                        pad_acc[1] += float(ceiling)
                        reg.gauge(
                            "tmx_jterator_padded_flops_avoided_frac"
                        ).set(1.0 - pad_acc[0] / pad_acc[1])
                # fleet-era batch summaries embed per-device wall times
                # measured at block time, so ledger-derived metrics carry
                # the same device series the live registry does
                dev_times = result.get("device_wall_times")
                if isinstance(dev_times, dict) and dev_times:
                    for dev, secs in sorted(dev_times.items()):
                        reg.gauge(
                            "tmx_device_batch_seconds",
                            device=str(dev), step=step, **hl,
                        ).set(float(secs))
                skew = result.get("straggler_skew_s")
                if skew is not None:
                    reg.gauge(
                        "tmx_straggler_skew_seconds", step=step, **hl
                    ).set(float(skew))
        elif kind == "straggler":
            reg.counter("tmx_stragglers_total", step=step, **hl).inc()
            if "skew_s" in ev:
                reg.gauge(
                    "tmx_straggler_skew_seconds", step=step, **hl
                ).set(float(ev["skew_s"]))
        elif kind == "batch_failed":
            reg.counter("tmx_batches_failed_total", step=step, **hl).inc()
        elif kind in ("step_done", "step_partial"):
            if kind == "step_partial":
                reg.counter("tmx_steps_partial_total", step=step, **hl).inc()
            else:
                reg.counter("tmx_steps_done_total", step=step, **hl).inc()
            if "elapsed" in ev:
                reg.histogram("tmx_step_seconds", step=step, **hl).observe(
                    float(ev["elapsed"])
                )
            quarantined = ev.get("quarantined") or []
            if quarantined:
                reg.counter(
                    "tmx_batches_quarantined_total", step=step, **hl
                ).inc(len(quarantined))
            ps = ev.get("pipeline_stats")
            if isinstance(ps, dict):
                reg.gauge("tmx_pipeline_depth", step=step).set(
                    ps.get("depth", 0)
                )
                for phase, vals in (ps.get("phases") or {}).items():
                    reg.gauge(
                        "tmx_pipeline_phase_seconds_total",
                        step=step, phase=phase,
                    ).set(vals.get("total_s", 0.0))
                    reg.gauge(
                        "tmx_pipeline_phase_seconds_max",
                        step=step, phase=phase,
                    ).set(vals.get("max_s", 0.0))
        elif kind == "step_failed":
            reg.counter("tmx_steps_failed_total", step=step, **hl).inc()
        elif kind == "depth_clamped":
            reg.counter("tmx_depth_clamps_total", step=step, **hl).inc()
        elif kind == "backend_degraded":
            reg.counter("tmx_backend_degradations_total", **hl).inc()
        elif kind == "span":
            name = str(ev.get("span", "")) or "unknown"
            if "elapsed" in ev:
                reg.histogram("tmx_span_seconds", span=name, **hl).observe(
                    float(ev["elapsed"])
                )
        elif kind == "qc_batch":
            # QC summary gauge fields are run-cumulative at append time
            # (qc.QCSession.observe_batch), so replaying them with
            # last-write-wins gauge semantics reconstructs exactly what
            # the live registry showed
            s = ev.get("summary") or {}
            if isinstance(s, dict):
                for ch, entry in sorted((s.get("channels") or {}).items()):
                    if "focus_min" in entry:
                        reg.gauge("tmx_qc_worst_focus",
                                  channel=str(ch), **hl).set(
                            float(entry["focus_min"]))
                    if "saturation_max" in entry:
                        reg.gauge("tmx_qc_max_saturation_frac",
                                  channel=str(ch), **hl).set(
                            float(entry["saturation_max"]))
                    if "background_mean" in entry:
                        reg.gauge("tmx_qc_background_mean",
                                  channel=str(ch), **hl).set(
                            float(entry["background_mean"]))
                if "nan_columns" in s:
                    reg.gauge("tmx_qc_nan_columns", **hl).set(
                        float(s.get("nan_columns") or 0))
                bad = (int(s.get("nan_values") or 0)
                       + int(s.get("inf_values") or 0))
                if bad:
                    reg.counter("tmx_qc_nan_values_total", **hl).inc(bad)
                if "count_z_max" in s:
                    reg.gauge("tmx_qc_count_z_max", **hl).set(
                        float(s.get("count_z_max") or 0.0))
        elif kind == "qc_site":
            reg.counter("tmx_qc_sites_flagged_total", step=step, **hl).inc()
        elif kind == "qc_budget_exceeded":
            reg.counter("tmx_qc_budget_exceeded_total",
                        step=step, **hl).inc()
        elif kind == "first_batch":
            # cold-start attribution (engine.py): wall seconds from
            # run_started to the first persisted batch — the number the
            # aotstore warm path exists to shrink
            if "time_to_first_batch_s" in ev:
                reg.gauge("tmx_time_to_first_batch_seconds", **hl).set(
                    float(ev["time_to_first_batch_s"]))
        elif kind == "run_preempted":
            reg.counter("tmx_preemptions_total", **hl).inc()
        elif kind == "watchdog":
            reg.counter(
                "tmx_watchdog_fired_total", step=step,
                phase=str(ev.get("phase", "")) or "unknown", **hl,
            ).inc()
        elif kind in ("job_admitted", "job_rejected", "job_done",
                      "job_failed", "job_expired", "job_requeued",
                      "job_reclaimed", "stale_claim",
                      "job_started", "serve_preempted", "slo_burn",
                      "query_fused"):
            # serve-ledger events (serve.py): per-tenant admission /
            # outcome series, mirroring the daemon's live tmx_serve_*
            # and tmx_slo_* metrics so a serve ledger alone reconstructs
            # them (order-independent, like the fleet merge)
            if ev.get("kind") == "canary":
                # canary probes (canary.py) are invisible to tenants:
                # they feed their own tmx_canary_* series — never the
                # per-tenant serve counters and never the SLO series —
                # exactly as the live daemon records them
                if kind == "job_admitted":
                    reg.counter("tmx_canary_probes_total", **hl).inc()
                elif kind == "job_done":
                    reg.counter("tmx_canary_ok_total", **hl).inc()
                    if "elapsed_s" in ev:
                        reg.histogram("tmx_canary_latency_seconds",
                                      **hl).observe(float(ev["elapsed_s"]))
                    if ev.get("degraded"):
                        reg.counter("tmx_canary_degraded_total",
                                    **hl).inc()
                elif kind == "job_failed":
                    reg.counter("tmx_canary_failed_total", **hl).inc()
                continue
            tenant = str(ev.get("tenant", "")) or "unknown"
            if kind == "job_admitted":
                reg.counter("tmx_serve_admitted_total",
                            tenant=tenant, **hl).inc()
                if "queue_wait_s" in ev:
                    reg.histogram("tmx_serve_queue_wait_seconds",
                                  tenant=tenant, **hl).observe(
                        float(ev["queue_wait_s"]))
                if ev.get("affinity") == "hit":
                    # fleet affinity routing (serve.py): the claiming
                    # host's compiled-program cache was already warm
                    reg.counter("tmx_serve_affinity_hits_total",
                                tenant=tenant, **hl).inc()
            elif kind == "job_started":
                if "sched_delay_s" in ev:
                    reg.histogram("tmx_serve_sched_delay_seconds",
                                  tenant=tenant, **hl).observe(
                        float(ev["sched_delay_s"]))
            elif kind == "slo_burn":
                # warn-only breach events (slo.py) — same contract as QC
                reg.counter(
                    "tmx_slo_burn_total", tenant=tenant,
                    window=str(ev.get("window", "")) or "unknown", **hl,
                ).inc()
            elif kind == "job_rejected":
                reason = str(ev.get("reason", "")) or "unknown"
                reg.counter("tmx_serve_rejected_total", tenant=tenant,
                            reason=reason, **hl).inc()
                from tmlibrary_tpu.workflow.admission import SHED_REASONS

                if reason in SHED_REASONS:
                    reg.counter("tmx_serve_shed_total",
                                tenant=tenant, **hl).inc()
            elif kind == "job_done":
                reg.counter("tmx_serve_jobs_done_total",
                            tenant=tenant, **hl).inc()
                if "elapsed_s" in ev:
                    reg.histogram("tmx_serve_job_seconds",
                                  tenant=tenant, **hl).observe(
                        float(ev["elapsed_s"]))
                _observe_slo(reg, tenant, "ok", ev.get("elapsed_s"), hl)
                # warm-start provenance (aotstore): done events carry the
                # job's cold-compile / store-import deltas; replayed
                # totals match the live ones summed across programs (the
                # live series carry a program label the ledger does not)
                if ev.get("compiles_cold"):
                    reg.counter("tmx_compile_cold_total", **hl).inc(
                        int(ev["compiles_cold"]))
                if ev.get("compile_imports"):
                    reg.counter("tmx_compile_import_hit_total", **hl).inc(
                        int(ev["compile_imports"]))
                if ev.get("kind") == "query" and ev.get("tool"):
                    # analytics query jobs (serve.py _run_query): replay
                    # the tmx_analytics_* series run_query fed live —
                    # the event carries the exact observed values
                    tool = str(ev["tool"])
                    cache = str(ev.get("cache", "")) or "unknown"
                    reg.counter("tmx_analytics_queries_total",
                                tool=tool, cache=cache, **hl).inc()
                    if cache == "hit":
                        reg.counter("tmx_analytics_cache_hits_total",
                                    tool=tool, **hl).inc()
                    if ev.get("query_elapsed_s") is not None:
                        reg.histogram("tmx_analytics_query_seconds",
                                      tool=tool, **hl).observe(
                            float(ev["query_elapsed_s"]))
                    reg.counter("tmx_analytics_jobs_total",
                                tenant=tenant, tool=tool, **hl).inc()
                    # index lifecycle: only miss events carry these (the
                    # one path that drove an index ensure), so replayed
                    # build/hit/fallback counts equal the live ones
                    if ev.get("index_cache") == "build":
                        reg.counter(
                            "tmx_analytics_index_builds_total").inc()
                    elif ev.get("index_cache") == "hit":
                        reg.counter(
                            "tmx_analytics_index_hits_total").inc()
                    if ev.get("index_fallback"):
                        reg.counter(
                            "tmx_analytics_index_fallbacks_total").inc()
            elif kind == "job_failed":
                reg.counter("tmx_serve_jobs_failed_total",
                            tenant=tenant, **hl).inc()
                _observe_slo(reg, tenant, "failed", None, hl)
            elif kind == "job_expired":
                reg.counter("tmx_serve_deadline_expired_total",
                            tenant=tenant, **hl).inc()
                _observe_slo(reg, tenant, "expired", None, hl)
            elif kind == "job_requeued":
                reg.counter("tmx_serve_requeued_total",
                            tenant=tenant, **hl).inc()
            elif kind == "job_reclaimed":
                # the reaper swept a dead host's leased job back to
                # incoming/ (serve.py _reclaim) — attempt preserved, so
                # no retry-budget series moves here
                reg.counter("tmx_serve_reclaims_total",
                            tenant=tenant, **hl).inc()
            elif kind == "stale_claim":
                # a fenced terminal transition: the claim epoch check
                # stopped a reclaimed job's first owner from publishing
                reg.counter("tmx_serve_stale_claims_total",
                            tenant=tenant, **hl).inc()
            elif kind == "query_fused":
                # one batched sweep served `window` query jobs (serve.py
                # _run_query fusion) — same series the daemon fed live
                window = float(ev.get("window") or 0)
                reg.counter("tmx_serve_query_fused_total",
                            **hl).inc(window)
                reg.histogram("tmx_serve_fusion_window",
                              **hl).observe(window)
            elif kind == "serve_preempted":
                reg.counter("tmx_serve_preemptions_total", **hl).inc()
        elif kind == "anomaly":
            # latched warn-only detector events (canary.py): same
            # counter the live daemon ticks, keyed by the degraded
            # signal stream
            reg.counter(
                "tmx_anomalies_total",
                metric=str(ev.get("metric", "")) or "unknown", **hl,
            ).inc()
        elif kind in ("init_done", "description_drift",
                      "serve_started"):
            pass  # known structural events with no metric series
        elif kind:
            # forward compatibility: a newer writer's ledger may carry
            # event kinds this checkout has never heard of — surface it
            # once per kind and keep deriving, never raise (an old
            # checkout must stay able to read a new ledger)
            warn_once(
                logger, f"ledger-kind:{kind}",
                "ignoring unknown ledger event kind '%s' (written by a "
                "newer version?)", kind,
            )
    for (step, host), acc in sorted(step_units.items()):
        if acc["seconds"] > 0:
            hl = {"host": host} if host else {}
            reg.gauge("tmx_step_units_per_sec", step=step, **hl).set(
                acc["units"] / acc["seconds"]
            )
    return reg


# ---------------------------------------------------------------------------
# span tree + critical path (tmx trace)


def build_span_tree(events: Iterable[dict]) -> dict:
    """Assemble the run → step → batch → phase tree from ledger events.

    Structure comes from event fields (``step``/``batch``/``span``), not
    from span nesting paths, so phase spans recorded on executor worker
    threads land under the right batch.  Ledgers without ``span`` events
    (seed-era) still produce a tree from ``batch_done``/``step_done``
    timing.
    """
    root: dict[str, Any] = {"name": "run", "elapsed": 0.0, "children": []}
    steps: dict[str, dict] = {}
    batches: dict[tuple[str, Any], dict] = {}

    def _step_node(step: str) -> dict:
        node = steps.get(step)
        if node is None:
            node = {"name": f"step:{step}", "elapsed": 0.0, "children": []}
            steps[step] = node
            root["children"].append(node)
        return node

    def _batch_node(step: str, batch: Any) -> dict:
        key = (step, batch)
        node = batches.get(key)
        if node is None:
            node = {"name": f"batch:{batch}", "elapsed": 0.0, "children": []}
            batches[key] = node
            _step_node(step)["children"].append(node)
        return node

    for ev in events:
        kind = ev.get("event")
        step = str(ev.get("step", "")) or "unknown"
        if kind == "span":
            name = str(ev.get("span", ""))
            elapsed = float(ev.get("elapsed", 0.0) or 0.0)
            if name == "run":
                root["elapsed"] = elapsed
            elif name == "step":
                _step_node(step)["elapsed"] = elapsed
            elif name == "batch":
                node = _batch_node(step, ev.get("batch"))
                node["elapsed"] = elapsed
            else:  # phase span (prefetch_wait/dispatch/device_block/persist)
                # batch-less phase spans (e.g. a compile attributed to
                # the step, not to one batch) stay OUT of the tree:
                # fabricating a "batch:None" node would miscount
                # batches, and nesting under the step would outweigh
                # every real batch on the critical path.  Compile cost
                # keeps its own surfaces (perf profiles, `tmx trace`
                # raw spans, the WARM row).
                if ev.get("batch") is None:
                    continue
                parent = _batch_node(step, ev["batch"])
                parent["children"].append(
                    {"name": f"phase:{name}", "elapsed": elapsed,
                     "children": []}
                )
        elif kind == "batch_done":
            node = _batch_node(step, ev.get("batch"))
            if not node["elapsed"]:
                node["elapsed"] = float(ev.get("elapsed", 0.0) or 0.0)
        elif kind in ("step_done", "step_partial"):
            node = _step_node(step)
            if not node["elapsed"]:
                node["elapsed"] = float(ev.get("elapsed", 0.0) or 0.0)
    if not root["elapsed"]:
        root["elapsed"] = round(
            sum(c["elapsed"] for c in root["children"]), 6
        )
    return root


def annotate_critical_path(node: dict) -> dict:
    """Mark the longest child at every level with ``critical: True``.

    The chain of critical nodes is the dominant cost path — for a
    pipelined step it identifies the phase the window spends its time in
    (matching the largest ``total_s`` in ``pipeline_stats``).
    """
    node.setdefault("critical", True)
    children = node.get("children") or []
    if children:
        longest = max(children, key=lambda c: c.get("elapsed", 0.0))
        for child in children:
            child["critical"] = child is longest
            if child is longest:
                annotate_critical_path(child)
            else:
                _clear_critical(child)
    return node


def _clear_critical(node: dict) -> None:
    node["critical"] = False
    for child in node.get("children") or []:
        _clear_critical(child)


def render_span_tree(node: dict, indent: int = 0) -> str:
    marker = "*" if node.get("critical") else " "
    lines = [
        f"{marker} {'  ' * indent}{node['name']:<24} "
        f"{node.get('elapsed', 0.0):10.4f}s"
    ]
    for child in node.get("children") or []:
        lines.append(render_span_tree(child, indent + 1))
    return "\n".join(lines)


def phase_totals(events: Iterable[dict]) -> dict[str, float]:
    """Sum phase-span durations per phase name (critical-path accounting
    cross-checkable against ``pipeline_stats`` totals)."""
    totals: dict[str, float] = {}
    for ev in events:
        if ev.get("event") != "span":
            continue
        name = str(ev.get("span", ""))
        if name in ("run", "step", "batch"):
            continue
        totals[name] = totals.get(name, 0.0) + float(ev.get("elapsed", 0.0))
    return totals
