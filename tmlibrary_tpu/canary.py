"""Synthetic canary probes + fleet anomaly detection (DESIGN.md §27).

Two halves of the observability plane's *proactive* layer:

Canary probes
    The serve daemon periodically enqueues a tiny self-addressed
    ``kind="canary"`` job per host.  The probe rides the normal
    spool → claim → done lifecycle — so its end-to-end latency measures
    the whole serving pipeline, not a hand-picked code path — but it is
    **invisible to tenants**: it never enters the admission queue (no
    quota, no WDRR deficit, no retry budget, no breaker), it never feeds
    the per-tenant SLO series, and its result is discarded (the spool
    file is deleted, not archived).  Its latency/success stream into the
    time-series and into :func:`slo.canary_report`'s per-host
    availability — the fleet's black-box health signal.

Anomaly detection
    An EWMA/z-score detector over ledger-derived signal streams —
    canary latency, job latency (throughput inverse), queue wait,
    scheduling-delay straggler skew, reclaim cadence, SLO burn.  The
    pinned contract: :func:`anomaly_report` is a **pure function of the
    ordered event window** — no wall clock, no randomness, no process
    state — so replaying a ledger reproduces the live daemon's anomaly
    sequence bit-identically (the same replay discipline as
    ``registry_from_ledger``).  Detection is latched inside the pure
    function itself: one anomaly per excursion, re-armed only when the
    stream returns under the threshold.  Like QC and SLO burn, anomalies
    are warn-only: a latched ``anomaly`` ledger event and a
    ``tmx_anomalies_total{metric,host}`` tick, never an abort.
"""

from __future__ import annotations

import math
import time
import zlib
from typing import Iterable

from tmlibrary_tpu import faults
from tmlibrary_tpu.errors import TransientDeviceError
from tmlibrary_tpu.workflow.admission import JobSpec

#: the reserved job kind and pseudo-tenant canary probes run under; the
#: pseudo-tenant never reaches the admission queue or the SLO report —
#: it exists so ledger events are self-describing
CANARY_KIND = "canary"
CANARY_TENANT = "_canary"

#: a foreign host's probe older than this is debris from a dead daemon;
#: any live host may sweep it to ``rejected/`` (canaries are
#: self-addressed, so nobody else will ever execute it)
CANARY_STALE_S = 120.0

# ---- pinned detector constants (DESIGN.md §27) — part of the replay
# contract: live detection and ledger replay must run the same math
#: EWMA smoothing factor for mean and variance
ANOMALY_ALPHA = 0.3
#: samples a stream must accumulate before it can flag (warmup)
ANOMALY_MIN_SAMPLES = 5
#: |z| at or above this flags an anomaly
ANOMALY_THRESHOLD = 4.0
#: z denominator floor, relative to |EWMA|: keeps near-constant streams
#: (sub-ms canary latencies) from flagging on harmless jitter
ANOMALY_REL_FLOOR = 0.5
#: absolute z denominator floor, in the signal's own units (seconds for
#: the latency streams) — the scale below which excursions are noise
ANOMALY_ABS_FLOOR = 0.05
#: burn values are clamped here so an "inf" burn cannot poison the EWMA
ANOMALY_VALUE_CLAMP = 1e6


# ------------------------------------------------------------------ probe
def make_probe_spec(serve_root, host: str, seq: int,
                    now: float | None = None) -> JobSpec:
    """One self-addressed canary job spec.

    The job id embeds the submission time so a restarted daemon's first
    probe can never collide with a predecessor's; ``payload.seq`` is the
    per-daemon probe counter (the fault-injection context — chaos plans
    target "the Nth probe" through it)."""
    now = time.time() if now is None else float(now)
    return JobSpec(
        job_id=f"canary-{host}-{int(now * 1000):013x}",
        root=str(serve_root),
        tenant=CANARY_TENANT,
        kind=CANARY_KIND,
        submitted_at=now,
        payload={"host": host, "seq": int(seq)},
    )


def run_probe(payload: dict | None = None) -> dict:
    """Execute one canary probe: a tiny deterministic CPU checksum — the
    probe measures the *serving pipeline* (spool, claim, dispatch), not
    device throughput, so the work itself is microseconds.

    The ``canary_probe`` fault site fires here with the probe sequence
    as its batch context.  A ``hang`` fault sleeps then raises
    :class:`TransientDeviceError`; the probe absorbs it as a *degraded*
    success — a transient device blip is exactly what a canary exists to
    measure, and the inflated end-to-end latency is the signal.  Any
    other exception propagates and the probe fails."""
    payload = payload or {}
    degraded = False
    try:
        faults.maybe_fire("canary_probe", batch=payload.get("seq"))
    except TransientDeviceError:
        degraded = True
    seed = f"{payload.get('host', '')}/{payload.get('seq', 0)}"
    checksum = zlib.crc32(seed.encode())
    return {"ok": True, "degraded": degraded, "checksum": checksum}


# -------------------------------------------------------------- detector
def signal_samples(events: Iterable[dict]) -> list[tuple]:
    """Extract the detector's signal streams from ledger events, in
    event order: ``(metric, host, ts, value)`` tuples.

    Streams (the metric names the anomaly events carry):

    * ``canary_latency`` — canary ``job_done.elapsed_s``
    * ``job_seconds`` — non-canary ``job_done.elapsed_s`` (throughput
      inverse)
    * ``queue_wait`` — ``job_admitted.queue_wait_s``
    * ``straggler_skew`` — ``job_started.sched_delay_s`` (admit→start
      delay, the serving tier's straggler signal)
    * ``reclaim_gap`` — seconds between consecutive ``job_reclaimed``
      events per host (a shrinking gap is a reclaim storm)
    * ``slo_burn`` — ``slo_burn.burn`` values, clamped

    Pure: no wall clock, no state beyond the events themselves."""
    out: list[tuple] = []
    last_reclaim: dict[str, float] = {}
    for ev in events:
        kind = ev.get("event")
        ts = ev.get("ts")
        if ts is None:
            continue
        ts = float(ts)
        host = str(ev.get("host", "")) or "host0"
        if kind == "job_done" and ev.get("elapsed_s") is not None:
            metric = ("canary_latency" if ev.get("kind") == CANARY_KIND
                      else "job_seconds")
            out.append((metric, host, ts, float(ev["elapsed_s"])))
        elif (kind == "job_admitted"
              and ev.get("queue_wait_s") is not None
              and ev.get("kind") != CANARY_KIND):
            out.append(("queue_wait", host, ts,
                        float(ev["queue_wait_s"])))
        elif (kind == "job_started"
              and ev.get("sched_delay_s") is not None
              and ev.get("kind") != CANARY_KIND):
            out.append(("straggler_skew", host, ts,
                        float(ev["sched_delay_s"])))
        elif kind == "job_reclaimed":
            prev = last_reclaim.get(host)
            last_reclaim[host] = ts
            if prev is not None:
                out.append(("reclaim_gap", host, ts, max(0.0, ts - prev)))
        elif kind == "slo_burn":
            try:
                burn = float(ev.get("burn"))
            except (TypeError, ValueError):
                continue
            out.append(("slo_burn", host, ts,
                        min(burn, ANOMALY_VALUE_CLAMP)))
    return out


class _StreamState:
    __slots__ = ("mean", "var", "n", "armed", "anomalies")

    def __init__(self):
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.armed = True
        self.anomalies = 0


def anomaly_report(events: Iterable[dict],
                   alpha: float = ANOMALY_ALPHA,
                   min_samples: int = ANOMALY_MIN_SAMPLES,
                   threshold: float = ANOMALY_THRESHOLD) -> list[dict]:
    """The full anomaly sequence for an event window.

    A pure, prefix-stable function: the report over a ledger prefix is
    exactly the first k entries of the report over the full ledger, so a
    live daemon emitting anomalies incrementally and a post-hoc replay
    of the final ledger agree bit-identically (the acceptance contract).
    ``anomaly`` events in the input are ignored — the detector never
    feeds on its own output.

    Each record: ``{"metric", "host", "seq", "ts", "value", "ewma",
    "zscore"}`` with ``seq`` the anomaly's index within its
    (metric, host) stream.  Values are rounded here, once, so the ledger
    events the daemon writes carry exactly these numbers."""
    streams: dict[tuple, _StreamState] = {}
    out: list[dict] = []
    samples = signal_samples(
        ev for ev in events if ev.get("event") != "anomaly")
    for metric, host, ts, value in samples:
        st = streams.setdefault((metric, host), _StreamState())
        if st.n >= min_samples:
            std = math.sqrt(max(st.var, 0.0))
            floor = max(std, ANOMALY_REL_FLOOR * abs(st.mean),
                        ANOMALY_ABS_FLOOR)
            z = (value - st.mean) / floor
            if abs(z) >= threshold:
                if st.armed:
                    st.armed = False
                    out.append({
                        "metric": metric, "host": host,
                        "seq": st.anomalies, "ts": round(ts, 6),
                        "value": round(value, 6),
                        "ewma": round(st.mean, 6),
                        "zscore": round(z, 3),
                    })
                    st.anomalies += 1
                # anomalous samples never update the EWMA — a spike must
                # not drag the baseline toward itself, or a sustained
                # degradation would self-normalize and unlatch
                continue
            st.armed = True
        d = value - st.mean
        if st.n == 0:
            st.mean = value
        else:
            st.mean += alpha * d
            st.var = (1.0 - alpha) * (st.var + alpha * d * d)
        st.n += 1
    return out
