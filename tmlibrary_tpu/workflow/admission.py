"""Admission control for the ``tmx serve`` daemon.

The serving loop (``tmlibrary_tpu/serve.py``) is only viable as a
long-lived process if overload degrades *gracefully*: a flooded queue
must shed deterministically, one tenant's burst must not starve the
others, a retry storm must turn into early rejection, and a failing
tenant must trip to tenant-scoped rejection instead of taking the
daemon down.  All of those policies live here, in front of the
workflow engine, so the engine itself never sees load it cannot carry.

Mechanisms
----------
Bounded queue with watermark hysteresis
    At ``max_queue`` total queued jobs the queue enters *shedding*:
    every new offer is rejected with ``queue_full`` until the depth
    drains below ``low_watermark``.  Hysteresis prevents admit/shed
    flapping right at the boundary.
Per-tenant quotas
    A tenant may hold at most ``tenant_quota`` queued jobs; excess
    offers are rejected with ``tenant_quota`` while other tenants keep
    admitting.
Weighted deficit-round-robin dispatch
    :meth:`AdmissionQueue.take` serves tenants in sorted-name rotation,
    accumulating ``quantum * weight`` deficit per visit and spending
    one unit per job — a classic DRR scheduler, fully deterministic
    (no randomness, no wall-clock dependence).
Per-tenant retry budgets
    Resubmissions (``attempt > 0``) spend one token from the tenant's
    budget; an exhausted budget rejects with ``retry_budget``.  Each
    successful job refunds one token (capped at the budget), so a
    healthy tenant's budget self-heals.
Per-tenant circuit breakers
    Job failures feed a :class:`~tmlibrary_tpu.resilience.CircuitBreaker`
    per tenant; an open breaker rejects that tenant's offers with
    ``tenant_breaker_open`` while everyone else is unaffected.

Every rejection carries a **pinned** ``retry_after_s`` from
:data:`RETRY_AFTER_S` — the contract clients (and the chaos tests)
rely on.  Rejection is always a *decision*, never an exception: the
admission layer cannot crash the daemon.
"""

from __future__ import annotations

import dataclasses
import time
from bisect import insort

from tmlibrary_tpu.resilience import CircuitBreaker

# --------------------------------------------------------------- contract
#: pinned rejection reasons (ledger ``job_rejected.reason`` values)
REASON_QUEUE_FULL = "queue_full"
REASON_TENANT_QUOTA = "tenant_quota"
REASON_RETRY_BUDGET = "retry_budget"
REASON_BREAKER_OPEN = "tenant_breaker_open"
REASON_DEADLINE = "deadline_expired"
REASON_DUPLICATE = "duplicate"
REASON_INVALID = "invalid_spec"
REASON_FAULT = "admission_fault"

#: pinned retry-after seconds per rejection reason — part of the serve
#: API contract (DESIGN.md §20 overload policy table); clients sleep
#: this long before resubmitting.  0 means "do not retry as-is".
RETRY_AFTER_S: dict[str, float] = {
    REASON_QUEUE_FULL: 30.0,
    REASON_TENANT_QUOTA: 15.0,
    REASON_RETRY_BUDGET: 120.0,
    REASON_BREAKER_OPEN: 60.0,
    REASON_DEADLINE: 0.0,
    REASON_DUPLICATE: 0.0,
    REASON_INVALID: 0.0,
    REASON_FAULT: 10.0,
}

#: rejection reasons that count as load shedding (the overload signal,
#: as opposed to a per-job problem like an expired deadline)
SHED_REASONS = frozenset(
    {REASON_QUEUE_FULL, REASON_TENANT_QUOTA, REASON_RETRY_BUDGET,
     REASON_BREAKER_OPEN}
)


# ------------------------------------------------------------------- job
@dataclasses.dataclass
class JobSpec:
    """One spooled serve job: a workflow submission — or, with
    ``kind="query"``, one analytics query — for one experiment.

    ``deadline`` is an *absolute* unix timestamp (computed by ``tmx
    enqueue`` from its ``--deadline`` relative seconds) so the budget
    keeps counting down across re-spools and daemon restarts.
    ``attempt`` counts tenant resubmissions of the same job id — the
    daemon's own preemption re-spool preserves it, so a drain/restart
    cycle never charges the tenant's retry budget.
    """

    job_id: str
    root: str
    tenant: str = "default"
    description: str | None = None
    priority: int = 0
    deadline: float | None = None
    pipeline_depth: int | None = None
    attempt: int = 0
    submitted_at: float = 0.0
    #: end-to-end trace correlation id stamped by ``tmx enqueue``; every
    #: span/ledger event emitted on behalf of this job carries it, so one
    #: id links enqueue → admission → queue wait → execution phases.
    trace_id: str | None = None
    #: job kind: ``workflow`` (the default — run the experiment's
    #: workflow), ``query`` (answer one analytics query; see
    #: ``analytics/query.py``), or ``canary`` (a self-addressed health
    #: probe — claimed directly by its issuing daemon, never admitted to
    #: the queue; see ``canary.py``).  Old spool files carry no ``kind``
    #: and deserialize as workflows.
    kind: str = "workflow"
    #: the query payload for ``kind="query"`` jobs (tool name +
    #: tool-specific arguments); ignored for workflow jobs
    payload: dict | None = None
    #: highest lease epoch this job has ever been claimed under (fleet
    #: spool protocol, DESIGN.md §25).  Each claim stamps ``epoch + 1``
    #: back into the spooled spec; the claiming host checks its epoch
    #: against the on-disk claim before every done/failed transition, so
    #: a stale host resuming after a GC pause cannot clobber a reclaimed
    #: job's result.  Old spool files deserialize at epoch 0.
    claim_epoch: int = 0
    #: compiled-program affinity key (``serve.affinity_key_for``): a
    #: content digest over the job's workflow description + jterator
    #: project, the routing hint a fleet host compares against its warm
    #: AOT/compile caches when choosing which spooled jobs to claim.
    affinity_key: str | None = None

    def sort_key(self) -> tuple:
        """Deterministic within-tenant order: priority desc, then
        submission time, then id (the final tiebreak makes replayed
        offer sequences reproduce byte-identical take() orders)."""
        return (-int(self.priority), float(self.submitted_at), self.job_id)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        if not d.get("job_id") or not d.get("root"):
            raise ValueError("job spec needs 'job_id' and 'root'")
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class AdmissionDecision:
    """Outcome of one :meth:`AdmissionQueue.offer`."""

    admitted: bool
    reason: str | None = None
    retry_after_s: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def reject(reason: str) -> AdmissionDecision:
    """The pinned rejection for ``reason`` (unknown reasons get the
    admission-fault retry-after rather than crashing)."""
    return AdmissionDecision(
        admitted=False, reason=reason,
        retry_after_s=RETRY_AFTER_S.get(reason, RETRY_AFTER_S[REASON_FAULT]),
    )


@dataclasses.dataclass
class AdmissionConfig:
    """Queue policy knobs (``cfg.serve_*`` defaults; CLI flags beat)."""

    max_queue: int = 64
    low_watermark: int = 0  # 0 = max_queue // 2
    tenant_quota: int = 16
    retry_budget: int = 8
    quantum: float = 1.0
    tenant_weights: dict[str, float] = dataclasses.field(default_factory=dict)
    breaker_threshold: int = 3
    breaker_cooldown: float = 60.0

    @classmethod
    def from_library_config(cls) -> "AdmissionConfig":
        from tmlibrary_tpu.config import cfg

        return cls(
            max_queue=int(cfg.serve_max_queue),
            low_watermark=int(cfg.serve_low_watermark),
            tenant_quota=int(cfg.serve_tenant_quota),
            retry_budget=int(cfg.serve_retry_budget),
        )

    @property
    def effective_low_watermark(self) -> int:
        low = int(self.low_watermark)
        if low <= 0:
            low = max(1, int(self.max_queue) // 2)
        return min(low, int(self.max_queue))


@dataclasses.dataclass
class _TenantState:
    name: str
    weight: float = 1.0
    queue: list = dataclasses.field(default_factory=list)  # (sort_key, job)
    deficit: float = 0.0
    retry_tokens: int = 0
    admitted: int = 0
    rejected: int = 0
    done: int = 0
    failed: int = 0
    rejected_by_reason: dict = dataclasses.field(default_factory=dict)
    breaker: CircuitBreaker | None = None


# ----------------------------------------------------------------- queue
class AdmissionQueue:
    """Bounded multi-tenant priority queue with deterministic shedding.

    Single-threaded by design: the serve daemon's admission loop is the
    only caller (thread discipline mirrors the ledger's engine-thread
    rule), so no lock is needed and every decision is a pure function
    of the offer/take/record_result history — which is what makes the
    shed-determinism chaos tests possible.
    """

    def __init__(self, config: AdmissionConfig | None = None,
                 clock=time.time):
        self.config = config or AdmissionConfig()
        self._clock = clock
        self._tenants: dict[str, _TenantState] = {}
        self._queued_ids: set[str] = set()
        self._shedding = False
        self._last_served: str | None = None

    # ------------------------------------------------------------ state
    def _tenant(self, name: str) -> _TenantState:
        st = self._tenants.get(name)
        if st is None:
            st = _TenantState(
                name=name,
                weight=float(self.config.tenant_weights.get(name, 1.0)),
                retry_tokens=int(self.config.retry_budget),
                breaker=CircuitBreaker(
                    failure_threshold=self.config.breaker_threshold,
                    cooldown=self.config.breaker_cooldown,
                ),
            )
            self._tenants[name] = st
        return st

    def depth(self) -> int:
        return sum(len(st.queue) for st in self._tenants.values())

    def shedding(self) -> bool:
        return self._shedding

    def oldest_age(self, now: float | None = None) -> float | None:
        """Age in seconds of the oldest queued job, None when empty."""
        now = self._clock() if now is None else now
        oldest = min(
            (job.submitted_at for st in self._tenants.values()
             for _, job in st.queue),
            default=None,
        )
        return None if oldest is None else max(0.0, now - oldest)

    # ------------------------------------------------------------ offer
    def offer(self, job: JobSpec,
              now: float | None = None) -> AdmissionDecision:
        """Admit or reject ``job``.  Check order is pinned (and
        documented in DESIGN.md §20): duplicate → deadline → breaker →
        retry budget → tenant quota → watermark.  Never raises."""
        now = self._clock() if now is None else now
        st = self._tenant(job.tenant)
        depth = self.depth()
        # watermark hysteresis bookkeeping happens on every offer, even
        # ones rejected for per-job reasons, so shedding state tracks
        # the actual depth trajectory
        if self._shedding and depth <= self.config.effective_low_watermark:
            self._shedding = False

        decision: AdmissionDecision | None = None
        if job.job_id in self._queued_ids:
            decision = reject(REASON_DUPLICATE)
        elif job.deadline is not None and now >= float(job.deadline):
            decision = reject(REASON_DEADLINE)
        elif st.breaker is not None and not st.breaker.allow():
            decision = reject(REASON_BREAKER_OPEN)
        elif job.attempt > 0 and st.retry_tokens <= 0:
            decision = reject(REASON_RETRY_BUDGET)
        elif len(st.queue) >= int(self.config.tenant_quota):
            decision = reject(REASON_TENANT_QUOTA)
        elif self._shedding or depth >= int(self.config.max_queue):
            self._shedding = True
            decision = reject(REASON_QUEUE_FULL)

        if decision is not None:
            st.rejected += 1
            st.rejected_by_reason[decision.reason] = (
                st.rejected_by_reason.get(decision.reason, 0) + 1
            )
            return decision

        if job.attempt > 0:
            st.retry_tokens -= 1
        insort(st.queue, (job.sort_key(), job))
        self._queued_ids.add(job.job_id)
        st.admitted += 1
        return AdmissionDecision(admitted=True)

    # ------------------------------------------------------------- take
    def take(self, now: float | None = None) -> JobSpec | None:
        """Next job under weighted deficit-round-robin, or None."""
        if self.depth() == 0:
            # classic DRR: deficit does not accumulate while idle
            for st in self._tenants.values():
                st.deficit = 0.0
            return None
        # a tenant whose visit left residual deficit keeps the floor
        # until it is spent — this is what makes weights > 1 grant
        # proportionally more service (weight 2.0 => two jobs per
        # rotation) instead of degenerating to plain round-robin
        if self._last_served is not None:
            held = self._tenants.get(self._last_served)
            if held is not None and held.queue and held.deficit >= 1.0:
                held.deficit -= 1.0
                _, job = held.queue.pop(0)
                self._queued_ids.discard(job.job_id)
                return job
        tenants = sorted(t for t, st in self._tenants.items() if st.queue)
        start = 0
        if self._last_served is not None:
            for i, t in enumerate(tenants):
                if t > self._last_served:
                    start = i
                    break
        order = tenants[start:] + tenants[:start]
        quantum = float(self.config.quantum)
        min_weight = min(self._tenants[t].weight for t in order)
        rounds = 2 + int(1.0 / max(min_weight * quantum, 1e-6))
        for _ in range(rounds):
            for name in order:
                st = self._tenants[name]
                if not st.queue:
                    st.deficit = 0.0
                    continue
                st.deficit += quantum * st.weight
                if st.deficit >= 1.0:
                    st.deficit -= 1.0
                    _, job = st.queue.pop(0)
                    self._queued_ids.discard(job.job_id)
                    self._last_served = name
                    return job
        return None  # unreachable with positive weights; defensive

    def take_matching(self, pred, limit: int) -> list[JobSpec]:
        """Remove and return up to ``limit`` queued jobs satisfying
        ``pred(job)``, in deterministic (tenant-name, priority) order —
        the multi-query fusion path pulls same-store query jobs to ride
        one batched device sweep.  DRR deficits are untouched: fused
        followers ride the leader's turn (their work is free at the
        device), and every follower still records its own result, so
        per-tenant accounting stays intact."""
        out: list[JobSpec] = []
        if limit <= 0:
            return out
        for name in sorted(self._tenants):
            st = self._tenants[name]
            keep = []
            for item in st.queue:
                job = item[1]
                if len(out) < int(limit) and pred(job):
                    out.append(job)
                    self._queued_ids.discard(job.job_id)
                else:
                    keep.append(item)
            st.queue[:] = keep
            if len(out) >= int(limit):
                break
        return out

    def drain(self) -> list[JobSpec]:
        """Remove and return every queued job in deterministic
        (tenant-name, priority) order — the SIGTERM re-spool path."""
        out: list[JobSpec] = []
        for name in sorted(self._tenants):
            st = self._tenants[name]
            out.extend(job for _, job in st.queue)
            st.queue.clear()
            st.deficit = 0.0
        self._queued_ids.clear()
        return out

    # ---------------------------------------------------------- results
    def record_result(self, tenant: str, ok: bool) -> None:
        """Feed a job outcome back into the tenant's breaker and retry
        budget (success refunds one retry token)."""
        st = self._tenant(tenant)
        if ok:
            st.done += 1
            if st.breaker is not None:
                st.breaker.record_success()
            st.retry_tokens = min(
                int(self.config.retry_budget), st.retry_tokens + 1
            )
        else:
            st.failed += 1
            if st.breaker is not None:
                st.breaker.record_failure()

    # --------------------------------------------------------- snapshot
    def snapshot(self, now: float | None = None) -> dict:
        """Status view: depth, shedding flag, per-tenant counters."""
        now = self._clock() if now is None else now
        age = self.oldest_age(now)
        return {
            "depth": self.depth(),
            "shedding": self._shedding,
            "high_watermark": int(self.config.max_queue),
            "low_watermark": self.config.effective_low_watermark,
            "oldest_job_age_s": None if age is None else round(age, 3),
            "tenants": {
                name: {
                    "queued": len(st.queue),
                    "admitted": st.admitted,
                    "rejected": st.rejected,
                    "rejected_by_reason": dict(st.rejected_by_reason),
                    "done": st.done,
                    "failed": st.failed,
                    "retry_budget_remaining": st.retry_tokens,
                    "weight": st.weight,
                    "breaker": (st.breaker.state if st.breaker else "closed"),
                }
                for name, st in sorted(self._tenants.items())
            },
        }
