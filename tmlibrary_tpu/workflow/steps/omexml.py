"""Minimal OME-XML read/write for experiment metadata.

Reference parity: ``tmlib/workflow/metaconfig/omexml.py`` — the reference
normalises all vendor metadata into OME-XML (via python-bioformats'
``OMEXML`` class) before deriving the experiment layout, and can consume
companion ``*.ome.xml`` files written by the microscope.

TPU rebuild: a dependency-free subset of the OME schema
(``Image``/``Pixels``/``Channel``/``Plane`` with ``SizeX/Y/Z/C/T``,
``DimensionOrder`` and stage positions) implemented on
``xml.etree.ElementTree``.  This is host-side ingest code — no device math.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from pathlib import Path

OME_NS = "http://www.openmicroscopy.org/Schemas/OME/2016-06"


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


@dataclass
class OmePlane:
    """One 2-D pixel plane within an image series."""

    the_z: int = 0
    the_t: int = 0
    the_c: int = 0
    position_x: float | None = None
    position_y: float | None = None


@dataclass
class OmeImage:
    """One image series (in HCS data: one site of one well)."""

    name: str
    size_x: int
    size_y: int
    size_z: int = 1
    size_c: int = 1
    size_t: int = 1
    dimension_order: str = "XYZCT"
    pixel_type: str = "uint16"
    channel_names: list[str] = field(default_factory=list)
    planes: list[OmePlane] = field(default_factory=list)


def parse_ome_xml(text: str) -> list[OmeImage]:
    """Parse an OME-XML document into a list of :class:`OmeImage`.

    Namespace-agnostic: accepts any OME schema revision (tags are matched
    by local name), which is what the reference's handler zoo needs since
    vendors pin different schema years.
    """
    from tmlibrary_tpu.errors import MetadataError

    try:
        root = ET.fromstring(text)
    except ET.ParseError as exc:
        raise MetadataError(f"cannot parse OME-XML document: {exc}")
    images: list[OmeImage] = []
    for el in root.iter():
        if _strip_ns(el.tag) != "Image":
            continue
        pixels = None
        for child in el:
            if _strip_ns(child.tag) == "Pixels":
                pixels = child
                break
        if pixels is None:
            continue
        img = OmeImage(
            name=el.get("Name", el.get("ID", "")),
            size_x=int(pixels.get("SizeX", 0)),
            size_y=int(pixels.get("SizeY", 0)),
            size_z=int(pixels.get("SizeZ", 1)),
            size_c=int(pixels.get("SizeC", 1)),
            size_t=int(pixels.get("SizeT", 1)),
            dimension_order=pixels.get("DimensionOrder", "XYZCT"),
            pixel_type=pixels.get("Type", pixels.get("PixelType", "uint16")),
        )
        for sub in pixels:
            tag = _strip_ns(sub.tag)
            if tag == "Channel":
                img.channel_names.append(
                    sub.get("Name") or f"channel_{len(img.channel_names)}"
                )
            elif tag == "Plane":
                px = sub.get("PositionX")
                py = sub.get("PositionY")
                img.planes.append(
                    OmePlane(
                        the_z=int(sub.get("TheZ", 0)),
                        the_t=int(sub.get("TheT", 0)),
                        the_c=int(sub.get("TheC", 0)),
                        position_x=float(px) if px is not None else None,
                        position_y=float(py) if py is not None else None,
                    )
                )
        images.append(img)
    return images


def read_ome_companion(path: Path) -> list[OmeImage]:
    return parse_ome_xml(Path(path).read_text(errors="replace"))


def write_ome_xml(manifest) -> str:
    """Serialise an experiment manifest to an OME-XML document.

    Reference parity artifact: metaconfig's collect phase leaves the merged
    OME metadata on disk; here one ``Image`` element is emitted per site
    with the experiment's channel set and z/t extents.
    """
    ET.register_namespace("", OME_NS)
    root = ET.Element(f"{{{OME_NS}}}OME")
    idx = 0
    for plate in manifest.plates:
        plate_el = ET.SubElement(root, f"{{{OME_NS}}}Plate")
        plate_el.set("ID", f"Plate:{plate.name}")
        plate_el.set("Name", plate.name)
        plate_el.set("Rows", str(max((w.row for w in plate.wells), default=0) + 1))
        plate_el.set(
            "Columns", str(max((w.column for w in plate.wells), default=0) + 1)
        )
        for well in plate.wells:
            well_el = ET.SubElement(plate_el, f"{{{OME_NS}}}Well")
            well_el.set("Row", str(well.row))
            well_el.set("Column", str(well.column))
            for site in well.sites:
                ws = ET.SubElement(well_el, f"{{{OME_NS}}}WellSample")
                ws.set("ID", f"WellSample:{idx}")
                ws.set("ImageRef", f"Image:{idx}")

                img = ET.SubElement(root, f"{{{OME_NS}}}Image")
                img.set("ID", f"Image:{idx}")
                img.set(
                    "Name",
                    f"{plate.name}_r{well.row:02d}c{well.column:02d}"
                    f"_y{site.y}x{site.x}",
                )
                px = ET.SubElement(img, f"{{{OME_NS}}}Pixels")
                px.set("ID", f"Pixels:{idx}")
                px.set("DimensionOrder", "XYZCT")
                px.set("Type", "uint16")
                px.set("SizeX", str(manifest.site_width))
                px.set("SizeY", str(manifest.site_height))
                px.set("SizeZ", str(manifest.n_zplanes))
                px.set("SizeC", str(manifest.n_channels))
                px.set("SizeT", str(manifest.n_tpoints))
                for c in manifest.channels:
                    ch = ET.SubElement(px, f"{{{OME_NS}}}Channel")
                    ch.set("ID", f"Channel:{idx}:{c.index}")
                    ch.set("Name", c.name)
                idx += 1
    return ET.tostring(root, encoding="unicode")
