"""Vendor sidecar-metadata handlers for metaconfig.

Reference parity: ``tmlib/workflow/metaconfig/`` ships one handler module
per microscope vendor (``cellvoyager.py`` for the Yokogawa CellVoyager is
the confirmed member — SURVEY.md §2 metaconfig row); each handler reads the
vendor's sidecar metadata files and yields per-plane records that the
configurator merges into the canonical experiment layout.

TPU rebuild: handlers are host-side parsers that return canonical entry
dicts (same keys as ``FilenameHandler.parse`` plus optional stage
positions).  Two sidecar handlers cover the formats that need more than a
filename regex:

- ``cellvoyager``: Yokogawa ``MeasurementData.mlf`` (one XML record per
  acquired plane: well row/column, field, timepoint, z index, channel,
  stage X/Y) plus the optional ``MeasurementSetting.mes`` channel table.
- ``omexml``: companion ``*.ome.xml`` / ``*.companion.ome`` documents
  (parsed by :mod:`tmlibrary_tpu.workflow.steps.omexml`).

Stage positions, when present, are converted to within-well site grid
coordinates by :func:`positions_to_grid` — the reference derives grid
coords from stage positions the same way (metaconfig ``base.py``).
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Callable

import logging

from tmlibrary_tpu.errors import (
    MetadataError,
    NotSupportedError,
    VendorConflictError,
)
from tmlibrary_tpu.workflow.steps.omexml import _strip_ns

logger = logging.getLogger(__name__)

#: registry: handler name -> callable(source_dir) ->
#:   (entries, n_skipped) when sidecar files were found (entries may be
#:   empty: sidecars present but nothing resolvable), or None when the
#:   vendor's sidecar files are absent entirely.
SIDECAR_HANDLERS: dict[
    str, Callable[[Path], "tuple[list[dict], int] | None"]
] = {}


def register_sidecar_handler(name: str):
    def deco(fn):
        SIDECAR_HANDLERS[name] = fn
        return fn

    return deco


def _index_files(source_dir: Path, stems: bool = False) -> dict[str, Path]:
    """filename (and optionally extension-less stem) -> path, first wins."""
    by_name: dict[str, Path] = {}
    for p in source_dir.rglob("*"):
        if p.is_file():
            by_name.setdefault(p.name, p)
            if stems and p.suffix.lower() in (".tif", ".tiff", ".png", ".stk"):
                by_name.setdefault(p.stem, p)
    return by_name


def _attr(el: ET.Element, *names: str) -> str | None:
    """Look an attribute up by local name, ignoring XML namespaces."""
    for key, value in el.attrib.items():
        if _strip_ns(key) in names:
            return value
    return None


def positions_to_grid(positions: list[float], tol: float | None = None) -> dict:
    """Map stage coordinates to dense grid indices.

    Positions within ``tol`` of each other collapse onto one grid line
    (stage repeatability jitter).  The default ``tol`` is derived from the
    gap distribution: real grids produce bimodal gaps (tiny jitter vs the
    site pitch), detected as the largest ratio jump in the sorted gaps.
    Without clear bimodality (exact grid with no jitter, or a single grid
    line where every gap IS jitter) tol falls to 0 and each distinct value
    keeps its own line — callers must cross-check the resulting grid
    (e.g. against the field-index count) before trusting it.
    """
    if not positions:
        return {}
    distinct = sorted(set(positions))
    if tol is None:
        gaps = sorted(
            b - a for a, b in zip(distinct, distinct[1:])
        )
        tol = 0.0
        if gaps:
            best_ratio, split = 1.0, None
            for a, b in zip(gaps, gaps[1:]):
                ratio = b / a if a > 0 else float("inf")
                if ratio > best_ratio:
                    best_ratio, split = ratio, (a, b)
            if split is not None and best_ratio > 10.0:
                tol = (split[0] * split[1]) ** 0.5  # between the two modes
    lines: list[float] = []
    index_of: dict[float, int] = {}
    for p in distinct:
        if lines and p - lines[-1] <= tol:
            index_of[p] = len(lines) - 1
        else:
            lines.append(p)
            index_of[p] = len(lines) - 1
    return index_of


def derive_well_grids(
    entries: list[dict],
) -> dict[tuple[int, int], tuple[dict, dict]]:
    """Per-well (y_index, x_index) grids from stage positions.

    Positions are absolute stage coordinates, so the grid must be derived
    per well (reference metaconfig ``base.py`` does the same per-well grid
    derivation).  A well's grid is kept only when it cross-checks: the
    grid cells must form a dense rectangle addressing exactly the well's
    field set, else stage jitter was misread as grid lines
    (:func:`positions_to_grid` docstring) and callers fall back to field
    indices for that well.
    """
    from collections import defaultdict

    per_well: dict[tuple[int, int], list[dict]] = defaultdict(list)
    for e in entries:
        per_well[(e["well_row"], e["well_col"])].append(e)
    grids: dict[tuple[int, int], tuple[dict, dict]] = {}
    for key, group in per_well.items():
        pairs = [
            (e["stage_y"], e["stage_x"]) for e in group
            if e["stage_x"] is not None and e["stage_y"] is not None
        ]
        fields = {e["site"] for e in group}
        res = dense_grid(
            [p[0] for p in pairs], [p[1] for p in pairs], len(fields)
        )
        if res is not None:
            grids[key] = (res[1], res[2])
    return grids


def dense_grid(ys, xs, n) -> "tuple[list, dict, dict] | None":
    """(cells, y_index, x_index) when the coordinates form a dense
    rectangle addressing exactly ``n`` items, else None — the ONE home
    of the cross-check shared by stage-position well grids and CZI
    mosaic tile origins (a misclustered grid must fall back, never
    emit wrong geometry)."""
    y_index = positions_to_grid(ys)
    x_index = positions_to_grid(xs)
    cells = [(y_index[y], x_index[x]) for y, x in zip(ys, xs)]
    ny = len(set(y_index.values()))
    nx = len(set(x_index.values()))
    if len(set(cells)) != n or ny * nx != n:
        return None
    return cells, y_index, x_index


# --------------------------------------------------------------- cellvoyager
def parse_mes_channels(path: Path) -> dict[int, str]:
    """Parse ``MeasurementSetting.mes``: channel number -> descriptive name."""
    channels: dict[int, str] = {}
    try:
        root = ET.fromstring(path.read_text(errors="replace"))
    except ET.ParseError as exc:
        raise MetadataError(f"cannot parse CellVoyager .mes file {path}: {exc}")
    for el in root.iter():
        if _strip_ns(el.tag) != "Channel":
            continue
        num = _attr(el, "Ch", "Number", "ChannelNumber")
        if num is None:
            continue
        name = (
            _attr(el, "Target", "Fluorophore", "Dye", "Name", "Acquisition")
            or f"C{int(num):02d}"
        )
        channels[int(num)] = str(name)
    return channels


def parse_mlf(path: Path) -> list[dict]:
    """Parse ``MeasurementData.mlf`` into canonical plane entries.

    Each ``MeasurementRecord`` of type ``IMG`` carries well row/column,
    field (site), timeline/timepoint, z index, channel and stage X/Y; the
    element text is the image filename.
    """
    try:
        root = ET.fromstring(path.read_text(errors="replace"))
    except ET.ParseError as exc:
        raise MetadataError(f"cannot parse CellVoyager .mlf file {path}: {exc}")
    entries = []
    for el in root.iter():
        if _strip_ns(el.tag) != "MeasurementRecord":
            continue
        rtype = _attr(el, "Type")
        if rtype is not None and rtype.upper() not in ("IMG", "IMAGE"):
            continue  # ERR / timeline bookkeeping records
        row = _attr(el, "Row")
        col = _attr(el, "Column")
        field_i = _attr(el, "FieldIndex", "Field")
        if row is None or col is None or field_i is None:
            continue
        ch = _attr(el, "Ch", "Channel", "ActionIndex") or "1"
        tp = _attr(el, "TimePoint", "TimelineIndex", "T") or "1"
        zi = _attr(el, "ZIndex", "Z") or "1"
        x = _attr(el, "X")
        y = _attr(el, "Y")
        entries.append(
            {
                "well_row": int(row) - 1,
                "well_col": int(col) - 1,
                "site": int(field_i) - 1,
                "channel": str(int(ch)),
                "cycle": 0,
                "tpoint": int(tp) - 1,
                "zplane": int(zi) - 1,
                "filename": (el.text or "").strip(),
                "stage_x": float(x) if x is not None else None,
                "stage_y": float(y) if y is not None else None,
            }
        )
    return entries


@register_sidecar_handler("cellvoyager")
def cellvoyager_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """CellVoyager handler: requires a ``*.mlf`` file in the source tree."""
    mlfs = sorted(source_dir.rglob("*.mlf"))
    if not mlfs:
        return None
    entries: list[dict] = []
    for mlf in mlfs:
        entries.extend(parse_mlf(mlf))
    if not entries:
        return [], 0  # .mlf present but held no IMG records

    # channel names from the .mes settings file, if present; a corrupt .mes
    # must not abort ingest — the C<nn> fallback names cover its absence
    channel_names: dict[int, str] = {}
    for mes in sorted(source_dir.rglob("*.mes")):
        try:
            channel_names.update(parse_mes_channels(mes))
        except (MetadataError, ValueError) as exc:
            # ValueError: well-formed XML with a non-numeric channel number
            logger.warning("ignoring unparseable .mes file: %s", exc)

    # resolve filenames against the tree once (rglob per entry would be O(n^2))
    by_name = _index_files(source_dir)

    # stage positions -> within-well grid (shared per-well derivation)
    grids = derive_well_grids(entries)

    out = []
    skipped = 0
    for e in entries:
        path = by_name.get(e["filename"])
        if path is None:
            skipped += 1  # record for a file not exported alongside the sidecar
            continue
        rec = {
            "plate": "plate00",
            "well_row": e["well_row"],
            "well_col": e["well_col"],
            "site": e["site"],
            "channel": channel_names.get(int(e["channel"]), f"C{int(e['channel']):02d}"),
            "cycle": e["cycle"],
            "tpoint": e["tpoint"],
            "zplane": e["zplane"],
            "path": str(path),
        }
        grid = grids.get((e["well_row"], e["well_col"]))
        if grid is not None and e["stage_x"] is not None and e["stage_y"] is not None:
            y_index, x_index = grid
            rec["site_y"] = y_index[e["stage_y"]]
            rec["site_x"] = x_index[e["stage_x"]]
        out.append(rec)
    return out, skipped


# ------------------------------------------------------------------- omexml
def _plane_page(order: str, c: int, t: int, z: int, img) -> int:
    """Linear page index of plane (c, t, z) in a multi-page OME-TIFF.

    ``DimensionOrder`` lists all five dims; the first non-XY dim varies
    fastest across pages (OME spec).
    """
    sizes = {"C": img.size_c, "T": img.size_t, "Z": img.size_z}
    coords = {"C": c, "T": t, "Z": z}
    page, stride = 0, 1
    for dim in order.upper():
        if dim in ("X", "Y"):
            continue
        page += coords[dim] * stride
        stride *= sizes[dim]
    return page


@register_sidecar_handler("omexml")
def omexml_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Companion OME-XML handler: one Image element per (well, site).

    Multi-plane images (SizeC/T/Z > 1 backed by one file) get a ``page``
    index per entry so the extractor reads the right TIFF page instead of
    silently duplicating page 0 across planes.
    """
    import re

    from tmlibrary_tpu.workflow.steps.omexml import read_ome_companion

    companions = sorted(source_dir.rglob("*.ome.xml")) + sorted(
        source_dir.rglob("*.companion.ome")
    )
    if not companions:
        return None

    # TIFF series referenced by stem: Image Name "foo" -> file foo.tif
    by_name = _index_files(source_dir, stems=True)

    entries: list[dict] = []
    skipped = 0
    for comp in companions:
        for img in read_ome_companion(comp):
            path = by_name.get(img.name) or by_name.get(Path(img.name).name)
            if path is None:
                skipped += 1  # Image declared but no pixel file on disk
                continue
            m = re.search(r"r(\d+)c(\d+).*?y(\d+)x(\d+)", img.name) or re.search(
                r"([A-P])(\d{2})_s(\d+)", img.name
            )
            if m and len(m.groups()) == 4:
                row, col, sy, sx = (int(g) for g in m.groups())
                site = None
            elif m:
                row = ord(m.group(1)) - ord("A")
                col = int(m.group(2)) - 1
                site = int(m.group(3))
                sy = sx = None
            else:
                skipped += 1  # image name carries no recognisable layout
                continue
            multi_plane = img.size_c * img.size_t * img.size_z > 1
            for c in range(img.size_c):
                for t in range(img.size_t):
                    for z in range(img.size_z):
                        rec = {
                            "plate": "plate00",
                            "well_row": row,
                            "well_col": col,
                            # None marks "grid coords are the only site
                            # address" — _linearise_sites refuses to drop
                            # the grid for such entries
                            "site": site,
                            "channel": (
                                img.channel_names[c]
                                if c < len(img.channel_names)
                                else f"channel_{c}"
                            ),
                            "cycle": 0,
                            "tpoint": t,
                            "zplane": z,
                            "path": str(path),
                        }
                        if multi_plane:
                            rec["page"] = _plane_page(
                                img.dimension_order, c, t, z, img
                            )
                        if sy is not None:
                            rec["site_y"] = sy
                            rec["site_x"] = sx
                        entries.append(rec)
    return entries, skipped


# ------------------------------------------------------------------ harmony
def _child_text(el: ET.Element, *names: str) -> str | None:
    """First child element's text matched by local tag name."""
    for ch in el:
        if _strip_ns(ch.tag) in names and ch.text is not None:
            return ch.text.strip()
    return None


def parse_harmony_index(path: Path) -> list[dict]:
    """Parse a PerkinElmer Operetta/Opera Phenix ``Index.idx.xml``.

    Reference parity: the reference's metaconfig vendor-handler set
    (SURVEY.md §2 metaconfig row, exact vendor set tagged [L]) is a plugin
    registry per microscope; Harmony exports are the PerkinElmer member of
    that zoo.  The index document lists one ``<Image>`` record per plane
    with child elements ``URL`` (filename), ``Row``/``Col`` (1-based well),
    ``FieldID`` (site), ``ChannelID``/``ChannelName``, ``PlaneID`` (z),
    ``TimepointID`` and stage ``PositionX``/``PositionY``.
    """
    try:
        root = ET.fromstring(path.read_text(errors="replace"))
    except ET.ParseError as exc:
        raise MetadataError(f"cannot parse Harmony index file {path}: {exc}")
    entries: list[dict] = []
    for el in root.iter():
        if _strip_ns(el.tag) != "Image":
            continue
        url = _child_text(el, "URL")
        row = _child_text(el, "Row")
        col = _child_text(el, "Col")
        field = _child_text(el, "FieldID")
        if url is None or row is None or col is None or field is None:
            continue  # non-plane Image stanza (e.g. map entries)
        ch_id = _child_text(el, "ChannelID") or "1"
        ch_name = _child_text(el, "ChannelName")
        z = _child_text(el, "PlaneID") or "1"
        t = _child_text(el, "TimepointID") or "1"
        # TimepointID is 0-based in some Harmony exports, 1-based in others;
        # normalised by a min-subtraction over the whole index below.
        x = _child_text(el, "PositionX")
        y = _child_text(el, "PositionY")
        entries.append(
            {
                "well_row": int(row) - 1,
                "well_col": int(col) - 1,
                "site": int(field) - 1,
                "channel": ch_name or f"ch{int(ch_id)}",
                "cycle": 0,
                "tpoint": int(t),
                "zplane": int(z) - 1,
                "filename": url,
                "stage_x": float(x) if x is not None else None,
                "stage_y": float(y) if y is not None else None,
            }
        )
    if entries:
        t_min = min(e["tpoint"] for e in entries)
        for e in entries:
            e["tpoint"] -= t_min
    return entries


@register_sidecar_handler("harmony")
def harmony_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Operetta/Opera Phenix handler: requires an ``Index.idx.xml``
    under the source tree (``Index.ref.xml`` is a fallback when no idx
    file exists — a tree holding both describes the SAME planes twice,
    so only one flavour is ever read).

    FieldID order is not guaranteed row-major (Harmony supports meander /
    center-out field layouts), so within-well grid coordinates are derived
    from the stage positions via :func:`derive_well_grids` whenever they
    cross-check against the field set.
    """
    indexes = sorted(source_dir.rglob("Index.idx.xml")) or sorted(
        source_dir.rglob("Index.ref.xml")
    )
    if not indexes:
        return None
    entries: list[dict] = []
    for idx in indexes:
        entries.extend(parse_harmony_index(idx))
    if not entries:
        return [], 0

    by_name = _index_files(source_dir)
    grids = derive_well_grids(entries)
    out: list[dict] = []
    skipped = 0
    for e in entries:
        path = by_name.get(e["filename"]) or by_name.get(Path(e["filename"]).name)
        if path is None:
            skipped += 1
            continue
        rec = {
            "plate": "plate00",
            "well_row": e["well_row"],
            "well_col": e["well_col"],
            "site": e["site"],
            "channel": e["channel"],
            "cycle": e["cycle"],
            "tpoint": e["tpoint"],
            "zplane": e["zplane"],
            "path": str(path),
        }
        grid = grids.get((e["well_row"], e["well_col"]))
        if grid is not None and e["stage_x"] is not None and e["stage_y"] is not None:
            y_index, x_index = grid
            rec["site_y"] = y_index[e["stage_y"]]
            rec["site_x"] = x_index[e["stage_x"]]
        out.append(rec)
    return out, skipped


# -------------------------------------------------------------- imagexpress
def parse_htd(path: Path) -> dict:
    """Parse a Molecular Devices ImageXpress/MetaXpress ``.HTD`` file.

    Line-oriented ``"Key", v1, v2, ...`` records describing the plate scan:
    well grid (``XWells``/``YWells`` + per-row ``WellsSelection<r>``
    booleans), within-well site grid (``XSites``/``YSites`` +
    ``SiteSelection<r>``), wavelengths (``NWavelengths`` +
    ``WaveName<i>``) and timepoints.
    """
    fields: dict[str, list[str]] = {}
    for raw in path.read_text(errors="replace").splitlines():
        line = raw.strip()
        if not line:
            continue
        parts = [p.strip().strip('"') for p in line.split(",")]
        if parts:
            fields[parts[0]] = parts[1:]

    def num(name: str, default: int = 1) -> int:
        try:
            return int(fields.get(name, [str(default)])[0])
        except (ValueError, IndexError):
            raise MetadataError(f"malformed numeric field {name} in {path}")

    def bools(name: str) -> list[bool]:
        return [v.upper() == "TRUE" for v in fields.get(name, [])]

    n_waves = num("NWavelengths")
    waves = [
        fields.get(f"WaveName{i}", [f"w{i}"])[0] for i in range(1, n_waves + 1)
    ]
    x_sites, y_sites = num("XSites"), num("YSites")
    # site linear numbering (1-based, row-major) covers SELECTED cells only
    site_grid: list[tuple[int, int]] = []
    any_selection = any(f"SiteSelection{r + 1}" in fields for r in range(y_sites))
    for r in range(y_sites):
        sel = bools(f"SiteSelection{r + 1}") if any_selection else [True] * x_sites
        for c in range(x_sites):
            if c < len(sel) and sel[c]:
                site_grid.append((r, c))
    return {
        "waves": waves,
        "site_grid": site_grid,
        "sites_x": x_sites,
        "n_tpoints": num("TimePoints"),
        "n_zsteps": num("ZSteps") if fields.get("DoZSeries", ["FALSE"])[0].upper() == "TRUE" else 1,
    }


#: <base>_<well>_s<site>_w<wave>[GUID][_z<k>].tif — the GUID suffix appears
#: in MetaXpress ≥5 exports; thumbnails end in "_thumb" and are excluded
IMAGEXPRESS_FILE = re.compile(
    r"_(?P<well>[A-Z]{1,2}\d{2})"
    r"_s(?P<site>\d+)"
    r"_w(?P<wave>\d+)"
    r"(?!.*_thumb)"
    r"(?:[0-9A-F-]{36})?"
    r"(?:_z(?P<z>\d+))?"
    r"\.(?:tif|tiff|TIF|TIFF)$"
)


@register_sidecar_handler("imagexpress")
def imagexpress_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """ImageXpress handler: requires ``*.HTD`` plate-description files.

    Each ``.HTD`` describes ONE plate scan and applies only to the image
    files under its own directory (the standard MetaXpress export layout
    puts one HTD per plate folder); multi-plate source trees therefore get
    per-plate wave names and site grids instead of the first HTD's.  Image
    files are matched by the MetaXpress filename convention; the timepoint
    comes from the enclosing ``TimePoint_<t>`` directory when the scan is a
    timelapse.  Site linear indices from the filename are mapped onto the
    HTD's selected-site grid so the manifest's within-well grid coordinates
    are faithful even for sparse site selections.
    """
    htds = sorted(p for p in source_dir.rglob("*") if p.suffix.upper() == ".HTD")
    if not htds:
        return None
    # one plate scope per HTD directory; first parseable HTD in a dir wins.
    # Plate names come from the scope directory's path relative to the
    # source root — scope dirs are unique, so names cannot collide even
    # when two plate folders carry same-named .HTD files.
    scopes: list[tuple[Path, str, dict]] = []
    seen_dirs: set[Path] = set()
    for htd in htds:
        if htd.parent in seen_dirs:
            continue
        try:
            info = parse_htd(htd)
        except MetadataError as exc:
            logger.warning("ignoring unparseable .HTD file: %s", exc)
            continue
        seen_dirs.add(htd.parent)
        rel = htd.parent.relative_to(source_dir)
        plate = "_".join(rel.parts) if rel.parts else "plate00"
        scopes.append((htd.parent, plate, info))
    if not scopes:
        raise MetadataError(f"no parseable .HTD file under {source_dir}")

    entries: list[dict] = []
    skipped = 0
    claimed: set[Path] = set()
    # deepest scope first so nested plate folders claim their own files;
    # a final source-root pass under the shallowest scope picks up images
    # living outside every HTD directory (layouts that park the HTD in a
    # sidecar folder like PlateInfo/) instead of silently dropping them
    ordered = sorted(scopes, key=lambda s: len(s[0].parts), reverse=True)
    sweeps = list(ordered)
    if len(scopes) == 1:
        # single-plate layout with the HTD in a sidecar folder: images
        # outside the HTD directory unambiguously belong to that plate.
        # With several plates, a stray file outside every plate folder has
        # no owner — it is counted as skipped below, never guessed.
        only = scopes[0]
        sweeps.append((source_dir, only[1], only[2]))
    for scan_dir, plate, info in sweeps:
        for p in sorted(scan_dir.rglob("*")):
            if p in claimed or not p.is_file():
                continue
            if p.suffix.lower() not in (".tif", ".tiff"):
                continue
            claimed.add(p)
            if "_thumb" in p.name:
                continue
            m = IMAGEXPRESS_FILE.search(p.name)
            if m is None:
                skipped += 1
                continue
            row, col = parse_well_name_token(m.group("well"))
            site_i = int(m.group("site")) - 1
            if site_i < len(info["site_grid"]):
                sy, sx = info["site_grid"][site_i]
            else:
                sy, sx = divmod(site_i, info["sites_x"])
            wave_i = int(m.group("wave"))
            channel = (
                info["waves"][wave_i - 1]
                if 0 < wave_i <= len(info["waves"])
                else f"w{wave_i}"
            )
            tpoint = 0
            # only directory levels BELOW the plate scope address
            # timepoints — an ancestor dir named TimePoint_<n> must not
            for part in p.relative_to(scan_dir).parts[:-1]:
                tm = re.fullmatch(r"TimePoint_(\d+)", part)
                if tm:
                    tpoint = int(tm.group(1)) - 1
            entries.append(
                {
                    "plate": plate,
                    "well_row": row,
                    "well_col": col,
                    "site": site_i,
                    "site_y": sy,
                    "site_x": sx,
                    "channel": channel,
                    "cycle": 0,
                    "tpoint": tpoint,
                    "zplane": int(m.group("z") or 1) - 1,
                    "path": str(p),
                }
            )
    if len(scopes) > 1:
        # multi-plate: stray pattern-matching images outside every plate
        # folder are visible in the skip count instead of silently ignored
        for p in sorted(source_dir.rglob("*")):
            if p in claimed or not p.is_file():
                continue
            if p.suffix.lower() in (".tif", ".tiff") and "_thumb" not in p.name:
                skipped += 1
    return entries, skipped


def parse_well_name_token(token: str) -> tuple[int, int]:
    """'B03' → (1, 2) without importing metaconfig at module load."""
    from tmlibrary_tpu.workflow.steps.metaconfig import parse_well_name

    return parse_well_name(token)


# ----------------------------------------------------------------- metamorph
def parse_nd(path: Path) -> dict:
    """Parse a MetaMorph ``.nd`` acquisition-description file.

    Reference parity: ``tmlib/workflow/metaconfig``'s vendor handler set
    (SURVEY.md §2 metaconfig row, vendor set tagged [L]).  The ``.nd``
    format is line-oriented ``"Key", value`` pairs describing the
    wave (channel), stage-position and timepoint dimensions of one
    acquisition; image files are named
    ``<base>_w<N><wave>_s<position>_t<timepoint>``.
    """
    keys: dict[str, str] = {}
    for raw in path.read_text(errors="replace").splitlines():
        line = raw.strip()
        if not line or line == '"EndFile"':
            continue
        parts = line.split(",", 1)
        key = parts[0].strip().strip('"')
        val = parts[1].strip().strip('"') if len(parts) > 1 else ""
        keys[key] = val

    def flag(name: str) -> bool:
        return keys.get(name, "FALSE").upper() == "TRUE"

    def num(name: str, default: int = 1) -> int:
        try:
            return int(keys.get(name, default))
        except ValueError:
            raise MetadataError(f"malformed numeric field {name} in {path}")

    waves = []
    if flag("DoWave"):
        waves = [keys.get(f"WaveName{i}", f"w{i}") for i in range(1, num("NWaves") + 1)]
    stages = []
    if flag("DoStage"):
        stages = [
            keys.get(f"Stage{i}", f"s{i}") for i in range(1, num("NStagePositions") + 1)
        ]
    return {
        "waves": waves,
        "stages": stages,
        "n_tpoints": num("NTimePoints") if flag("DoTimelapse") else 1,
        "n_zsteps": num("NZSteps") if flag("DoZSeries") else 1,
    }


def _well_token():
    """Compiled well-name token search, sourced from metaconfig's
    WELL_NAME_PATTERN so the two can't drift.  Deferred import:
    metaconfig is the module that imports this handler registry."""
    from tmlibrary_tpu.workflow.steps.metaconfig import WELL_NAME_PATTERN

    return re.compile(WELL_NAME_PATTERN)


@register_sidecar_handler("metamorph")
def metamorph_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """MetaMorph handler: requires ``*.nd`` files in the source tree.

    Well assignment: a stage label containing a well token (``A01``) maps
    to that well, with repeated labels numbering sites within the well in
    label order; labels without a well token all land in one well with the
    position index as the site.  Z-series acquisitions are stored as
    multi-page stacks, addressed via per-plane ``page`` indices.
    """
    nds = sorted(source_dir.rglob("*.nd"))
    if not nds:
        return None
    by_stem = _index_files(source_dir, stems=True)

    entries: list[dict] = []
    skipped = 0
    # shared across .nd files: two acquisitions hitting the same well must
    # get distinct site numbers, not overwrite each other's store slots
    site_counter: dict[tuple[int, int], int] = {}
    for nd in nds:
        try:
            info = parse_nd(nd)
        except MetadataError as exc:
            logger.warning("ignoring unparseable .nd file: %s", exc)
            continue
        base = nd.stem
        waves = info["waves"] or [None]
        stages = info["stages"] or [None]

        from tmlibrary_tpu.workflow.steps.metaconfig import parse_well_name
        well_token = _well_token()
        addr: list[tuple[int, int, int]] = []
        for pos, label in enumerate(stages):
            m = well_token.search(label) if label else None
            if m:
                row, col = parse_well_name(m.group(0))
            else:
                row, col = 0, 0
            site = site_counter.get((row, col), 0)
            site_counter[(row, col)] = site + 1
            addr.append((row, col, site))

        for t in range(info["n_tpoints"]):
            for wi, wave in enumerate(waves):
                for pos, label in enumerate(stages):
                    stem = base
                    if wave is not None:
                        stem += f"_w{wi + 1}{wave}"
                    if info["stages"]:
                        stem += f"_s{pos + 1}"
                    if info["n_tpoints"] > 1:
                        stem += f"_t{t + 1}"
                    path = by_stem.get(stem)
                    if path is None:
                        skipped += 1
                        continue
                    row, col, site = addr[pos]
                    for z in range(info["n_zsteps"]):
                        rec = {
                            "plate": "plate00",
                            "well_row": row,
                            "well_col": col,
                            "site": site,
                            "channel": wave if wave is not None else "w1",
                            "cycle": 0,
                            "tpoint": t,
                            "zplane": z,
                            "path": str(path),
                        }
                        if info["n_zsteps"] > 1:
                            rec["page"] = z  # stack page = z plane
                        entries.append(rec)
    return entries, skipped


def _image_files(source_dir: Path) -> list[Path]:
    """All image files under the tree, sorted (shared by the token-based
    filename handlers)."""
    return [
        p for p in sorted(source_dir.rglob("*"))
        if p.suffix.lower() in (".tif", ".tiff", ".png")
    ]


# -------------------------------------------------------------------- scanr
#: standard plate geometries (wells -> (rows, cols)), smallest-first
_PLATE_GEOMETRIES = (
    (6, (2, 3)), (12, (3, 4)), (24, (4, 6)), (48, (6, 8)),
    (96, (8, 12)), (384, (16, 24)), (1536, (32, 48)),
)


def _scanr_tokens(stem: str) -> dict[str, str] | None:
    """Split a ScanR filename stem on ``--`` into its dimension tokens.

    ScanR names planes ``<prefix>--W00001--P00012--Z00000--T00000--<chan>``
    (Z/T optional); W (well) and P (position) are required for a match,
    the trailing token is the channel name."""
    parts = stem.split("--")
    if len(parts) < 3:
        return None
    out: dict[str, str] = {}
    for tok in parts[1:-1]:
        m = re.fullmatch(r"([WPZT])(\d+)", tok)
        if m:
            out[m.group(1)] = m.group(2)
    if "W" not in out or "P" not in out:
        return None
    out["channel"] = parts[-1]
    return out


def _scanr_plate_shape(source_dir: Path, n_wells: int) -> tuple[int, int]:
    """Plate geometry: from ``experiment_descriptor.xml`` when a
    plate-describing element carries row/column counts, else the smallest
    standard plate that fits the well count (documented heuristic — ScanR
    well indices are linear).

    Only elements whose tag mentions "plate" with exact ``rows``/
    ``columns``-style attribute names are considered, so per-well
    ``<Well Row=.. Column=..>`` entries or pitch/spacing attributes can't
    masquerade as the geometry."""
    attr_rows = re.compile(r"^(n?_?rows?)$", re.IGNORECASE)
    attr_cols = re.compile(r"^(n?_?col(umn)?s?)$", re.IGNORECASE)
    for xml in sorted(source_dir.rglob("experiment_descriptor.xml")):
        try:
            root = ET.parse(xml).getroot()
        except ET.ParseError:
            continue
        for el in root.iter():
            if "plate" not in _strip_ns(el.tag).lower():
                continue
            rows = next(
                (v for k, v in el.attrib.items() if attr_rows.match(k)), None
            )
            cols = next(
                (v for k, v in el.attrib.items() if attr_cols.match(k)), None
            )
            try:
                if rows and cols and int(rows) * int(cols) >= n_wells:
                    return int(rows), int(cols)
            except ValueError:
                continue
    for n, shape in _PLATE_GEOMETRIES:
        if n >= n_wells:
            return shape
    # beyond 1536: single row of wells
    return 1, n_wells


@register_sidecar_handler("scanr")
def scanr_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Olympus ScanR handler: recognizes the ``--W...--P...--`` token
    filename convention (``experiment_descriptor.xml`` is consulted for
    the plate geometry when present, but is not required).

    Reference parity: ``tmlib/workflow/metaconfig``'s vendor handler set
    (SURVEY.md §2 metaconfig row, vendor set tagged [L]).  ScanR well
    indices are linear and 1-based; they map row-major onto the plate
    geometry.  Positions are 1-based sites within the well; Z and T
    tokens become zplane/tpoint.
    """
    images = _image_files(source_dir)
    parsed = [(p, _scanr_tokens(p.stem)) for p in images]
    matches = [(p, t) for p, t in parsed if t is not None]
    if not matches:
        return None

    # ScanR W/P tokens are 1-based by convention, but some exports count
    # from 0: an observed zero token flips that dimension to 0-based.
    # (Min-normalization would be wrong — screens routinely image a well
    # subset, and W must keep its absolute plate position.)
    w_base = 0 if min(int(t["W"]) for _, t in matches) == 0 else 1
    p_base = 0 if min(int(t["P"]) for _, t in matches) == 0 else 1
    n_wells = max(int(t["W"]) for _, t in matches) - w_base + 1
    rows, cols = _scanr_plate_shape(source_dir, n_wells)

    entries: list[dict] = []
    skipped = len(parsed) - len(matches)
    for path, t in matches:
        w = int(t["W"]) - w_base  # linear well index, row-major
        entries.append(
            {
                "plate": "plate00",
                "well_row": w // cols,
                "well_col": w % cols,
                "site": int(t["P"]) - p_base,
                "channel": t["channel"],
                "cycle": 0,
                "tpoint": int(t.get("T", 0)),
                "zplane": int(t.get("Z", 0)),
                "path": str(path),
            }
        )
    return entries, skipped


# ------------------------------------------------------------------- leica
def _leica_tokens(stem: str) -> dict[str, int] | None:
    """Parse a Leica MatrixScreener image stem.

    The LAS X MatrixScreener export names planes
    ``image--L00--S00--U01--V02--J08--E00--O00--X03--Y04--T00--Z05--C01``:
    U/V are the well column/row on the plate, X/Y the field (site) grid
    within the well, T/Z/C the timepoint, z-plane and channel.  U, V, X
    and Y are required for a match; the other dimensions default to 0."""
    parts = stem.split("--")
    if len(parts) < 5:
        return None
    out: dict[str, int] = {}
    for tok in parts[1:]:
        m = re.fullmatch(r"([A-Z])(\d+)", tok)
        if m:
            out[m.group(1)] = int(m.group(2))
    if not {"U", "V", "X", "Y"} <= set(out):
        return None
    return out


@register_sidecar_handler("leica")
def leica_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Leica MatrixScreener handler (``--U--V--X--Y`` token filenames).

    Reference parity: ``tmlib/workflow/metaconfig``'s vendor handler set
    (SURVEY.md §2 metaconfig row, vendor set tagged [L]).  Wells come from
    the U (column) / V (row) tokens; the within-well field grid (X, Y)
    passes through as authoritative grid coordinates (metaconfig derives
    the site numbering from them); time loops (L) fold with T into one
    dense tpoint axis."""
    images = _image_files(source_dir)
    matches = [
        (p, t) for p, t in ((p, _leica_tokens(p.name.split(".")[0]))
                            for p in images)
        if t is not None
    ]
    if not matches:
        return None

    # time loops (L) and timepoints (T) compose lexicographically into one
    # dense tpoint axis — collapsing L would silently overwrite whole loops
    n_t = max(t.get("T", 0) for _, t in matches) + 1
    entries: list[dict] = []
    for path, t in matches:
        entries.append(
            {
                "plate": "plate00",
                "well_row": t["V"],
                "well_col": t["U"],
                # site index is derived by metaconfig._linearise_sites from
                # the authoritative grid coords — no duplicate flattening
                "site": 0,
                "site_y": t["Y"],
                "site_x": t["X"],
                "channel": f"C{t.get('C', 0):02d}",
                "cycle": 0,
                "tpoint": t.get("L", 0) * n_t + t.get("T", 0),
                "zplane": t.get("Z", 0),
                "path": str(path),
            }
        )
    return entries, len(images) - len(matches)


# ------------------------------------------------- container-format helpers
def parse_well_token(stem: str) -> tuple[int, int] | None:
    """First well-name token (``A01``) in a filename stem, or None."""
    for token in re.split(r"[_\-\s]+", stem):
        try:
            return parse_well_name_token(token)
        except MetadataError:
            continue
    return None


def assign_container_wells(
    readable: list, kind: str
) -> list:
    """Shared well-assignment policy for one-file-per-well container
    formats (nd2, czi, …): explicit well tokens are authoritative and
    must be unique — two files on one well would silently overwrite each
    other's pixels in the store — and token-less files take the next FREE
    column on row A so they can't collide with a real A-row well either.

    ``readable``: ``[(path, meta, well_or_None)]`` →
    ``[(path, meta, (row, col))]``; raises
    :class:`~tmlibrary_tpu.errors.VendorConflictError` on duplicates.
    """
    from tmlibrary_tpu.errors import VendorConflictError

    by_well: dict[tuple[int, int], Path] = {}
    for path, _, well in readable:
        if well is None:
            continue
        if well in by_well:
            raise VendorConflictError(
                f"{kind} files {by_well[well]} and {path} both claim well "
                f"{well} — their planes would overwrite each other"
            )
        by_well[well] = path
    out = []
    next_col = 0
    for path, meta, well in readable:
        if well is None:
            while (0, next_col) in by_well:
                next_col += 1
            well = (0, next_col)
            by_well[well] = path
        out.append((path, meta, well))
    return out


def sanitize_channel_label(names, c: int) -> str:
    """The ONE channel-label policy for container metadata names:
    sanitize to the ingest pattern's charset, fall back to ``C%02d``
    when the name is absent or empty.  Prefer :func:`channel_labels`
    for a whole channel set — it adds the collision guard."""
    if names and c < len(names) and names[c]:
        return re.sub(r"[^A-Za-z0-9\-]", "-", names[c])
    return f"C{c:02d}"


def channel_labels(names, n: int) -> list[str]:
    """Sanitized labels for ``n`` channels with a collision guard:
    duplicate labels (two detectors sharing one LUT name, or distinct
    names merged by sanitization) would collapse distinct channels into
    ONE store channel downstream — metaconfig builds channels from a
    set and imextract groups planes by channel label, so one channel's
    pixels would silently overwrite the other's.  Any collision drops
    the whole set to the ``C%02d`` fallback."""
    labels = [sanitize_channel_label(names, c) for c in range(n)]
    if len(set(labels)) != n:
        return [f"C{c:02d}" for c in range(n)]
    return labels


def _container_entry(path: Path, well: tuple[int, int], site: int,
                     channel: int, zplane: int, tpoint: int,
                     page: int) -> dict:
    """The one home of the container-format entry schema."""
    return {
        "plate": "plate00",
        "well_row": well[0],
        "well_col": well[1],
        "site": site,
        "channel": f"C{channel:02d}",
        "cycle": 0,
        "tpoint": tpoint,
        "zplane": zplane,
        "path": str(path),
        "page": page,
    }


def _container_sidecar(
    source_dir: Path, suffix: str, reader_cls, kind: str,
    dims_of: Callable, entries_of: Callable,
    well_of: "Callable | None" = None,
) -> tuple[list[dict], int] | None:
    """Shared scan -> skip-unreadable -> assign-wells -> emit loop of the
    one-file-per-well container handlers (nd2/czi/lif/dv); only the
    reader, the dims tuple and the page formula differ per format.
    ``suffix`` may be one extension or a tuple of them; ``well_of``
    overrides the default well-token parse (flex: Opera numeric names)."""
    suffixes = (suffix,) if isinstance(suffix, str) else suffix
    files = sorted(
        p for suf in suffixes for p in source_dir.rglob(f"*{suf}")
    )
    if not files:
        return None
    readable = []
    skipped = 0
    for path in files:
        try:
            with reader_cls(path) as r:
                dims = dims_of(r)
        # NotSupportedError too: a reader gating on a feature it does not
        # model (RGB .stk, interleaved .lsm) must skip that file like any
        # unreadable one, not abort the whole ingest
        except (MetadataError, NotSupportedError) as exc:
            logger.warning("skipping unreadable %s file %s: %s", kind, path, exc)
            skipped += 1
            continue
        readable.append(
            (path, dims, (well_of or parse_well_token)(path.stem))
        )
    entries: list[dict] = []
    for path, dims, well in assign_container_wells(readable, kind):
        entries.extend(entries_of(path, dims, well))
    return entries, skipped


# ----------------------------------------------------------------------- nd2
@register_sidecar_handler("nd2")
def nd2_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Nikon NIS-Elements ``.nd2`` containers, read by the first-party
    chunk-map parser (:class:`tmlibrary_tpu.readers.ND2Reader` — narrows
    the Bio-Formats gap, SURVEY.md §3 Readers row).

    One file per well when a well-name token (``A01``) appears in the
    filename; otherwise each file becomes its own well on row A.  The
    SLxExperiment loop structure assigns each sequence its
    (XY-position, Z, T) coordinate — XY positions map to sites with
    time/Z preserved; files without a modeled loop structure keep the
    flat sequences-as-sites mapping.  When the XYPosLoop's stage
    coordinates form a dense rectangle, each site also carries its
    within-well grid coordinate (``site_y``/``site_x``) so multi-point
    wells linearize in acquisition geometry (same dense-grid
    cross-check as CZI mosaic origins).  Interleaved components map to
    channels (``C00``/``C01``/…); ``page`` encodes
    ``seq * n_components + comp`` for imextract's plane decode."""
    from tmlibrary_tpu.readers import ND2Reader

    def entries_of(path, dims, well):
        n_seq, n_comp, coords, positions, names = dims
        if not coords:
            # zero-sequence file (aborted acquisition): no entries, and
            # max() below must not crash the whole ingest
            return []
        n_xy = max(xy for xy, _, _ in coords) + 1
        grid = None
        if positions is not None and len(positions) == n_xy and n_xy > 1:
            res = dense_grid(
                [p[0] for p in positions], [p[1] for p in positions], n_xy
            )
            grid = None if res is None else res[0]
        labels = channel_labels(names, n_comp)
        out = []
        for seq in range(n_seq):
            xy, z, t = coords[seq]
            for comp in range(n_comp):
                e = _container_entry(path, well, site=xy, channel=comp,
                                     zplane=z, tpoint=t,
                                     page=seq * n_comp + comp)
                e["channel"] = labels[comp]
                if grid is not None:
                    e["site_y"], e["site_x"] = grid[xy]
                out.append(e)
        return out

    return _container_sidecar(
        source_dir, ".nd2", ND2Reader, "ND2",
        lambda r: (r.n_sequences, r.n_components,
                   [r.seq_coords(s) for s in range(r.n_sequences)],
                   r.xy_positions(), r.channel_names()),
        entries_of,
    )


# ----------------------------------------------------------------------- czi
@register_sidecar_handler("czi")
def czi_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Zeiss ``.czi`` containers, read by the first-party ZISRAW parser
    (:class:`tmlibrary_tpu.readers.CZIReader`).

    Same conventions as the nd2 handler: one file per well (well-name
    token in the filename, else the next free column on row A), scenes
    (S) × mosaic tiles (M, slide scans) map to sites, channels to
    ``C00``/…, with Z/T preserved; ``page`` encodes
    ``(((s * M + m) * C + c) * Z + z) * T + t`` for imextract.

    Single-scene mosaics additionally carry each tile's within-well
    grid coordinate (``site_y``/``site_x`` from the subblock directory's
    mosaic pixel origins) whenever the origins form a dense rectangle —
    the adjacency ``--layout spatial`` needs to stitch a slide scan in
    acquisition geometry rather than a square-ish default grid."""
    from tmlibrary_tpu.readers import CZIReader

    def tile_grid(n_m, origins) -> "list[tuple[int, int]] | None":
        """(y, x) grid index per tile rank, or None when origins are
        absent or not a dense rectangle (shared cross-check)."""
        if origins is None:
            return None
        res = dense_grid(
            [float(y) for y, _ in origins],
            [float(x) for _, x in origins], n_m,
        )
        return None if res is None else res[0]

    def entries_of(path, dims, well):
        n_s, n_m, n_c, n_z, n_t, origins, names = dims
        grid = tile_grid(n_m, origins) if n_s == 1 and n_m > 1 else None
        labels = channel_labels(names, n_c)
        out = []
        for s in range(n_s):
            for m in range(n_m):
                for c in range(n_c):
                    label = labels[c]
                    for z in range(n_z):
                        for t in range(n_t):
                            e = _container_entry(
                                path, well, site=s * n_m + m, channel=c,
                                zplane=z, tpoint=t,
                                page=(((s * n_m + m) * n_c + c) * n_z + z)
                                * n_t + t)
                            e["channel"] = label
                            if grid is not None:
                                e["site_y"], e["site_x"] = grid[m]
                            out.append(e)
        return out

    return _container_sidecar(
        source_dir, ".czi", CZIReader, "CZI",
        lambda r: (r.n_scenes, r.n_tiles, r.n_channels, r.n_zplanes,
                   r.n_tpoints,
                   [r.tile_origin(0, m) for m in range(r.n_tiles)]
                   if r.n_scenes == 1 else None,
                   r.channel_names),
        entries_of,
    )


# ----------------------------------------------------------------------- lif
@register_sidecar_handler("lif")
def lif_sidecar(source_dir: Path) -> tuple[list[dict], int] | None:
    """Leica Image Files, read by the first-party block parser
    (:class:`tmlibrary_tpu.readers.LIFReader`).

    Same conventions as the nd2/czi handlers: one file per well (token or
    next free column on row A), image series map to sites, channel labels
    from the LUTName attributes (``C00``/… fallback), Z/T preserved;
    ``page`` encodes the whole-file linear index
    ``series * C*Z*T + (c*Z + z)*T + t`` for imextract.  Files whose
    series disagree on (C, Z, T) are skipped with a logged reason."""
    from tmlibrary_tpu.readers import LIFReader

    def entries_of(path, dims, well):
        n_series, n_c, n_z, n_t, names = dims
        labels = channel_labels(names, n_c)
        out = []
        for s in range(n_series):
            for c in range(n_c):
                for z in range(n_z):
                    for t in range(n_t):
                        e = _container_entry(
                            path, well, site=s, channel=c, zplane=z,
                            tpoint=t,
                            page=(s * n_c + c) * n_z * n_t + z * n_t + t)
                        e["channel"] = labels[c]
                        out.append(e)
        return out

    return _container_sidecar(
        source_dir, ".lif", LIFReader, "LIF",
        lambda r: (r.n_series, *r.uniform_dims(), r.channel_names()),
        entries_of,
    )


# ---------------------------------------------------------------------- ngff
@register_sidecar_handler("ngff")
def ngff_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """OME-NGFF (OME-Zarr v0.4) HCS plates, read by the first-party Zarr
    v2 parser (:class:`tmlibrary_tpu.ngff.NGFFReader`).

    HCS plates take their wells from the plate's own metadata
    (``rowIndex``/``columnIndex``) and their plate name from the
    ``*.zarr`` directory's stem; BARE multiscale images (no ``plate``
    key — the most common OME-Zarr form) are assigned wells like the
    nd2/czi/lif containers: filename token (``A01``), else the next
    free column on row A.  Fields map to sites, omero channel labels
    (sanitized) name the channels.  ``page`` encodes
    ``(((well * F + field) * T + t) * C + c) * Z + z`` — the convention
    :meth:`~tmlibrary_tpu.ngff.NGFFReader.read_plane_linear` decodes for
    imextract."""
    from tmlibrary_tpu.ngff import NGFFReader

    plates = sorted(
        p for p in source_dir.rglob("*.zarr")
        if p.is_dir() and (p / ".zattrs").exists()
    )
    if not plates:
        return None
    entries: list[dict] = []
    skipped = 0
    bare: list[tuple] = []

    def channel_names(nc, labels):
        return channel_labels(labels, nc)

    def emit(path, info, wells, plate_name):
        nf, nt, nc, nz, labels = info
        names = channel_names(nc, labels)
        for wi, well in enumerate(wells):
            for f in range(nf):
                for t in range(nt):
                    for c in range(nc):
                        for z in range(nz):
                            e = _container_entry(
                                path, well, site=f, channel=c,
                                zplane=z, tpoint=t,
                                page=(((wi * nf + f) * nt + t) * nc + c)
                                * nz + z,
                            )
                            e["plate"] = plate_name
                            e["channel"] = names[c]
                            entries.append(e)

    for path in plates:
        try:
            with NGFFReader(path) as r:
                info = (r.n_fields, r.n_tpoints, r.n_channels,
                        r.n_zplanes, r.channel_names)
                if r.is_plate:
                    plate_name = (
                        re.sub(r"[^A-Za-z0-9]", "", path.stem) or "plate00"
                    )
                    emit(path, info, list(r.well_indices), plate_name)
                else:
                    bare.append((path, info, parse_well_token(path.stem)))
        except MetadataError as exc:
            logger.warning("skipping unreadable NGFF plate %s: %s",
                           path, exc)
            skipped += 1
    # bare images land on "plate00" (the shared container convention);
    # assign_container_wells only deduplicates AMONG the bare files, so
    # an HCS plate whose sanitized stem is also "plate00" must not have
    # its wells silently overwritten by a bare image's pixels
    claimed = {
        (e["plate"], e["well_row"], e["well_col"]) for e in entries
    }
    for path, info, well in assign_container_wells(bare, "NGFF"):
        if ("plate00", well[0], well[1]) in claimed:
            from tmlibrary_tpu.errors import VendorConflictError

            raise VendorConflictError(
                f"bare NGFF image {path} would land on plate00 well "
                f"{well}, already claimed by an HCS plate in the same "
                f"source dir — rename one of them"
            )
        emit(path, info, [well], "plate00")
    return entries, skipped


# ------------------------------------------------------------------------ dv
@register_sidecar_handler("dv")
def dv_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """DeltaVision ``.dv`` / ``.r3d`` stacks, read by the first-party
    MRC-variant parser (:class:`tmlibrary_tpu.readers.DVReader`).

    Same conventions as the nd2/czi/lif handlers: one file per well
    (well-name token in the filename, else the next free column on row
    A); each stack is a single site with its wavelengths as channels and
    Z/T preserved; ``page`` encodes ``(c * Z + z) * T + t`` for
    imextract's plane decode."""
    from tmlibrary_tpu.readers import DVReader

    def entries_of(path, dims, well):
        n_c, n_z, n_t = dims
        return [
            _container_entry(path, well, site=0, channel=c, zplane=z,
                             tpoint=t, page=(c * n_z + z) * n_t + t)
            for c in range(n_c)
            for z in range(n_z)
            for t in range(n_t)
        ]

    return _container_sidecar(
        source_dir, (".dv", ".r3d"), DVReader, "DV",
        lambda r: (r.n_channels, r.n_zplanes, r.n_tpoints), entries_of,
    )


# ----------------------------------------------------------------------- ims
@register_sidecar_handler("ims")
def ims_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """Bitplane Imaris ``.ims`` files, read by
    :class:`tmlibrary_tpu.readers.IMSReader` (HDF5 layout; channel names
    from ``DataSetInfo/Channel <c>`` when present).

    Same conventions as the other container handlers: one file per well
    (token or next free column on row A), one site per file, Z/T
    preserved; ``page`` encodes ``(c * Z + z) * T + t``."""
    from tmlibrary_tpu.readers import IMSReader

    def entries_of(path, dims, well):
        n_c, n_z, n_t, names = dims
        labels = channel_labels(names, n_c)
        out = []
        for c in range(n_c):
            label = labels[c]
            for z in range(n_z):
                for t in range(n_t):
                    e = _container_entry(
                        path, well, site=0, channel=c, zplane=z,
                        tpoint=t, page=(c * n_z + z) * n_t + t,
                    )
                    e["channel"] = label
                    out.append(e)
        return out

    return _container_sidecar(
        source_dir, ".ims", IMSReader, "IMS",
        lambda r: (r.n_channels, r.n_zplanes, r.n_tpoints,
                   r.channel_names()),
        entries_of,
    )


# ----------------------------------------------------------------------- stk
@register_sidecar_handler("stk")
def stk_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """Standalone MetaMorph ``.stk`` stacks, read by
    :class:`tmlibrary_tpu.readers.STKReader` (the UIC2-tag plane count a
    paged TIFF reader cannot see).

    MetaMorph acquisitions WITH a parseable ``.nd`` go through the richer
    ``metamorph`` handler (wavelengths, stage labels): it is registered
    first, so in auto mode it wins whenever its sidecar resolves images
    and this handler only sees trees whose ``.nd`` is absent or
    unusable.  No ``.nd`` veto here — an explicit ``handler='stk'`` (or
    a stray/corrupt ``.nd`` in auto mode) must still ingest the stacks.
    Conventions: one file per well (token or next free column on row A),
    one site per file, single channel, planes map to Z; ``page = z``."""
    from tmlibrary_tpu.readers import STKReader

    def entries_of(path, dims, well):
        (n_z,) = dims
        return [
            _container_entry(path, well, site=0, channel=0, zplane=z,
                             tpoint=0, page=z)
            for z in range(n_z)
        ]

    return _container_sidecar(
        source_dir, ".stk", STKReader, "STK",
        lambda r: (r.n_zplanes,), entries_of,
    )


# ----------------------------------------------------------------------- lsm
@register_sidecar_handler("lsm")
def lsm_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """Zeiss LSM confocal stacks, read by
    :class:`tmlibrary_tpu.readers.LSMReader` (planar per-channel strips,
    thumbnail IFDs skipped, dims from CZ_LSMINFO).

    Same conventions as the other container handlers: one file per well
    (token or next free column on row A), one site per file, C/Z/T
    preserved; ``page`` encodes ``(c * Z + z) * T + t``."""
    from tmlibrary_tpu.readers import LSMReader

    def entries_of(path, dims, well):
        n_c, n_z, n_t = dims
        return [
            _container_entry(path, well, site=0, channel=c, zplane=z,
                             tpoint=t, page=(c * n_z + z) * n_t + t)
            for c in range(n_c)
            for z in range(n_z)
            for t in range(n_t)
        ]

    return _container_sidecar(
        source_dir, ".lsm", LSMReader, "LSM",
        lambda r: (r.n_channels, r.n_zplanes, r.n_tpoints), entries_of,
    )


# ------------------------------------------------------------------- olympus
@register_sidecar_handler("olympus")
def olympus_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """Olympus FluoView ``.oif`` acquisitions and their single-file
    ``.oib`` (OLE2 compound document) form, read by
    :class:`tmlibrary_tpu.readers.OIFReader` /
    :class:`~tmlibrary_tpu.readers.OIBReader` — the compound container
    parsed by the first-party :mod:`tmlibrary_tpu.cfb` walker, no JVM.

    Same conventions as the other container handlers: one file per well
    (token or next free column on row A), one site per file, C/Z/T
    preserved; ``page`` encodes ``(c * Z + z) * T + t``.  The companion
    ``.oif.files`` TIFF directories are consumed through their main file
    only — in auto mode this handler resolves them before the filename
    fallback could ingest the raw plane TIFFs as separate channels."""
    from tmlibrary_tpu.readers import OIBReader, OIFReader

    def entries_of(path, dims, well):
        n_c, n_z, n_t, names = dims
        labels = channel_labels(names, n_c)
        out = []
        for c in range(n_c):
            for z in range(n_z):
                for t in range(n_t):
                    e = _container_entry(
                        path, well, site=0, channel=c, zplane=z,
                        tpoint=t, page=(c * n_z + z) * n_t + t)
                    e["channel"] = labels[c]
                    out.append(e)
        return out

    def open_either(path):
        # ONE shared scan for both suffixes: two token-less files must
        # take two different free wells, which per-suffix passes (each
        # with its own assign_container_wells) would not guarantee
        cls = OIBReader if str(path).lower().endswith(".oib") else OIFReader
        return cls(path)

    return _container_sidecar(
        source_dir, (".oif", ".oib"), open_either, "Olympus",
        lambda r: (r.n_channels, r.n_zplanes, r.n_tpoints,
                   r.channel_names),
        entries_of,
    )


# ---------------------------------------------------------------------- flex
@register_sidecar_handler("flex")
def flex_sidecar(source_dir: Path) -> "tuple[list[dict], int] | None":
    """PerkinElmer Opera/Operetta ``.flex`` containers, read by
    :class:`tmlibrary_tpu.readers.FlexReader` (paged TIFF + FLEX XML in
    tag 65200) — the reference's own instrument class (high-content
    screening; upstream reads these through Bio-Formats' FlexReader).

    One file per well; unlike the other containers a flex file carries
    SEVERAL fields (sites) whose pages cycle channel-fastest, so
    ``site = page // C`` and ``page = field * C + c``.  Wells come from
    a filename token (``A01``) or the Opera numeric convention
    (``rrrcccfff…`` digit stems: first three digits = 1-based row, next
    three = column); token-less files take the next free column on row
    A.  Channel labels come from the FLEX Array names when present."""
    from tmlibrary_tpu.readers import FlexReader

    def opera_well(stem: str) -> "tuple[int, int] | None":
        token = parse_well_token(stem)
        if token is not None:
            return token
        digits = re.match(r"(\d{3})(\d{3})\d*$", stem)
        if digits:
            row, col = int(digits.group(1)), int(digits.group(2))
            if row >= 1 and col >= 1:
                return row - 1, col - 1
        return None

    def entries_of(path, dims, well):
        n_fields, n_c, names = dims
        labels = channel_labels(names, n_c)
        out = []
        for c in range(n_c):
            label = labels[c]
            for f in range(n_fields):
                e = _container_entry(path, well, site=f, channel=c,
                                     zplane=0, tpoint=0,
                                     page=f * n_c + c)
                e["channel"] = label
                out.append(e)
        return out

    return _container_sidecar(
        source_dir, ".flex", FlexReader, "FLEX",
        lambda r: (r.n_fields, r.n_channels, r.channel_names),
        entries_of, well_of=opera_well,
    )


def resolve_sidecars(
    src: Path, names: "list[str]", is_auto: bool,
) -> "tuple[str, list[dict], int] | None":
    """The ONE home of metaconfig's sidecar-resolution policy, shared
    with ``tmx inspect DIR``'s dry-run preview (a separate copy would
    silently drift from real ingest behavior).

    Tries ``names`` in order; returns ``(handler, entries, skipped)``
    for the first handler that resolves images, or None when none did
    (callers fall back to filename patterns).  A data-integrity conflict
    (:class:`~tmlibrary_tpu.errors.VendorConflictError`) always
    surfaces; in non-auto mode a broken or image-less sidecar raises
    instead of being skipped.
    """
    for name in names:
        try:
            result = SIDECAR_HANDLERS[name](src)
        except VendorConflictError:
            # e.g. two containers claim one well: must surface, not be
            # laundered into a "no files matched" fallback error
            raise
        except MetadataError:
            if not is_auto:
                raise
            continue  # auto: a broken sidecar should not end ingest
        if result is None:
            continue  # this vendor's sidecar files are absent
        found, skipped = result
        if found:
            return name, found, skipped
        if not is_auto:
            raise MetadataError(
                f"'{name}' sidecar files exist under {src} but no "
                "image could be resolved from them (unrecognised "
                "image names or missing pixel files)"
            )
    return None
