"""Built-in workflow steps.

Reference parity (SURVEY.md §2/§3): one module per reference step package —
``metaconfig`` (metadata → manifest), ``imextract`` (pixel ingest),
``corilla`` (illumination statistics), ``align`` (cycle registration),
``illuminati`` (pyramid tiles), ``jterator`` (image analysis).
Importing this package registers them all.
"""

from tmlibrary_tpu.workflow.steps import (  # noqa: F401
    align,
    corilla,
    illuminati,
    imextract,
    jterator,
    metaconfig,
)
