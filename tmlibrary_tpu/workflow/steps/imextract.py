"""imextract: extract pixel planes into the canonical store.

Reference parity: ``tmlib/workflow/imextract/api.py`` ``ImageExtractor`` —
reads planes out of vendor files via Bio-Formats and writes
``ChannelImageFile``s, batched over file mappings.  Here: cv2 host reads of
the metaconfig file mapping, written as contiguous site stacks
(the TPU feed format) in batched slices.
"""

from __future__ import annotations

import numpy as np

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.utils import create_partitions
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step


@register_step("imextract")
class ImageExtractor(Step):
    batch_args = ArgumentCollection(
        Argument("batch_size", int, default=64, help="files per batch"),
    )

    def create_batches(self, args):
        from tmlibrary_tpu.workflow.steps.metaconfig import MetadataConfigurator

        mapping = MetadataConfigurator(self.store).load_mapping()
        return [
            {"files": chunk}
            for chunk in create_partitions(mapping, args["batch_size"])
        ]

    @staticmethod
    def _read_plane(path: str, page: int | None, height: int, width: int):
        """One grayscale plane as uint16: first-party native TIFF reader
        (classic strip TIFF, none/LZW/PackBits — the native data-loader)
        with the Python paged fallback (BigTIFF, deflate strips), the
        first-party ND2 chunk-map reader for ``.nd2`` containers
        (``page`` encodes sequence * n_components + component, as written
        by the nd2 metaconfig handler), cv2 for everything else (PNG,
        tiled TIFF, RGB, ...).

        ``TMX_INGEST_THROTTLE_MS`` sleeps that long per plane read in
        the WORKER, simulating a slow/cold source (network filestore
        latency) deterministically: sleeps release the GIL, so the pool
        can overlap them exactly like real blocked IO — the measurable
        reason the decode pool exists (bench ``ingest`` cold rows)."""
        import os as _os

        throttle = _os.environ.get("TMX_INGEST_THROTTLE_MS")
        if throttle:
            import time as _time

            _time.sleep(float(throttle) / 1e3)
        from tmlibrary_tpu.readers import read_container_plane

        container = read_container_plane(path, page or 0)
        if container is not None:
            return container

        from tmlibrary_tpu.native import tiff_read

        img = tiff_read(path, page or 0, height, width)
        if img is not None:
            return img

        if path.lower().endswith((".tif", ".tiff")):
            from tmlibrary_tpu.readers import read_tiff_page_py

            img = read_tiff_page_py(path, page or 0)
            if img is not None:
                return img

        import cv2

        if page is not None:
            # multi-page OME-TIFF: decode only the declared page (caching
            # whole files across a batch risks host OOM on large z/t stacks)
            ok, pages = cv2.imreadmulti(
                path, start=page, count=1, flags=cv2.IMREAD_UNCHANGED
            )
            if not ok or not pages:
                raise MetadataError(f"cannot read page {page} of {path}")
            img = pages[0]
        else:
            img = cv2.imread(path, cv2.IMREAD_UNCHANGED)
        if img is None:
            raise MetadataError(f"cannot read image {path}")
        if img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2GRAY)
        return img

    def run_batch(self, batch: dict) -> dict:
        import concurrent.futures as cf
        import os

        exp = self.store.experiment
        # group by target plane so each plane's sites write in one slice
        by_plane: dict[tuple, list[dict]] = {}
        for f in batch["files"]:
            key = (f["cycle"], f["channel"], f["tpoint"], f["zplane"])
            by_plane.setdefault(key, []).append(f)

        # plane decode is the data-loader hot loop and is IO/decompress
        # bound; the native TIFF reader and cv2 both release the GIL, so a
        # thread pool loads one plane-group's files concurrently (the
        # reference fanned per-file-mapping batches out to cluster jobs)
        # TMX_INGEST_WORKERS pins the pool (bench.py's ingest config uses
        # 1 as its single-thread denominator); anything unparseable or
        # non-positive falls back to the default rather than failing
        # every ingest batch
        try:
            workers = int(os.environ.get("TMX_INGEST_WORKERS", ""))
        except ValueError:
            workers = 0
        if workers < 1:
            # IO-bound sizing, NOT cpu_count-bound: the pool exists to
            # overlap storage stalls (cold network filestores), where
            # threads spend most of their life blocked outside the GIL —
            # a 1-core host still wants several in flight.  The floor of
            # 4 is what makes the cold-source bench rows meaningful.
            workers = max(4, min(8, os.cpu_count() or 1))
        n_written = 0
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            # submit every decode up front (concurrency spans plane
            # groups — a mapping with one file per plane would otherwise
            # serialize), then drain and write group by group
            futures = {
                (key, i): pool.submit(
                    self._read_plane, f["path"], f.get("page"),
                    exp.site_height, exp.site_width,
                )
                for key, files in by_plane.items()
                for i, f in enumerate(files)
            }
            for key, files in by_plane.items():
                cycle, channel, tpoint, zplane = key
                pixels = []
                indices = []
                for i, f in enumerate(files):
                    img = futures[(key, i)].result()
                    if img.shape != (exp.site_height, exp.site_width):
                        raise MetadataError(
                            f"{f['path']}: shape {img.shape} != site shape "
                            f"({exp.site_height}, {exp.site_width})"
                        )
                    pixels.append(np.asarray(img, np.uint16))
                    indices.append(f["site_index"])
                self.store.write_sites(
                    np.stack(pixels), indices,
                    cycle=cycle, channel=channel, tpoint=tpoint, zplane=zplane,
                )
                n_written += len(files)
        return {"n_written": n_written}
