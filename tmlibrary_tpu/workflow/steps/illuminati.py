"""illuminati: multi-resolution pyramid tiles for the viewer.

Reference parity: ``tmlib/workflow/illuminati/api.py`` ``PyramidBuilder`` —
level 0 stitches corrected/aligned/rescaled site images into the plate
mosaic and cuts 256-px tiles; level L+1 jobs consume level L (inter-level
dependency waves); tiles land in the DB (SURVEY.md §4.5).

TPU execution: one batch per (plate, channel); correction + rescale run
batched on device, the mosaic assembles host-side (it can exceed HBM for
large plates), the downsample chain runs on device per level, PNG tiles go
to ``pyramids/<channel>/<level>/<row>_<col>.png`` — a zoomify-style layout
any slippy-map viewer can serve statically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.errors import WorkflowError
from tmlibrary_tpu.models.experiment import SiteRef
from tmlibrary_tpu.models.image import IllumstatsContainer
from tmlibrary_tpu.models.metadata import ChannelLayer
from tmlibrary_tpu.ops import image_ops
from tmlibrary_tpu.ops.pyramid import cut_tiles, pyramid_levels, to_uint8
from tmlibrary_tpu.utils import create_partitions
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step


@register_step("illuminati")
class PyramidBuilder(Step):
    batch_args = ArgumentCollection(
        Argument("correct", bool, default=True, help="apply illumination stats"),
        Argument("align", bool, default=False, help="apply cycle-0 alignment"),
        Argument("clip_percent", float, default=99.9,
                 help="upper clip percentile for display rescale"),
        Argument("batch_size", int, default=32, help="sites per device batch"),
        Argument("cycle", int, default=0, help="cycle to tile"),
        Argument("n_devices", int, default=1,
                 help="row-shard the mosaic pyramid over this many devices "
                      "(mosaics larger than one chip's HBM)"),
    )

    def create_batches(self, args):
        exp = self.store.experiment
        return [
            {"plate": p.name, "channel": ch.index}
            for p in exp.plates
            for ch in exp.channels
            if self.store.has_plane(cycle=args["cycle"], channel=ch.index)
        ]

    # ------------------------------------------------------------------ run
    def run_batch(self, batch: dict) -> dict:
        import time

        from tmlibrary_tpu import telemetry

        bt0 = time.perf_counter()
        args = batch["args"]
        exp = self.store.experiment
        channel = batch["channel"]
        cycle = args["cycle"]
        plate = next(p for p in exp.plates if p.name == batch["plate"])

        stats = None
        if args["correct"] and self.store.has_illumstats(cycle=cycle, channel=channel):
            stats = IllumstatsContainer.from_store(
                self.store.read_illumstats(cycle=cycle, channel=channel)
            )

        # display range from corilla percentiles (reference: scale step)
        if stats is not None and stats.percentiles:
            upper = stats.percentiles.get(args["clip_percent"])
            lower = stats.percentiles.get(0.1, 0.0)
        else:
            upper = lower = None

        prep = image_ops.make_batch_prep(stats, apply_shift=args["align"])

        # site grid geometry (shared helper — same layout as the static
        # outlines and the pyramid-depth computation)
        from tmlibrary_tpu.models.mapobject import plate_grid, plate_mosaic_shape

        rows, cols, spw_y, spw_x = plate_grid(exp, plate.name)
        H, W = exp.site_height, exp.site_width
        mosaic = np.zeros(plate_mosaic_shape(exp, plate.name), np.float32)

        refs = [
            (SiteRef(plate.name, w.row, w.column, s.y, s.x), w, s)
            for w in plate.wells
            for s in w.sites
        ]
        shifts_table = (
            self.store.read_shifts(cycle)
            if args["align"] and self.store.has_shifts(cycle)
            else np.zeros((self.store.n_sites, 2), np.int32)
        )
        for part in create_partitions(refs, args["batch_size"]):
            idx = [self.store.site_linear_index(r) for r, _, _ in part]
            stack = self.store.read_sites(idx, cycle=cycle, channel=channel)
            prepped = np.asarray(
                prep(jnp.asarray(stack), jnp.asarray(shifts_table[idx]))
            )
            for (ref, w, s), img in zip(part, prepped):
                y0 = (w.row * spw_y + s.y) * H
                x0 = (w.column * spw_x + s.x) * W
                mosaic[y0 : y0 + H, x0 : x0 + W] = img

        if upper is None:
            # one call partitions both quantiles in a single pass over the
            # plate mosaic (two separate np.percentile calls measured ~2x
            # the cost in the workflow bench profile)
            lo_up = np.percentile(mosaic, [0.1, args["clip_percent"]])
            lower, upper = float(lo_up[0]), float(lo_up[1])

        n_dev = min(args["n_devices"], len(jax.devices()))
        if n_dev > 1:
            from jax.sharding import Mesh

            from tmlibrary_tpu.parallel.halo import sharded_pyramid_levels

            mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("rows",))
            levels = sharded_pyramid_levels(jnp.asarray(mosaic), mesh)
        else:
            levels = pyramid_levels(jnp.asarray(mosaic))
        out_dir = self.store.root / "pyramids" / f"channel{channel:02d}"
        # PNG encode is host-side and embarrassingly parallel; cv2 releases
        # the GIL during imencode, so a thread pool overlaps tile encodes
        # (the reference fanned per-level tile jobs out to the cluster)
        import concurrent.futures as cf
        import os as _os

        import cv2

        workers = min(8, _os.cpu_count() or 1)
        n_tiles = 0
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            # submit per level so only one level8 array is held at a time
            # (cut_tiles returns views into it) — encodes overlap the next
            # level's cut; futures are drained per level before the array
            # is dropped
            for li, level in enumerate(levels):
                level8 = np.asarray(to_uint8(level, float(lower), float(upper)))
                ldir = out_dir / f"{len(levels) - 1 - li}"
                ldir.mkdir(parents=True, exist_ok=True)
                futures = {
                    pool.submit(cv2.imwrite, str(ldir / f"{ty}_{tx}.png"), tile):
                    f"{ty}_{tx}.png"
                    for (ty, tx), tile in cut_tiles(level8).items()
                }
                bad = [name for fut, name in futures.items() if not fut.result()]
                if bad:
                    raise WorkflowError(
                        f"PNG tile encode failed for {len(bad)} tiles of "
                        f"level {len(levels) - 1 - li}, e.g. {bad[0]}"
                    )
                n_tiles += len(futures)
        layer = ChannelLayer(
            channel=f"channel{channel:02d}",
            height=mosaic.shape[0],
            width=mosaic.shape[1],
            max_zoom=len(levels) - 1,
        )
        import json

        (out_dir / "layer.json").write_text(json.dumps(layer.to_dict()))
        telemetry.get_registry().throughput(
            "tmx_illuminati_tiles_per_sec"
        ).add(n_tiles, time.perf_counter() - bt0)
        return {
            "channel": channel,
            "mosaic_shape": list(mosaic.shape),
            "n_levels": len(levels),
            "n_tiles": n_tiles,
        }

    def collect(self) -> dict:
        """Register the static Plates/Wells/Sites mapobject types with their
        grid outlines (reference: the static ``MapobjectType`` rows created
        alongside the pyramid so the viewer can overlay plate geometry)."""
        import pandas as pd

        from tmlibrary_tpu.models.mapobject import (
            STATIC_REF_TYPES,
            MapobjectType,
            MapobjectTypeRegistry,
            static_mapobjects,
        )

        registry = MapobjectTypeRegistry(self.store.root)
        out_dir = self.store.root / "segmentations"
        out_dir.mkdir(exist_ok=True)
        counts: dict[str, int] = {}
        for plate in self.store.experiment.plates:
            geo = static_mapobjects(self.store.experiment, plate.name)
            for type_name, outlines in geo.items():
                rows = [
                    {
                        "plate": plate.name,
                        "name": label,
                        "centroid_y": float(rect[:-1, 0].mean()),
                        "centroid_x": float(rect[:-1, 1].mean()),
                        "contour_y": rect[:, 0].tolist(),
                        "contour_x": rect[:, 1].tolist(),
                    }
                    for label, rect in outlines
                ]
                df = pd.DataFrame(rows)
                df.to_parquet(
                    out_dir / f"{type_name}_polygons_{plate.name}.parquet",
                    index=False,
                )
                counts[type_name] = counts.get(type_name, 0) + len(rows)
        for type_name in counts:
            registry.register(
                MapobjectType(
                    name=type_name,
                    ref_type=STATIC_REF_TYPES[type_name],
                    min_poly_zoom=0,
                )
            )
        return {"static_mapobjects": counts}

    def delete_previous_output(self) -> None:
        import shutil

        root = self.store.root / "pyramids"
        if root.exists():
            shutil.rmtree(root)
        root.mkdir()
