"""metaconfig: configure experiment metadata from microscope files.

Reference parity: ``tmlib/workflow/metaconfig/`` — ``MetadataConfigurator``
merges vendor metadata (filenames, OME-XML, vendor sidecar files like
Yokogawa CellVoyager ``.mlf``/``.mes``) into a canonical experiment layout:
plates → wells → sites with grid coordinates, channels, cycles, z-planes.

TPU rebuild: pure host-side ingest planning.  The vendor zoo is represented
by two handlers that cover the common cases without Bio-Formats/JVM:

- ``default``: a configurable filename-regex handler (named groups
  ``well``, ``site``/(``site_y``,``site_x``), ``channel``, optional
  ``plate``, ``cycle``, ``tpoint``, ``zplane``) — the moral equivalent of
  the reference's ``default`` handler for "plain TIFF series" microscopes.
- ``cellvoyager``: the Yokogawa filename convention
  (``..._W<well>F<field>T<tpoint>Z<zplane>C<channel>.tif``-style), the
  vendor the reference's handler set confirms (SURVEY.md §2 metaconfig row).

The output is the experiment manifest + an image-file mapping JSON the
``imextract`` step consumes (reference ``ImageFileMapping``).
"""

from __future__ import annotations

import json
import re
from collections import defaultdict
from pathlib import Path

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.models.experiment import Channel, Experiment, Plate, Site, Well
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step

#: default handler: one named-group regex over the filename
DEFAULT_PATTERN = (
    r"(?:(?P<plate>[A-Za-z0-9]+)_)?"
    r"(?P<well>[A-Z]{1,2}\d{2})_"
    r"s(?P<site>\d+)_"
    r"(?:c(?P<cycle>\d+)_)?"
    r"(?:t(?P<tpoint>\d+)_)?"
    r"(?:z(?P<zplane>\d+)_)?"
    r"(?P<channel>[A-Za-z0-9\-]+)"
    r"\.(?:tif|tiff|png)$"
)

#: Yokogawa CellVoyager: ...__W0001F001T0001Z01C1.tif style
CELLVOYAGER_PATTERN = (
    r"(?P<prefix>.*?)_?"
    r"W(?P<well_num>\d+)"
    r"F(?P<site>\d+)"
    r"T(?P<tpoint>\d+)"
    r"Z(?P<zplane>\d+)"
    r"C(?P<channel>\d+)"
    r"\.(?:tif|tiff|png)$"
)


#: GE/Cytiva InCell Analyzer export convention ("A - 1(fld 1 wv
#: Blue - FITC).tif"; z-stack/timelapse exports add "z N" / "tp N"
#: tokens inside the parens, order varying by InCell version — the
#: style branch tokenizes the paren body instead of pinning an order)
INCELL_PATTERN = (
    r"^(?P<wrow>[A-Z]{1,2}) - (?P<wcol>\d{1,2})"
    r"\((?P<tokens>[^)]*\bfld\b[^)]*)\)"
    r"\.(?:tif|tiff)$"
)


def _parse_incell_tokens(tokens: str) -> "dict | None":
    """'fld 1 wv Blue - FITC z 3' → {site, channel, zplane, tpoint}.
    The wv value runs until a trailing ``z N``/``tp N`` token or the
    end (channel names like 'Blue - FITC' contain spaces/dashes but
    never a bare z/tp-digit token)."""
    site = re.search(r"\bfld (\d+)", tokens)
    wv = re.search(r"\bwv (.+?)(?= \b(?:z|tp) \d|$)", tokens)
    if not site or not wv:
        return None
    z = re.search(r"\bz (\d+)", tokens)
    tp = re.search(r"\btp (\d+)", tokens)
    return {
        "site": int(site.group(1)),
        "channel": wv.group(1).strip(),
        "zplane": int(z.group(1)) if z else 1,
        "tpoint": int(tp.group(1)) if tp else 1,
    }


#: the well-name grammar ('B03', 'AA12'): single source of truth shared by
#: parse_well_name and the vendor sidecar handlers' token search
WELL_NAME_PATTERN = r"([A-Z]{1,2})(\d{1,2})"


def parse_well_name(name: str) -> tuple[int, int]:
    """'B03' → (row=1, col=2)."""
    m = re.fullmatch(WELL_NAME_PATTERN, name)
    if not m:
        raise MetadataError(f"cannot parse well name '{name}'")
    letters, digits = m.groups()
    row = 0
    for ch in letters:
        row = row * 26 + (ord(ch) - ord("A") + 1)
    return row - 1, int(digits) - 1


def well_num_to_rowcol(num: int, plate_cols: int = 24) -> tuple[int, int]:
    """CellVoyager numeric well index (1-based, row-major) → (row, col)."""
    return (num - 1) // plate_cols, (num - 1) % plate_cols


class FilenameHandler:
    """Parse one file path into a canonical index dict."""

    def __init__(self, pattern: str, style: str, plate_cols: int = 24,
                 sites_per_well_x: int | None = None):
        self.regex = re.compile(pattern)
        self.style = style
        self.plate_cols = plate_cols
        self.sites_per_well_x = sites_per_well_x

    def parse(self, filename: str) -> dict | None:
        m = self.regex.search(filename)
        if not m:
            return None
        g = m.groupdict()
        if self.style == "incell":
            row = 0
            for ch in g["wrow"]:
                row = row * 26 + (ord(ch) - ord("A") + 1)
            parsed = _parse_incell_tokens(g["tokens"])
            if parsed is None:
                return None
            return {
                "plate": "plate00",
                "well_row": row - 1,
                "well_col": int(g["wcol"]) - 1,
                "site": parsed["site"] - 1,  # fld is 1-based
                "channel": parsed["channel"],
                "cycle": 0,
                "tpoint": parsed["tpoint"] - 1,
                "zplane": parsed["zplane"] - 1,
            }
        if self.style == "cellvoyager":
            row, col = well_num_to_rowcol(int(g["well_num"]), self.plate_cols)
        else:
            row, col = parse_well_name(g["well"])
        return {
            "plate": g.get("plate") or "plate00",
            "well_row": row,
            "well_col": col,
            "site": int(g["site"]) - (1 if self.style == "cellvoyager" else 0),
            "channel": str(g["channel"]),
            "cycle": int(g.get("cycle") or 0),
            "tpoint": int(g.get("tpoint") or (1 if self.style == "cellvoyager" else 0))
            - (1 if self.style == "cellvoyager" else 0),
            "zplane": int(g.get("zplane") or (1 if self.style == "cellvoyager" else 0))
            - (1 if self.style == "cellvoyager" else 0),
        }


@register_step("metaconfig")
class MetadataConfigurator(Step):
    """Build the experiment manifest + file mapping from a source directory."""

    batch_args = ArgumentCollection(
        Argument("source_dir", str, required=True,
                 help="directory of microscope image files"),
        Argument("handler", str, default="default",
                 choices=("default", "cellvoyager", "incell", "omexml",
                          "metamorph", "harmony", "imagexpress", "scanr",
                          "leica", "nd2", "czi", "lif", "ngff", "dv",
                          "ims", "stk", "lsm", "olympus", "flex", "auto"),
                 help="vendor metadata handler (sidecar files preferred, "
                      "filename patterns as fallback)"),
        Argument("pattern", str, default=None,
                 help="override the handler's filename regex"),
        Argument("sites_per_well_x", int, default=None,
                 help="well grid width in sites (default: square-ish)"),
        Argument("plate_cols", int, default=24,
                 help="plate width in wells (cellvoyager numeric wells)"),
    )

    MAPPING_FILE = "file_mapping.json"

    def delete_previous_output(self) -> None:
        # the persisted file mapping and merged OME-XML, or a later
        # imextract would silently extract against a stale mapping
        for name in (self.MAPPING_FILE, "experiment.ome.xml"):
            (self.step_dir / name).unlink(missing_ok=True)

    def create_batches(self, args):
        # metadata configuration is one unit of host work
        return [{"source_dir": args["source_dir"]}]

    def run_batch(self, batch: dict) -> dict:
        args = batch["args"]
        src = Path(args["source_dir"])
        if not src.is_dir():
            raise MetadataError(f"source directory not found: {src}")

        # sidecar metadata (CellVoyager .mlf/.mes, companion OME-XML) wins
        # over filename parsing when present — reference metaconfig likewise
        # prefers vendor metadata files over filename heuristics.  An
        # explicit --pattern overrides everything: the user is naming the
        # files to ingest, so sidecars must not widen the selection.
        from tmlibrary_tpu.workflow.steps.vendors import SIDECAR_HANDLERS

        entries: list[dict] | None = None
        skipped = 0
        use_sidecars = not args.get("pattern") and (
            args["handler"] in SIDECAR_HANDLERS or args["handler"] == "auto"
        )
        if use_sidecars:
            from tmlibrary_tpu.workflow.steps.vendors import resolve_sidecars

            is_auto = args["handler"] == "auto"
            names = list(SIDECAR_HANDLERS) if is_auto else [args["handler"]]
            resolved = resolve_sidecars(src, names, is_auto)
            if resolved is not None:
                _, entries, skipped = resolved
        if entries is None and use_sidecars and args["handler"] == "omexml":
            raise MetadataError(f"no companion OME-XML files found under {src}")

        if entries is None:  # filename-pattern fallback
            style = (
                args["handler"]
                if args["handler"] in ("cellvoyager", "incell")
                else "default"
            )
            # --handler auto with no sidecars: try every filename style
            # and keep the one matching the MOST files (InCell and
            # CellVoyager export names cannot match the default pattern;
            # first-match-wins would let one stray default-named file in
            # a vendor export dir shadow the real style)
            styles = (
                [("default", DEFAULT_PATTERN),
                 ("cellvoyager", CELLVOYAGER_PATTERN),
                 ("incell", INCELL_PATTERN)]
                if args["handler"] == "auto" and not args.get("pattern")
                else [(style, args["pattern"] or {
                    "cellvoyager": CELLVOYAGER_PATTERN,
                    "incell": INCELL_PATTERN,
                }.get(style, DEFAULT_PATTERN))]
            )
            files = [p for p in sorted(src.rglob("*")) if p.is_file()]
            entries, skipped = [], len(files)
            for sname, pattern in styles:
                handler = FilenameHandler(pattern, sname, args["plate_cols"])
                cand = []
                for path in files:
                    parsed = handler.parse(path.name)
                    if parsed is None:
                        continue
                    parsed["path"] = str(path)
                    cand.append(parsed)
                if len(cand) > len(entries):
                    entries, skipped = cand, len(files) - len(cand)
        if not entries:
            raise MetadataError(
                f"no files in {src} matched the '{args['handler']}' pattern"
            )
        self._linearise_sites(entries, args)

        manifest = self._build_manifest(entries, args)
        store = ExperimentStore.create(self.store.root, manifest)
        # refresh our store handle's manifest
        self.store.experiment = manifest
        self.store._site_index = store._site_index

        mapping = self._build_mapping(entries, manifest)
        (self.step_dir / self.MAPPING_FILE).write_text(json.dumps(mapping))
        # parity artifact: merged metadata as OME-XML (reference metaconfig
        # normalises everything into OME-XML before layout derivation)
        from tmlibrary_tpu.workflow.steps.omexml import write_ome_xml

        (self.step_dir / "experiment.ome.xml").write_text(write_ome_xml(manifest))
        return {
            "n_files": len(entries),
            "n_skipped": skipped,
            "n_sites": manifest.n_sites,
            "n_channels": manifest.n_channels,
        }

    @staticmethod
    def _linearise_sites(entries: list[dict], args) -> None:
        """Collapse explicit (site_y, site_x) grid coords to linear indices.

        Sidecar handlers emit stage-position-derived grid coordinates;
        filename handlers emit linear indices.  Everything downstream works
        on the linear index + a well grid width.
        """
        if not any("site_y" in e for e in entries):
            if any(e.get("site") is None for e in entries):
                raise MetadataError(
                    "sidecar metadata provided neither site indices nor "
                    "grid coordinates for some images"
                )
            return
        if not all("site_y" in e for e in entries):
            # mixed basis (some records lacked stage positions): grid-derived
            # and field-index site numbers would collide, so fall back to the
            # always-present field index for every entry — unless an entry
            # has no field index at all (grid was its only address).
            if any(e.get("site") is None for e in entries):
                raise MetadataError(
                    "inconsistent site addressing in sidecar metadata: some "
                    "images carry only grid coordinates, others only site "
                    "indices — cannot merge them into one layout"
                )
            for e in entries:
                e.pop("site_y", None)
                e.pop("site_x", None)
            return
        derived = max(e["site_x"] for e in entries) + 1
        explicit = args.get("sites_per_well_x")
        if explicit and explicit < derived:
            raise MetadataError(
                f"sites_per_well_x={explicit} is narrower than the "
                f"stage-position-derived well grid ({derived} columns)"
            )
        spw_x = explicit or derived
        for e in entries:
            e["site"] = e["site_y"] * spw_x + e["site_x"]
        if not explicit:
            args["sites_per_well_x"] = spw_x

    # ------------------------------------------------------------------ build
    def _build_manifest(self, entries: list[dict], args) -> Experiment:
        import cv2

        channels = sorted({e["channel"] for e in entries})
        n_cycles = max(e["cycle"] for e in entries) + 1
        n_tpoints = max(e["tpoint"] for e in entries) + 1
        n_zplanes = max(e["zplane"] for e in entries) + 1

        # site linear index -> (y, x) grid within well
        sites_per_well = max(e["site"] for e in entries) + 1
        spw_x = args["sites_per_well_x"] or int(round(sites_per_well**0.5)) or 1
        spw_y = -(-sites_per_well // spw_x)

        by_plate: dict[str, set[tuple[int, int]]] = defaultdict(set)
        for e in entries:
            by_plate[e["plate"]].add((e["well_row"], e["well_col"]))

        site_objs = tuple(
            Site(y=i // spw_x, x=i % spw_x) for i in range(sites_per_well)
        )
        plates = [
            Plate(
                name=pname,
                wells=tuple(
                    Well(row=r, column=c, sites=site_objs)
                    for r, c in sorted(wells)
                ),
            )
            for pname, wells in sorted(by_plate.items())
        ]

        probe_path = entries[0]["path"]
        # container formats (nd2/czi/lif) carry their own dimensions
        from tmlibrary_tpu.readers import container_dimensions

        dims = container_dimensions(probe_path)
        if dims is not None:
            h, w = dims
        else:
            probe = cv2.imread(probe_path, cv2.IMREAD_UNCHANGED)
            if probe is None:
                raise MetadataError(f"cannot read probe image {probe_path}")
            h, w = probe.shape[:2]

        return Experiment(
            name=self.store.experiment.name,
            plates=plates,
            channels=[Channel(index=i, name=n) for i, n in enumerate(channels)],
            site_height=int(h),
            site_width=int(w),
            n_cycles=n_cycles,
            n_tpoints=n_tpoints,
            n_zplanes=n_zplanes,
        )

    def _build_mapping(self, entries: list[dict], manifest: Experiment) -> list[dict]:
        """Reference ``ImageFileMapping``: file path → store coordinates."""
        channel_index = {c.name: c.index for c in manifest.channels}
        spw_x = max(s.x for p in manifest.plates for w in p.wells for s in w.sites) + 1
        from tmlibrary_tpu.models.experiment import SiteRef

        mapping = []
        for e in entries:
            ref = SiteRef(
                plate=e["plate"],
                well_row=e["well_row"],
                well_column=e["well_col"],
                site_y=e["site"] // spw_x,
                site_x=e["site"] % spw_x,
            )
            rec = {
                "path": e["path"],
                "site_index": self.store.site_linear_index(ref),
                "cycle": e["cycle"],
                "channel": channel_index[e["channel"]],
                "tpoint": e["tpoint"],
                "zplane": e["zplane"],
            }
            if "page" in e:  # multi-page OME-TIFF plane
                rec["page"] = e["page"]
            mapping.append(rec)
        return mapping

    def load_mapping(self) -> list[dict]:
        path = self.step_dir / self.MAPPING_FILE
        if not path.exists():
            raise MetadataError("file mapping missing — run metaconfig first")
        return json.loads(path.read_text())
