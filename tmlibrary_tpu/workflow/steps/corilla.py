"""corilla: online illumination statistics per channel.

Reference parity: ``tmlib/workflow/corilla/api.py``
``IlluminationStatisticsCalculator`` — one run job per channel folding every
site through ``OnlineStatistics`` and writing an ``IllumstatsFile``
(SURVEY.md §4.4).

TPU execution: sites stream through ``lax.scan`` in device-resident chunks
(bounded HBM) with the Welford carry living on device across chunks; on a
multi-chip mesh the site axis shards and shard states merge with the
parallel-variance fold (``tmlibrary_tpu.parallel.stats``).  The metric is
channels/sec (BASELINE.json).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.ops.stats import (
    welford_finalize,
    welford_init,
    welford_merge,
    welford_scan,
)
from tmlibrary_tpu.parallel.mesh import shard_batch, site_mesh
from tmlibrary_tpu.parallel.stats import sharded_welford
from tmlibrary_tpu.utils import create_partitions
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.pipelined import prefetch_iter
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step

import functools


@functools.lru_cache(maxsize=1)
def _welford_scan_jit():
    """Shared jit wrapper: a per-run ``jax.jit(welford_scan)`` would
    re-trace every chunk shape on every step instance (re-run overhead
    measured by the workflow bench)."""
    return jax.jit(welford_scan)


@functools.lru_cache(maxsize=1)
def _welford_merge_jit():
    return jax.jit(welford_merge)


@register_step("corilla")
class IlluminationStatisticsCalculator(Step):
    batch_args = ArgumentCollection(
        Argument("chunk_size", int, default=32,
                 help="sites per device-resident chunk"),
        Argument("n_devices", int, default=0,
                 help="mesh size (0 = all visible devices)"),
        Argument("smooth_sigma", float, default=0.0,
                 help="pre-smooth stat fields before storing (0 = off)"),
        Argument("prefetch_chunks", int, default=2,
                 help="site chunks read ahead on worker threads while the "
                      "device scans the current chunk (1 = sequential)"),
    )

    def create_batches(self, args):
        # one batch per (cycle, channel), exactly the reference's job split
        exp = self.store.experiment
        return [
            {"cycle": cycle, "channel": ch.index}
            for cycle in range(exp.n_cycles)
            for ch in exp.channels
            if self.store.has_plane(cycle=cycle, channel=ch.index)
        ]

    def run_batch(self, batch: dict) -> dict:
        import time

        from tmlibrary_tpu import telemetry

        bt0 = time.perf_counter()
        args = batch["args"]
        cycle, channel = batch["cycle"], batch["channel"]
        exp = self.store.experiment
        n_sites = self.store.n_sites
        n_dev = args["n_devices"] or len(jax.devices())
        n_dev = min(n_dev, len(jax.devices()))
        chunk = max(args["chunk_size"], 1)

        site_indices = list(range(n_sites))
        state = None

        if n_dev > 1:
            mesh = site_mesh(n_dev)
            # largest site prefix divisible by the mesh; remainder scans below
            even = n_sites - n_sites % n_dev
            if even:
                stack = self.store.read_sites(site_indices[:even], cycle=cycle,
                                              channel=channel)
                state = jax.tree.map(
                    np.asarray, sharded_welford(shard_batch(jnp.asarray(stack), mesh), mesh)
                )
                site_indices = site_indices[even:]

        scan_jit = _welford_scan_jit()
        merge_jit = _welford_merge_jit()
        dev_state = None
        # store reads for chunk N+1 run on prefetch workers while the
        # device scans chunk N; prefetch_iter preserves chunk order, so
        # the Welford merge chain (order-sensitive in floating point) is
        # bit-identical to the sequential loop
        chunks = create_partitions(site_indices, chunk)
        loaded = prefetch_iter(
            chunks,
            lambda part: self.store.read_sites(part, cycle=cycle,
                                               channel=channel),
            depth=max(args.get("prefetch_chunks", 2), 1),
        )
        for stack in loaded:
            if dev_state is None:
                dev_state = scan_jit(jnp.asarray(stack))
            else:
                dev_state = merge_jit(dev_state, scan_jit(jnp.asarray(stack)))
        if dev_state is not None:
            state = (
                jax.tree.map(np.asarray, dev_state)
                if state is None
                else jax.tree.map(
                    np.asarray,
                    merge_jit(
                        jax.tree.map(jnp.asarray, state),
                        jax.tree.map(jnp.asarray, dev_state),
                    ),
                )
            )
        if state is None:
            state = jax.tree.map(np.asarray, welford_init((exp.site_height, exp.site_width)))

        out = jax.tree.map(np.asarray, welford_finalize(jax.tree.map(jnp.asarray, state)))
        if args["smooth_sigma"] > 0:
            from tmlibrary_tpu.ops.smooth import gaussian_smooth

            out["mean_log"] = np.asarray(
                gaussian_smooth(out["mean_log"], args["smooth_sigma"])
            )
            out["std_log"] = np.asarray(
                gaussian_smooth(out["std_log"], args["smooth_sigma"])
            )
        # the finalize already inverted exact raw-intensity percentiles
        # from the Welford histogram — hand them to the QC session (one
        # no-op call when QC is off) so the run profile records each
        # channel's acquisition dynamic range for free
        from tmlibrary_tpu import qc as qc_mod

        ch_name = next(
            (c.name for c in exp.channels if c.index == channel),
            str(channel),
        )
        qc_mod.get_session().observe_illumination(
            ch_name, out["percentile_keys"], out["percentile_values"]
        )
        out.pop("hist", None)
        self.store.write_illumstats(out, cycle=cycle, channel=channel)
        # one batch == one channel; same perf_counter wall-time math as
        # bench.py's channels/sec metric (BASELINE.json)
        telemetry.get_registry().throughput(
            "tmx_corilla_channels_per_sec"
        ).add(1, time.perf_counter() - bt0)
        return {"cycle": cycle, "channel": channel, "n_sites": int(out["n"])}

    def delete_previous_output(self) -> None:
        for p in (self.store.root / "illumstats").glob("*.npz"):
            p.unlink()
