"""jterator: run the image-analysis pipeline over all sites.

Reference parity: ``tmlib/workflow/jterator/api.py`` ``ImageAnalysisPipeline``
— ``create_run_batches`` groups sites by ``batch_size``; ``run_job`` loads
channel images (correct + align), runs the module chain per site, registers
segmented objects (label images → PostGIS polygons) and persists feature
values (SURVEY.md §4.3 — THE hot path).

TPU execution: one compiled program per experiment geometry
(jit(vmap(chain))); a batch of sites is one device dispatch, sharded over
the mesh when more than one chip is visible.  Outputs: label stacks in the
segmentation store, feature Parquet shards (idempotent per batch), optional
host-traced polygons.  Metric: sites/sec/chip (BASELINE.json).
"""

from __future__ import annotations

import threading
import time

import numpy as np

import logging

from tmlibrary_tpu import telemetry
from tmlibrary_tpu.errors import PipelineError, StoreError
from tmlibrary_tpu.models.image import IllumstatsContainer
from tmlibrary_tpu.utils import create_partitions
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step

logger = logging.getLogger(__name__)


def _mosaic_intensity_stats(labels, vals_mosaic, count):
    """Ragged per-object intensity accumulators over a mosaic:
    (sum, sq_sum, min, max), each ``(count + 1,)`` with index 0 =
    background.  ONE native C pass (``tm_mosaic_intensity``) with a
    chunked-vectorized numpy fallback — no O(H) interpreter loop on a
    plate-scale mosaic (round-3 VERDICT weak #4)."""
    from tmlibrary_tpu import native as native_mod

    return native_mod.mosaic_intensity_host(labels, vals_mosaic, count)


_CORRECT_JIT = None


def _well_shard(batch: dict) -> str:
    """The ONE home of the per-well shard token used by feature-table
    shards, polygon filenames and figure filenames alike."""
    plate, well_row, well_col = batch["well"]
    return f"well_{plate}_{well_row:02d}_{well_col:02d}"


def _best_spatial_grid(requested: int, hm: int, wm: int) -> tuple[int, int]:
    """Largest ``nr * nc <= requested`` with ``nr`` dividing the mosaic
    rows and ``nc`` the columns; equal products prefer more rows (the
    1-D-like shape, fewer seam axes)."""
    best = (1, 1)
    for nr in range(requested, 0, -1):
        if hm % nr:
            continue
        cap = requested // nr
        nc = next(k for k in range(cap, 0, -1) if wm % k == 0)
        if nr * nc > best[0] * best[1]:
            best = (nr, nc)
    return best


def _correct_batch(imgs, mean_log, std_log) -> "np.ndarray":
    """Batched illumination correction, jitted ONCE (per shape) — a
    per-well closure would recompile the same elementwise program for
    every well of the plate."""
    import jax
    import jax.numpy as jnp

    from tmlibrary_tpu.ops import image_ops

    global _CORRECT_JIT
    if _CORRECT_JIT is None:
        _CORRECT_JIT = jax.jit(
            jax.vmap(image_ops.correct_illumination, in_axes=(0, None, None))
        )
    return np.asarray(
        _CORRECT_JIT(
            jnp.asarray(imgs, jnp.float32),
            jnp.asarray(mean_log),
            jnp.asarray(std_log),
        )
    )


def _host_shift(img: np.ndarray, dy: int, dx: int) -> np.ndarray:
    """Integer translate with zero fill — host twin of ops.image_ops.shift_image."""
    out = np.roll(img, (int(dy), int(dx)), axis=(0, 1))
    h, w = out.shape
    if dy > 0:
        out[:dy, :] = 0
    elif dy < 0:
        out[h + dy:, :] = 0
    if dx > 0:
        out[:, :dx] = 0
    elif dx < 0:
        out[:, w + dx:] = 0
    return out


@register_step("jterator")
class ImageAnalysisRunner(Step):
    batch_args = ArgumentCollection(
        Argument("pipe", str, default="",
                 help="path to the .pipe.yaml pipeline description "
                      "(required for --layout sites)"),
        Argument("layout", str, default="sites", choices=("sites", "spatial"),
                 help="'sites': vmap the module chain over per-site batches; "
                      "'spatial': stitch each well into one mosaic, row-shard "
                      "it over the device mesh and segment it with halo "
                      "exchange + distributed connected components — objects "
                      "crossing site borders get ONE id (the reference splits "
                      "them, SURVEY.md §6 long-context row)"),
        Argument("spatial_channel", str, default="",
                 help="channel segmented in spatial layout "
                      "(default: first experiment channel)"),
        Argument("spatial_sigma", float, default=1.5,
                 help="gaussian sigma for spatial-layout smoothing"),
        Argument("spatial_grid", str, default="auto",
                 choices=("auto", "rows", "grid"),
                 help="spatial-layout mesh shape: 'rows' shards the mosaic "
                      "row axis 1-D; 'grid' tiles it rows x cols (2-D halo "
                      "exchange, corner-exact seams); 'auto' picks whichever "
                      "uses more devices — results are identical either way"),
        Argument("spatial_objects", str, default="mosaic_cells",
                 help="objects name for spatial-layout segmentation output"),
        Argument("spatial_zernike_degree", int, default=9,
                 help="Zernike moment degree for spatial-layout features "
                      "(matches measure_zernike's default; 0 disables)"),
        Argument("spatial_secondary_channel", str, default="",
                 help="grow secondary objects (cells) from the primary "
                      "mosaic objects through THIS channel via distributed "
                      "watershed — ids stay the primary's global ids "
                      "(empty: disabled)"),
        Argument("spatial_secondary_objects", str, default="mosaic_secondary",
                 help="objects name for the spatial secondary segmentation"),
        Argument("spatial_secondary_factor", float, default=1.0,
                 help="otsu correction factor for the secondary mask "
                      "(segment_secondary's correction_factor)"),
        Argument("spatial_secondary_levels", int, default=32,
                 help="watershed flooding levels for the secondary mask "
                      "(segment_secondary's n_levels)"),
        Argument("spatial_align", bool, default=True,
                 help="apply align-step shifts when stitching (the sites "
                      "layout gates this per pipe channel; disable if the "
                      "stored registration is untrusted)"),
        Argument("batch_size", int, default=0,
                 help="sites per device batch (0 = auto: the tuning "
                      "sweep's best_batch on device backends, else 32)"),
        Argument("max_objects", int, default=256,
                 help="static per-site object capacity"),
        Argument("object_buckets", str, default="auto",
                 help="object-capacity bucket ladder (capacity.py): "
                      "'auto' compiles power-of-two buckets up to "
                      "max_objects and routes each batch by observed "
                      "object counts; 'off' pins every batch at "
                      "max_objects; or an explicit comma list of "
                      "capacities, e.g. '8,32'. Results are bit-identical "
                      "across bucket choices — routing is purely a "
                      "performance decision"),
        Argument("schedule", str, default="auto",
                 choices=("auto", "pack", "off"),
                 help="work-aware site scheduling (workflow/schedule.py): "
                      "'pack' plans cost-model batches (rung-homogeneous "
                      "packing + straggler-balanced shard order) from the "
                      "per-site count history; 'off' keeps directory-order "
                      "batching; 'auto' follows TMX_SCHEDULE / config / "
                      "the tuned verdict, then packs. Results are "
                      "bit-identical per site either way — scheduling is "
                      "purely a performance decision"),
        Argument("reduction_strategy", str, default="auto",
                 choices=("auto", "onehot", "sort", "scatter", "fused"),
                 help="grouped-reduction strategy for the measurement "
                      "stack (ops/reduction.py): one-hot MXU matmuls, "
                      "deterministic sort+segment reductions, direct "
                      "scatters, or the single-pass Pallas measure "
                      "megakernels (ops/fused_measure.py); 'auto' "
                      "follows TMX_REDUCTION_STRATEGY / config / the "
                      "tuned verdict, then a backend-safe default"),
        Argument("donate_buffers", bool, default=True,
                 help="donate each batch's raw-image/stats/shift device "
                      "buffers to the compiled program so XLA reuses "
                      "their memory for outputs (safe: the engine "
                      "transfers fresh arrays per batch)"),
        Argument("auto_resegment", bool, default=True,
                 help="collect re-runs saturated batches at doubled "
                      "max_objects (bounded at 4096) until counts fit; "
                      "disable to keep the manual warn-and-rerun flow"),
        Argument("n_devices", int, default=0, help="mesh size (0 = all)"),
        Argument("cycle", int, default=0),
        Argument("tpoint", int, default=0),
        Argument("zplane", int, default=0),
        Argument("as_polygons", bool, default=False,
                 help="also trace object outlines host-side"),
        Argument("figures", bool, default=False,
                 help="write segmentation-overlay PNGs: per site in the "
                      "sites layout, one downsampled whole-well mosaic per "
                      "object family in the spatial layout (reference: "
                      "jterator module plot/Figure artifacts)"),
    )

    def __init__(self, store):
        super().__init__(store)
        # (capacity, qc gate) -> compiled batch fn: the bucket router
        # compiles one program per object-capacity bucket it actually
        # routes to (each is also process-cached in
        # jterator.pipeline.cached_batch_fn)
        self._compiled: dict[tuple, object] = {}
        self._desc = None
        self._window: tuple[int, int, int, int] | None = None
        self._window_resolved = False
        # prefetch workers read the pipeline description (and the figures
        # path re-resolves the compiled program) concurrently with the
        # main thread's launch; the lock keeps the compile cache coherent
        # when two threads race on different capacities
        self._compile_lock = threading.Lock()
        # bucket routing reads/writes the process-level per-program
        # peak-count history (capacity.note_observed_peak) — scoped by
        # compiled-program key so a long-lived serve process interleaving
        # tenants with different object densities never thrashes another
        # experiment's capacity-rung choices.  The lock only guards this
        # instance's memoized routing-key table (persist runs on the
        # pipelined executor's worker thread while launch runs on the
        # engine's).
        self._bucket_lock = threading.Lock()
        self._routing_keys: dict[tuple, str] = {}

    def create_batches(self, args):
        if args["layout"] == "spatial":
            # one batch per well: the well mosaic is the sharding unit
            wells: dict[tuple, list[int]] = {}
            for i, r in enumerate(self.store.experiment.sites()):
                key = (r.plate, r.well_row, r.well_column)
                wells.setdefault(key, []).append(i)
            return [
                {"sites": idxs, "well": list(key)}
                for key, idxs in sorted(wells.items())
            ]
        if not args["pipe"]:
            raise ValueError("--pipe is required for --layout sites")
        sites = list(range(self.store.n_sites))
        batch_size = args["batch_size"] or self._auto_batch_size()
        plan = self._schedule_plan(args, sites, batch_size)
        if plan is not None:
            from tmlibrary_tpu.workflow import schedule as schedule_mod

            schedule_mod.write_plan(self._schedule_plan_path, plan)
            return [
                {
                    "sites": b["sites"],
                    "schedule": {
                        "rung": b["rung"],
                        "predicted": b["predicted"],
                        "shard_work": b["shard_work"],
                        "shard_work_naive": b["shard_work_naive"],
                        "plan_digest": plan["digest"],
                    },
                }
                for b in plan["batches"]
            ]
        return [
            {"sites": part} for part in create_partitions(sites, batch_size)
        ]

    def init(self, args=None):
        """Harvest the PREVIOUS run's persisted per-site object counts
        into the scheduler's cost model before ``delete_previous_output``
        wipes the feature shards they live in — the predictor's seed for
        a fresh process planning over a previously-analyzed experiment."""
        resolved = self.batch_args.resolve(args)
        if resolved.get("layout", "sites") == "sites" and resolved.get("pipe"):
            self._seed_schedule_history(resolved)
        return super().init(args)

    def _seed_schedule_history(self, args) -> None:
        from tmlibrary_tpu.workflow import schedule as schedule_mod

        try:
            mode, _ = schedule_mod.resolve_schedule(args.get("schedule"))
            if not schedule_mod.schedule_enabled(mode):
                return
            counts = schedule_mod.harvest_store_counts(self.store)
            if not counts:
                return
            from tmlibrary_tpu.capacity import (
                resolve_bucket_ladder,
                seed_site_counts,
            )

            ceiling = int(args["max_objects"])
            ladder = resolve_bucket_ladder(
                ceiling, args.get("object_buckets", "auto")
            )
            seeded = seed_site_counts(
                self._routing_key(args, ceiling, ladder), counts
            )
            if seeded:
                logger.info(
                    "schedule: seeded %d site cost(s) from persisted "
                    "feature shards", seeded,
                )
        except Exception:
            # the cost model is a performance input, never a planning
            # dependency — a broken harvest degrades to the prior
            logger.debug("schedule history harvest failed", exc_info=True)

    def _schedule_plan(self, args, sites: list, batch_size: int):
        """The work-model packing plan for a sites-layout run, or None
        when scheduling is off (or the run is too small to pack)."""
        from tmlibrary_tpu.workflow import schedule as schedule_mod

        mode, source = schedule_mod.resolve_schedule(args.get("schedule"))
        if not schedule_mod.schedule_enabled(mode) or len(sites) <= 1:
            schedule_mod.write_plan(self._schedule_plan_path, None)
            return None
        import jax

        from tmlibrary_tpu.capacity import (
            observed_peak,
            resolve_bucket_ladder,
        )
        from tmlibrary_tpu.jterator.pipeline import description_digest

        ceiling = int(args["max_objects"])
        ladder = resolve_bucket_ladder(
            ceiling, args.get("object_buckets", "auto")
        )
        key = self._routing_key(args, ceiling, ladder)
        from tmlibrary_tpu.capacity import site_count_snapshot

        table = site_count_snapshot(key)
        peak = observed_peak(key)
        if not table and peak is None:
            # true cold start: no per-site history AND no program-family
            # peak.  A uniform prediction cannot beat directory order,
            # and pinning a guessed rung would mint compiles the
            # unpacked run never pays — degenerate to no plan (classic
            # ladder[0]-and-escalate routing) until history exists.
            schedule_mod.write_plan(self._schedule_plan_path, None)
            return None
        # prior for sites with no history: the routing-key peak when one
        # exists, else the densest harvested site (conservative)
        prior = float(peak) if peak is not None else float(max(table.values()))
        predicted = schedule_mod.predict_site_counts(key, sites, prior)
        n_dev = args["n_devices"] or len(jax.devices())
        n_dev = min(int(n_dev), len(jax.devices()))
        return schedule_mod.pack_plan(
            sites, predicted, batch_size, ladder, n_dev,
            seed=description_digest(self._description(args)),
            mode=mode, source=source,
        )

    @staticmethod
    def _auto_batch_size() -> int:
        """``batch_size=0``: the hardware-swept ``best_batch`` on device
        backends (the sweep measured the device, so a CPU run keeps the
        static default)."""
        import jax

        if jax.default_backend() != "cpu":
            from tmlibrary_tpu.tuning import tuned_batch_size

            tuned = tuned_batch_size()
            if tuned:
                logger.info(
                    "batch_size auto: %d sites/batch (source: tuning "
                    "best_batch)", tuned,
                )
                return tuned
        return 32

    # ---------------------------------------------------------------- compile
    def _description(self, args):
        """The parsed pipeline description alone — prefetch workers need
        the channel/object lists to plan store reads without forcing a
        compile on their thread."""
        from pathlib import Path

        from tmlibrary_tpu.jterator.description import PipelineDescription

        with self._compile_lock:
            if self._desc is None:
                pipe_path = Path(args["pipe"])
                if not pipe_path.is_absolute():
                    pipe_path = self.store.root / pipe_path
                self._desc = PipelineDescription.load(pipe_path)
            return self._desc

    def _pipeline(self, args, capacity: int | None = None):
        """The compiled batch program for ``capacity`` (default: the
        ``max_objects`` ceiling).  One entry per object-capacity bucket —
        the router picks the capacity at launch time, and collect's
        auto-resegmentation re-runs a batch at a doubled ceiling, so the
        cache is keyed by the cap a program was actually built for."""
        self._description(args)
        cap = int(capacity if capacity is not None else args["max_objects"])
        from tmlibrary_tpu import qc as qc_mod

        # the QC gate joins the instance cache key: a QC-on program
        # returns (SiteResult, qc_stats) instead of a bare SiteResult,
        # so a mid-process gate flip (tests, tools) must never reuse a
        # program built for the other shape
        qc_on = qc_mod.enabled()
        cache_key = (cap, qc_on)
        with self._compile_lock:
            if cache_key not in self._compiled:
                # aligned multiplexing experiments crop every channel to the
                # inter-cycle intersection (reference SiteIntersection); the
                # window is experiment-static, so it compiles into the program
                if not self._window_resolved:
                    if any(ch.align for ch in self._desc.channels):
                        try:
                            w = self.store.read_intersection()
                            self._window = (w["top"], w["bottom"],
                                            w["left"], w["right"])
                        except StoreError:
                            self._window = None  # align step didn't run: no crop
                        if self._window == (0, 0, 0, 0):
                            self._window = None
                    self._window_resolved = True
                # process-level cache: a re-built Step (fresh Workflow, engine
                # re-run, tool request) running the same description reuses
                # the traced+compiled program instead of re-paying trace+load
                from tmlibrary_tpu.jterator.pipeline import (
                    cached_batch_fn,
                    weight_digests,
                )

                # checkpoint provenance, once per step: the resolved
                # weight content digests this run's programs compiled
                # against (the same digests keying the program cache)
                digests = weight_digests(self._desc)
                if digests and not getattr(self, "_weights_logged", False):
                    self._weights_logged = True
                    logger.info(
                        "model weights resolved: %s",
                        "; ".join(f"{m} {s} @{d}" for m, s, d in digests),
                    )

                self._compiled[cache_key] = cached_batch_fn(
                    self._desc, cap, self._window,
                    # arg True defers to the config default (so
                    # TM_DONATE_BUFFERS=0 still disables it); arg False
                    # forces donation off for this run
                    donate=None if args.get("donate_buffers", True) else False,
                    reduction_strategy=args.get("reduction_strategy", "auto"),
                    qc=qc_on,
                )
            return self._desc, self._compiled[cache_key]

    # -------------------------------------------------------------------- run
    def _effective_batch(self, batch: dict) -> dict:
        """Fold in collect's auto-resegmentation cap escalation.  The
        override lives in a SIDE file rather than a rewritten
        batch_*.json: the engine's resume staleness check compares
        planned batch args against the description's, and a rewritten
        cap would read as "args changed" and trigger a from-scratch
        re-plan that wipes every output."""
        override = self._cap_overrides().get(str(batch["index"]))
        if override and override > batch["args"].get("max_objects", 0):
            return {**batch, "args": {**batch["args"],
                                      "max_objects": int(override)}}
        return batch

    def _route_capacity(self, batch: dict) -> int:
        """Pick the object-capacity bucket for a batch at launch time.

        Ordering matters for the pipelined executor: routing happens on
        the engine thread at launch, reading the peak per-site count the
        persist worker has recorded so far — the first batch has no
        history, so it starts from the hardware-swept capacity verdict
        (``TUNING.json``) when one is on the ladder, else the ladder's
        smallest bucket.  A mis-route only costs a re-launch one bucket
        up (:meth:`_persist` escalates before persisting), never a
        wrong result."""
        args = batch["args"]
        ceiling = int(args["max_objects"])
        from tmlibrary_tpu.capacity import resolve_bucket_ladder, select_capacity

        ladder = resolve_bucket_ladder(
            ceiling, args.get("object_buckets", "auto")
        )
        if len(ladder) == 1:
            return ceiling
        # a packed batch routes to its PLANNED rung: the whole point of
        # rung-homogeneous packing is that a sparse batch stops paying
        # for the global peak.  Under-prediction only costs the existing
        # escalation re-launch (_persist), never a wrong result.
        planned = (batch.get("schedule") or {}).get("rung")
        if planned and int(planned) in ladder:
            return int(planned)
        from tmlibrary_tpu.capacity import observed_peak

        observed = observed_peak(self._routing_key(args, ceiling, ladder))
        if observed is None:
            from tmlibrary_tpu.tuning import tuned_object_capacity

            hint = tuned_object_capacity()
            if hint and hint in ladder:
                return int(hint)
            return ladder[0]
        return select_capacity(observed, ladder)

    def _routing_key(self, args, ceiling: int,
                     ladder: tuple[int, ...]) -> str:
        """The compiled-program-family key scoping this step's bucket
        history (memoized per (ceiling, ladder) — the description digest
        is instance-stable)."""
        from tmlibrary_tpu.capacity import routing_key
        from tmlibrary_tpu.jterator.pipeline import description_digest

        desc = self._description(args)
        cache_key = (int(ceiling), tuple(ladder))
        with self._bucket_lock:
            key = self._routing_keys.get(cache_key)
            if key is None:
                key = routing_key(description_digest(desc), ceiling, ladder)
                self._routing_keys[cache_key] = key
            return key

    def _note_peak(self, args, peak: int) -> None:
        """Feed one batch's peak per-site object count into the
        per-program routing history (persist-worker side)."""
        from tmlibrary_tpu.capacity import (
            note_observed_peak,
            resolve_bucket_ladder,
        )

        ceiling = int(args["max_objects"])
        ladder = resolve_bucket_ladder(
            ceiling, args.get("object_buckets", "auto")
        )
        note_observed_peak(self._routing_key(args, ceiling, ladder), peak)

    def _note_site_costs(self, args, sites, site_counts) -> None:
        """Feed one batch's per-site peak object counts into the work
        model's EWMA history (persist-worker side, same stream as
        :meth:`_note_peak`).  Fed unconditionally — a schedule-off run
        still builds the history a later packed run predicts from."""
        try:
            from tmlibrary_tpu.capacity import (
                note_site_counts,
                resolve_bucket_ladder,
            )

            ceiling = int(args["max_objects"])
            ladder = resolve_bucket_ladder(
                ceiling, args.get("object_buckets", "auto")
            )
            note_site_counts(
                self._routing_key(args, ceiling, ladder),
                {int(s): float(c) for s, c in zip(sites, site_counts)},
            )
        except Exception:
            logger.debug("site-cost history update failed", exc_info=True)

    def _shard_objects(self, args, site_counts) -> "list[int] | None":
        """Actual per-shard object totals under the leading-axis slicing
        :meth:`_load_inputs` applies (ceil-width chunks; padding lanes
        are appended at the END and their recomputed objects are dropped
        on export, so they count zero here).  None on a 1-device mesh —
        there is no skew to report."""
        try:
            import jax

            n_dev = int(args["n_devices"] or len(jax.devices()))
            n_dev = min(n_dev, len(jax.devices()))
        except Exception:
            return None
        n = len(site_counts)
        if n_dev <= 1 or n == 0:
            return None
        chunk = -(-n // n_dev)
        arr = np.asarray(site_counts)
        return [
            int(arr[s * chunk:(s + 1) * chunk].sum()) for s in range(n_dev)
        ]

    def _note_schedule(self, escalations: int) -> None:
        """Plan-accounting counters: batches dispatched under a schedule
        plan, and plan hits (the planned rung held without an escalation
        re-launch) — the prediction-quality signal ``tmx top``'s PACK
        row and ``tmx perf`` read."""
        if not telemetry.enabled():
            return
        reg = telemetry.get_registry()
        reg.counter("tmx_schedule_batches_total").inc()
        if not escalations:
            reg.counter("tmx_schedule_plan_hit_total").inc()

    def run_batch(self, batch: dict) -> dict:
        self._mark_work_start()
        batch = self._effective_batch(batch)
        # .get: batch JSONs persisted by a pre-layout init lack the key
        if batch["args"].get("layout", "sites") == "spatial":
            return self._run_spatial(batch)
        cap = self._route_capacity(batch)
        result = self._launch(batch, capacity=cap)
        return self._persist(batch, result, capacity=cap)

    # -------------------------------------------------- throughput gauge
    # sites/sec over cumulative wall time since the first batch — the same
    # total-units / perf_counter-wall math bench.py's
    # jterator_*_sites_per_sec metrics use, so the live gauge converges to
    # the bench figure for the same workload (pipelined overlap included)
    def _mark_work_start(self) -> None:
        if telemetry.enabled() and getattr(self, "_sites_t0", None) is None:
            self._sites_lock = threading.Lock()
            self._sites_t0 = time.perf_counter()
            self._sites_done = 0

    def _note_sites(self, n: int) -> None:
        if not telemetry.enabled() or getattr(self, "_sites_t0", None) is None:
            return
        with self._sites_lock:
            self._sites_done += int(n)
            elapsed = time.perf_counter() - self._sites_t0
            done = self._sites_done
        reg = telemetry.get_registry()
        reg.counter("tmx_jterator_sites_total").inc(n)
        if elapsed > 0:
            reg.gauge("tmx_jterator_sites_per_sec").set(done / elapsed)

    def _note_bucket(
        self, cap: int, ceiling: int, objects: int, slots: int,
        escalations: int,
    ) -> None:
        """Bucket-router telemetry: routed/saturated counters plus the
        run-cumulative slot-occupancy and padded-FLOPs-avoided gauges
        (the per-object measure FLOPs scale with the capacity, so the
        slot ratio routed/ceiling IS the padded-work fraction saved)."""
        if not telemetry.enabled():
            return
        reg = telemetry.get_registry()
        reg.counter(
            "tmx_jterator_bucket_routed_total", capacity=str(cap)
        ).inc()
        if escalations:
            reg.counter("tmx_jterator_bucket_saturated_total").inc(escalations)
        from tmlibrary_tpu.capacity import ceiling_slots

        with self._bucket_lock:
            self._occ_objects = getattr(self, "_occ_objects", 0) + objects
            self._occ_slots = getattr(self, "_occ_slots", 0) + slots
            self._occ_ceiling_slots = (
                getattr(self, "_occ_ceiling_slots", 0)
                + ceiling_slots(slots, cap, ceiling)
            )
            occ_o, occ_s, occ_c = (
                self._occ_objects, self._occ_slots, self._occ_ceiling_slots
            )
        if occ_s:
            reg.gauge("tmx_jterator_slot_occupancy").set(occ_o / occ_s)
        if occ_c:
            reg.gauge("tmx_jterator_padded_flops_avoided_frac").set(
                1.0 - occ_s / occ_c
            )

    # ------------------------------------------------- launch/persist split
    # (the pipelined executor's step protocol — workflow/pipelined.py)
    def prefetch_batch(self, batch: dict):
        """Host-side input loading only (store reads, illumstats, shift
        tables, mosaic stitching) — safe on a prefetch worker thread."""
        batch = self._effective_batch(batch)
        if batch["args"].get("layout", "sites") == "spatial":
            return self._prefetch_spatial(batch)
        return self._load_inputs(batch)

    def launch_batch(self, batch: dict, prefetched=None):
        """Async device dispatch; returns ``(effective_batch, ctx)`` with
        un-fetched device arrays inside ``ctx``."""
        self._mark_work_start()
        batch = self._effective_batch(batch)
        if batch["args"].get("layout", "sites") == "spatial":
            return batch, ("spatial", self._launch_spatial(batch, prefetched))
        cap = self._route_capacity(batch)
        # meta travels alongside the device arrays so block_batch can stamp
        # per-device completion times against the true dispatch instant
        meta = {"t0": time.perf_counter(), "index": batch.get("index")}
        plan = batch.get("schedule") or {}
        if plan.get("shard_work"):
            # predicted per-shard work rides to the telemetry/ledger
            # surfaces so the anomaly plane can tell data skew (predicted
            # AND actual both skewed) from a slow device (actual only)
            meta["predicted_shard_work"] = [
                float(w) for w in plan["shard_work"]
            ]
        return batch, (
            "sites",
            (self._launch(batch, prefetched, capacity=cap), cap, meta),
        )

    def block_batch(self, ctx) -> None:
        """Wait for the launched device arrays (distinct pipeline-stats
        phase from the persist writes that follow)."""
        import jax

        kind, payload = ctx
        if kind == "sites":
            meta = payload[2] if len(payload) > 2 else None
            if meta is not None and telemetry.enabled():
                times = telemetry.device_wall_times(payload[0], meta["t0"])
                if len(times) > 1:
                    meta["device_times"] = times
                    meta["skew"] = telemetry.record_device_times(
                        times, step=self.name, batch=meta.get("index"),
                        predicted=meta.get("predicted_shard_work"),
                    )
            # SiteResult is a registered pytree: block on all leaves
            jax.block_until_ready(payload[0])
            return
        jax.block_until_ready(payload["labels_dev"])
        jax.block_until_ready(payload["count_dev"])
        if payload["sec"] is not None:
            jax.block_until_ready(payload["sec"][2])

    def persist_batch(self, batch: dict, ctx) -> dict:
        """Fetch + write one launched batch (the effective batch from
        :meth:`launch_batch`)."""
        kind, payload = ctx
        if kind == "spatial":
            return self._persist_spatial(batch, payload)
        result, cap = payload[0], payload[1]
        meta = payload[2] if len(payload) > 2 else None
        out = self._persist(batch, result, capacity=cap)
        if meta and meta.get("device_times"):
            # ride the batch summary so the ledger's batch_done record (and
            # registry_from_ledger) carry device provenance; the ledger
            # append itself stays on the engine thread
            out["device_wall_times"] = {
                d: round(float(t), 6) for d, t in meta["device_times"]
            }
            out["straggler_skew_s"] = round(float(meta.get("skew", 0.0)), 6)
        if meta and meta.get("predicted_shard_work"):
            pred = [round(float(w), 3) for w in meta["predicted_shard_work"]]
            out["predicted_shard_work"] = pred
            out["predicted_skew"] = round(max(pred) - min(pred), 3)
        return out

    # ------------------------------------------------------------ spatial run
    def _stitched_channel(
        self, sites, srefs, ch_index, args, n_sy, n_sx, h, w
    ) -> "np.ndarray":
        """One channel's well mosaic, illumination-corrected when corilla
        statistics exist and cycle-aligned when the align step stored
        shifts for this cycle (the same correct+align prep the sites
        layout applies — the two layouts must see the same pixels).
        Alignment is shift-only: the per-site intersection crop cannot
        apply at mosaic scale (it would shrink tiles out of the grid), so
        shifted-in edges are zero-filled exactly like the sites path's
        ``shift_image``."""
        imgs = self.store.read_sites(
            sites, cycle=args["cycle"], channel=ch_index,
            tpoint=args["tpoint"], zplane=args["zplane"],
        )
        if self.store.has_illumstats(cycle=args["cycle"], channel=ch_index):
            cont = IllumstatsContainer.from_store(
                self.store.read_illumstats(cycle=args["cycle"], channel=ch_index)
            )
            imgs = _correct_batch(imgs, cont.mean_log, cont.std_log)
        shifts = None
        if args.get("spatial_align", True) and self.store.has_shifts(
            args["cycle"]
        ):
            shifts = self.store.read_shifts(args["cycle"])
        mosaic = np.zeros((n_sy * h, n_sx * w), np.float32)
        for img, r, site_idx in zip(imgs, srefs, sites):
            if shifts is not None:
                dy, dx = int(shifts[site_idx][0]), int(shifts[site_idx][1])
                if dy or dx:
                    img = _host_shift(img, dy, dx)
            mosaic[r.site_y * h:(r.site_y + 1) * h,
                   r.site_x * w:(r.site_x + 1) * w] = img
        return mosaic

    def _stitch_validity(
        self, sites, srefs, args, n_sy, n_sx, h, w
    ) -> "np.ndarray | None":
        """Boolean mosaic of pixels that carry real data after the
        per-site alignment shift (zero-filled shifted-in edges are
        False).  None when no shift moved anything — every pixel is
        valid and callers can skip the masked-threshold path."""
        if not (args.get("spatial_align", True)
                and self.store.has_shifts(args["cycle"])):
            return None
        shifts = self.store.read_shifts(args["cycle"])
        if not any(
            int(shifts[s][0]) or int(shifts[s][1]) for s in sites
        ):
            return None
        valid = np.zeros((n_sy * h, n_sx * w), bool)
        for r, site_idx in zip(srefs, sites):
            v = _host_shift(
                np.ones((h, w), np.float32),
                int(shifts[site_idx][0]), int(shifts[site_idx][1]),
            ) > 0
            valid[r.site_y * h:(r.site_y + 1) * h,
                  r.site_x * w:(r.site_x + 1) * w] = v
        return valid

    def _run_spatial(self, batch: dict) -> dict:
        return self._persist_spatial(batch, self._launch_spatial(batch))

    def _prefetch_spatial(self, batch: dict) -> dict:
        """Host half of the spatial launch: resolve the well geometry and
        stitch the segmentation channel's mosaic (store reads + host
        assembly) ahead of device dispatch."""
        args = batch["args"]
        sites = batch["sites"]
        exp = self.store.experiment
        ch_name = args["spatial_channel"] or exp.channels[0].name
        idx = exp.channel_index(ch_name)
        refs = list(exp.sites())
        srefs = [refs[i] for i in sites]
        h, w = exp.site_height, exp.site_width
        n_sy = max(r.site_y for r in srefs) + 1
        n_sx = max(r.site_x for r in srefs) + 1
        mosaic = self._stitched_channel(sites, srefs, idx, args, n_sy, n_sx, h, w)
        valid = self._stitch_validity(sites, srefs, args, n_sy, n_sx, h, w)
        return {
            "idx": idx, "srefs": srefs, "h": h, "w": w,
            "n_sy": n_sy, "n_sx": n_sx, "mosaic": mosaic, "valid": valid,
        }

    def _launch_spatial(self, batch: dict, prefetched: dict | None = None) -> dict:
        """Whole-mosaic segmentation of one well (``--layout spatial``) —
        the LAUNCH half: host stitch + async device dispatch (primary
        segmentation and, when configured, the chained secondary
        watershed).  Returns a context of un-fetched device arrays for
        :meth:`_persist_spatial`.

        Stitch the well's sites into one mosaic (illumination-corrected
        when corilla statistics exist — same op as the sites layout's
        preprocess), row-shard it over the device mesh, segment with
        halo-exact smoothing + a global Otsu cut +
        :func:`~tmlibrary_tpu.parallel.label.distributed_connected_components`
        (scipy scan order across the WHOLE mosaic), then export: per-site
        label stacks carrying the global ids, a mosaic-level polygon table
        when ``as_polygons`` is set, and a host-side ragged feature table
        (area/centroid) for the well.  This is the rebuild's
        context-parallelism path: objects crossing site borders keep one
        identity, which per-site fan-out (reference or 'sites' layout)
        cannot do.  Cycle-alignment shifts stored by the align step are
        applied per site during stitching (shift-only — see
        :meth:`_stitched_channel`), so multiplexing cycles segment in
        the aligned frame; ``--figures`` writes one downsampled
        whole-well overlay PNG per object family."""
        import jax
        import jax.numpy as jnp
        import pandas as pd
        from jax.sharding import Mesh

        from tmlibrary_tpu.parallel.label import sharded_segment_mosaic

        args = batch["args"]
        sites = batch["sites"]
        exp = self.store.experiment
        tpoint, zplane = args["tpoint"], args["zplane"]

        if prefetched is None:
            prefetched = self._prefetch_spatial(batch)
        idx = prefetched["idx"]
        srefs = prefetched["srefs"]
        h, w = prefetched["h"], prefetched["w"]
        n_sy, n_sx = prefetched["n_sy"], prefetched["n_sx"]
        mosaic = prefetched["mosaic"]

        # alignment zero-fills shifted-in edges INSIDE the mosaic; those
        # stripes would feed the global Otsu histogram as an artificial
        # zero mode (the sites layout crops them away via the
        # intersection window), so when any exist the threshold is
        # computed over the VALID pixels only and passed in explicitly
        # (stitch + validity come prefetched; the device-side smoothing
        # and Otsu stay on the dispatching thread)
        valid = prefetched["valid"]
        threshold = None
        if valid is not None:
            from tmlibrary_tpu.ops.smooth import gaussian_smooth
            from tmlibrary_tpu.ops.threshold import otsu_value

            sm = np.asarray(jax.jit(
                lambda x: gaussian_smooth(x, args["spatial_sigma"])
            )(jnp.asarray(mosaic)))
            threshold = float(otsu_value(jnp.asarray(sm[valid])))

        requested = args["n_devices"] or len(jax.devices())
        requested = min(requested, len(jax.devices()))
        hm, wm = mosaic.shape
        # the mesh must divide the mosaic EXACTLY — padding would corrupt
        # the global Otsu histogram and edge smoothing, breaking
        # bit-identity with the unsharded chain; shrink to divisors
        # instead.  Candidates: 1-D row shards vs a 2-D rows x cols tile
        # grid — a 2-D factorization often keeps MORE devices busy (e.g.
        # 100 rows on 8 devices: rows-only shrinks to 5, a 4x2 grid uses
        # all 8), and the outputs are layout-invariant either way.
        n_rows1d = next(k for k in range(requested, 0, -1) if hm % k == 0)
        nr2, nc2 = _best_spatial_grid(requested, hm, wm)
        kind = args.get("spatial_grid", "auto")
        use_grid = kind == "grid" or (
            kind == "auto" and nr2 * nc2 > n_rows1d
        )
        if use_grid:
            from tmlibrary_tpu.parallel.label import sharded_segment_mosaic_2d

            n_dev = nr2 * nc2
            if n_dev < requested:
                logger.info(
                    "spatial layout: %dx%d grid uses %d of %d devices — "
                    "mosaic %dx%d must divide the mesh evenly",
                    nr2, nc2, n_dev, requested, hm, wm,
                )
            mesh = Mesh(
                np.asarray(jax.devices()[:n_dev]).reshape(nr2, nc2),
                ("rows", "cols"),
            )
            mesh_shape = [nr2, nc2]
            labels, count = sharded_segment_mosaic_2d(
                jnp.asarray(mosaic), mesh, sigma=args["spatial_sigma"],
                threshold=threshold,
            )
        else:
            n_dev = n_rows1d
            if n_dev < requested:
                logger.info(
                    "spatial layout: using %d of %d devices — mosaic rows "
                    "%d must divide the mesh evenly", n_dev, requested, hm,
                )
            mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("rows",))
            mesh_shape = [n_dev, 1]
            labels, count = sharded_segment_mosaic(
                jnp.asarray(mosaic), mesh, sigma=args["spatial_sigma"],
                threshold=threshold,
            )
        # with a secondary channel every stitched mosaic is used at least
        # twice (watershed input + both families' intensity loops), so
        # memoize — accepting a peak of one mosaic per channel.  Without
        # one, each channel is read exactly once: caching would only
        # regress peak memory (plate-scale mosaics are GBs each), so
        # stitch on demand and let each mosaic go out of scope.
        sec_ch = args.get("spatial_secondary_channel", "")
        stitched = {idx: mosaic}

        def get_channel(i: int) -> np.ndarray:
            if i in stitched:
                return stitched[i]
            m = self._stitched_channel(sites, srefs, i, args, n_sy, n_sx, h, w)
            if sec_ch:
                stitched[i] = m
            return m

        # secondary objects over the whole mosaic: primary labels seed a
        # distributed watershed through a second channel (the sites
        # layout's segment_secondary chain — otsu mask, level flooding,
        # seed ids preserved), so cells keep their nucleus' GLOBAL id.
        # Chained DEVICE-side on the un-fetched primary labels, so the
        # whole well is one async dispatch chain.
        sec = None
        if sec_ch:
            from tmlibrary_tpu.ops import threshold as threshold_ops
            from tmlibrary_tpu.parallel.label import (
                distributed_watershed_from_seeds,
                distributed_watershed_from_seeds_2d,
            )

            sec_idx = exp.channel_index(sec_ch)
            sec_np = np.asarray(get_channel(sec_idx), np.float32)
            img = jnp.asarray(sec_np)
            if valid is not None:
                # same zero-stripe exclusion as the primary threshold
                t_sec = float(
                    threshold_ops.otsu_value(jnp.asarray(sec_np[valid]))
                ) * args["spatial_secondary_factor"]
                mask = img > t_sec
            else:
                mask = threshold_ops.threshold_otsu(
                    img,
                    correction_factor=args["spatial_secondary_factor"],
                )
            flood = (
                distributed_watershed_from_seeds_2d if use_grid
                else distributed_watershed_from_seeds
            )
            sec = (args["spatial_secondary_objects"], sec_np, flood(
                img, labels, mask, mesh,
                n_levels=args["spatial_secondary_levels"],
            ))

        return {
            "batch": batch, "labels_dev": labels, "count_dev": count,
            "sec": sec, "mosaic": mosaic, "get_channel": get_channel,
            "sites": sites, "srefs": srefs, "mesh_shape": mesh_shape,
            "tpoint": tpoint, "zplane": zplane,
        }

    def _persist_spatial(self, batch: dict, ctx: dict) -> dict:
        """Fetch one launched well's device results and write them out —
        the host half of the stitch → device → write overlap
        (``run_batches_pipelined`` launches well N+1's stitch while this
        blocks on well N's arrays).  Peak memory holds two wells'
        mosaics while the pipeline is full."""
        args = batch["args"]
        sites = ctx["sites"]
        srefs = ctx["srefs"]
        tpoint, zplane = ctx["tpoint"], ctx["zplane"]
        get_channel = ctx["get_channel"]
        labels = np.asarray(ctx["labels_dev"])
        count = int(ctx["count_dev"])
        shard = _well_shard(batch)

        def emit_figure(fam_name, fam_mosaic, fam_labels):
            if not args.get("figures"):
                return
            from tmlibrary_tpu.jterator.figures import write_mosaic_figure

            write_mosaic_figure(
                self.store.root / "figures", fam_name, fam_mosaic,
                fam_labels, shard,
            )

        name = args["spatial_objects"]
        self._persist_mosaic_objects(
            name, labels, count, batch, args, sites, srefs, get_channel,
            tpoint, zplane, shard,
        )
        objects = {name: count}
        emit_figure(name, ctx["mosaic"], labels)

        if ctx["sec"] is not None:
            sec_name, sec_np, sec_labels_dev = ctx["sec"]
            sec_labels = np.asarray(sec_labels_dev)
            # watershed preserves seed ids: the id space (and count) is
            # the primary's, so features join across the two families
            self._persist_mosaic_objects(
                sec_name, sec_labels, count, batch, args, sites, srefs,
                get_channel, tpoint, zplane, shard,
            )
            objects[sec_name] = count
            emit_figure(sec_name, sec_np, sec_labels)

        self._note_sites(len(sites))
        return {
            "n_sites": len(sites),
            "objects": objects,
            "mosaic_shape": [int(labels.shape[0]), int(labels.shape[1])],
            "layout": "spatial",
            "mesh_shape": ctx["mesh_shape"],
        }

    def _persist_mosaic_objects(
        self, name, labels, count, batch, args, sites, srefs,
        get_channel, tpoint, zplane, shard,
    ) -> None:
        """Persist one mosaic-scale object family: per-site label stacks
        carrying the global ids, the ragged host-side feature table
        (morphology + per-channel intensity + Zernike), and optional
        mosaic-frame polygons.  ``get_channel(i)`` returns the stitched
        (corrected) mosaic of channel ``i`` — memoized by the caller so
        families share one stitch per channel."""
        import pandas as pd

        exp = self.store.experiment
        h, w = exp.site_height, exp.site_width
        per_site = np.stack([
            labels[r.site_y * h:(r.site_y + 1) * h,
                   r.site_x * w:(r.site_x + 1) * w]
            for r in srefs
        ])
        self.store.write_labels(per_site, sites, name,
                                tpoint=tpoint, zplane=zplane)

        # ragged global features, host-side (object count is dynamic here —
        # nothing is padded to max_objects in the mosaic path).  ONE
        # native C pass over the mosaic (area + centroid sums + bounding
        # boxes), chunked-vectorized numpy fallback — no O(H)
        # interpreter loop on a plate-scale mosaic.
        from tmlibrary_tpu import native as native_mod

        area_i, cy_sum, cx_sum, ymin, ymax, xmin, xmax = (
            native_mod.mosaic_morph_host(labels, count)
        )
        area = area_i[1:].astype(np.float64)
        denom = np.maximum(area, 1)
        cy = cy_sum[1:] / denom
        cx = cx_sum[1:] / denom
        bbox_h = (ymax[1:] - ymin[1:] + 1).astype(np.float64)
        bbox_w = (xmax[1:] - xmin[1:] + 1).astype(np.float64)

        # hull solidity uses the native helper when the library built; its
        # pure-python fallback is O(count * H * W) — at mosaic scale that
        # is effectively a hang, so degrade to NaN instead
        from tmlibrary_tpu import native as native_mod

        if count and native_mod.available():
            solidity = native_mod.solidity_host(
                labels, count, areas=area
            ).astype(np.float64)
        else:
            if count:
                logger.info(
                    "native library unavailable: mosaic solidity emitted "
                    "as NaN (the python hull fallback is quadratic at "
                    "mosaic scale)"
                )
            solidity = np.full(count, np.nan)
        plate, well_row, well_col = batch["well"]
        cols = {
            "site_index": -1,  # mosaic objects may span several sites
            "plate": plate,
            "well_row": well_row,
            "well_col": well_col,
            "site_y": -1,
            "site_x": -1,
            "label": np.arange(1, count + 1, dtype=np.int64),
            "Morphology_area": area,
            "Morphology_centroid_y": cy,
            "Morphology_centroid_x": cx,
            "Morphology_bbox_height": bbox_h,
            "Morphology_bbox_width": bbox_w,
            "Morphology_solidity": solidity,
        }
        # intensity over EVERY channel (sites-layout parity:
        # measure_intensity per channel), one stitched mosaic at a time;
        # the segmentation channel reuses the already-corrected stitch.
        # Zero-object wells still emit the (empty) columns so every
        # well's parquet shard carries the same schema.
        for ch in exp.channels:
            if count == 0:
                empty = np.zeros(0)
                for stat in ("mean", "sum", "std", "min", "max"):
                    cols[f"Intensity_{stat}_{ch.name}"] = empty
                continue
            vals_mosaic = get_channel(ch.index)
            s2, q2, mn2, mx2 = _mosaic_intensity_stats(labels, vals_mosaic, count)
            mean2 = s2[1:] / denom
            var2 = np.maximum(q2[1:] / denom - mean2 * mean2, 0.0)
            cols[f"Intensity_mean_{ch.name}"] = mean2
            cols[f"Intensity_sum_{ch.name}"] = s2[1:]
            cols[f"Intensity_std_{ch.name}"] = np.sqrt(var2)
            cols[f"Intensity_min_{ch.name}"] = np.where(area > 0, mn2[1:], 0.0)
            cols[f"Intensity_max_{ch.name}"] = np.where(area > 0, mx2[1:], 0.0)
        # shape moments: the public ragged host Zernike handles a dynamic
        # object count in row blocks (mahotas semantics; default degree 9
        # matches the sites layout's measure_zernike default, 0 disables)
        z_degree = args["spatial_zernike_degree"]
        if z_degree > 0:
            from tmlibrary_tpu.ops.measure import (
                _zernike_coeffs,
                zernike_host_features,
            )

            zern = zernike_host_features(labels, count, z_degree)
            for z_idx, (n_z, m_z, _) in enumerate(_zernike_coeffs(z_degree)):
                cols[f"Zernike_{n_z}_{m_z}"] = zern[:, z_idx].astype(np.float64)
        table = pd.DataFrame(cols)
        self.store.append_features(name, table, shard=shard)

        if args.get("as_polygons"):
            # mosaic-frame polygons: one ring per GLOBAL object, traced on
            # the stitched label image (site_index -1 marks the frame)
            from tmlibrary_tpu.ops.polygons import (
                labels_to_polygons,
                polygons_to_table,
            )

            polys = labels_to_polygons(labels)
            if polys:
                df = polygons_to_table(polys, site_index=-1)
                out = (self.store.root / "segmentations"
                       / f"{name}_polygons_{shard}.parquet")
                df.to_parquet(out, index=False)

    def run_batches_pipelined(self, batches, depth: int | None = None):
        """Generator over ``(batch, result_summary)`` with host work
        overlapped against device compute.

        XLA dispatch is asynchronous: device calls return futures
        immediately and only the host fetch blocks, so keeping a bounded
        window of launched batches in flight puts the host IO — store
        reads, Parquet writes, polygon tracing — in the shadow of device
        execution.  This recovers the reference's overlap of cluster
        jobs with DB writes (SURVEY.md §4.3 crossing points) without
        process fan-out.  Delegates to the shared
        :class:`~tmlibrary_tpu.workflow.pipelined.PipelinedExecutor`
        (``depth=None`` resolves config > tuning > per-backend default);
        yields stay in batch order and bit-identical to sequential runs.
        """
        from tmlibrary_tpu.workflow.pipelined import PipelinedExecutor

        yield from PipelinedExecutor(self, depth=depth).run(batches)

    def _load_inputs(self, batch: dict) -> dict:
        """Host-side input loading for a sites-layout batch: store reads,
        illumination statistics and shift tables, all as numpy — no
        device transfers, so a prefetch worker can run it while the
        device chews on earlier batches."""
        import jax

        args = batch["args"]
        sites = batch["sites"]
        desc = self._description(args)
        exp = self.store.experiment
        cycle, tpoint, zplane = args["cycle"], args["tpoint"], args["zplane"]

        n_dev = args["n_devices"] or len(jax.devices())
        n_dev = min(n_dev, len(jax.devices()))
        # pad the batch so the site axis shards evenly (padded lanes are
        # recomputed copies of site 0 and dropped on export)
        n_valid = len(sites)
        padded_sites = list(sites)
        if n_valid % n_dev:
            padded_sites += [sites[0]] * (n_dev - n_valid % n_dev)

        raw = {}
        for ch in desc.channels:
            idx = exp.channel_index(ch.name)
            if ch.zstack:
                planes = [
                    self.store.read_sites(padded_sites, cycle=cycle, channel=idx,
                                          tpoint=tpoint, zplane=zp)
                    for zp in range(exp.n_zplanes)
                ]
                stack = np.stack(planes, axis=1)  # (B, Z, H, W)
            else:
                stack = self.store.read_sites(padded_sites, cycle=cycle, channel=idx,
                                              tpoint=tpoint, zplane=zplane)
            raw[ch.name] = stack
        for obj in desc.objects_in:
            raw[obj.name] = self.store.read_labels(padded_sites, obj.name,
                                                   tpoint=tpoint, zplane=zplane)

        stats = {}
        for ch in desc.channels:
            # volumes skip correction (see build_preprocess_fn) — don't
            # demand stats they will never use
            if ch.correct and not ch.zstack:
                idx = exp.channel_index(ch.name)
                if not self.store.has_illumstats(cycle=cycle, channel=idx):
                    raise PipelineError(
                        f"channel '{ch.name}' wants illumination correction but "
                        f"corilla statistics are missing — run corilla first"
                    )
                cont = IllumstatsContainer.from_store(
                    self.store.read_illumstats(cycle=cycle, channel=idx)
                )
                stats[ch.name] = (cont.mean_log, cont.std_log)

        shifts_np = None
        if any(ch.align for ch in desc.channels) and self.store.has_shifts(cycle):
            table = self.store.read_shifts(cycle)
            shifts_np = table[np.asarray(padded_sites)]

        return {"padded_sites": padded_sites, "n_dev": n_dev,
                "raw": raw, "stats": stats, "shifts_np": shifts_np}

    def _launch(
        self, batch: dict, inputs: dict | None = None,
        capacity: int | None = None,
    ):
        """Transfer the (possibly prefetched) inputs and dispatch the
        device computation; returns without waiting for completion."""
        import jax
        import jax.numpy as jnp

        from tmlibrary_tpu.parallel.mesh import batch_sharding, site_mesh

        _, fn = self._pipeline(batch["args"], capacity)
        if inputs is None:
            inputs = self._load_inputs(batch)
        padded_sites = inputs["padded_sites"]
        n_dev = inputs["n_dev"]

        sharding = None
        if n_dev > 1:
            sharding = batch_sharding(site_mesh(n_dev))

        raw = {}
        for name, stack in inputs["raw"].items():
            arr = jnp.asarray(stack)
            raw[name] = jax.device_put(arr, sharding) if sharding else arr

        if inputs["shifts_np"] is not None:
            shifts = jnp.asarray(inputs["shifts_np"])
        else:
            shifts = jnp.zeros((len(padded_sites), 2), jnp.int32)
        if sharding is not None:
            shifts = jax.device_put(shifts, sharding)

        self._note_speculation_ctx(
            batch["args"], capacity, (raw, inputs["stats"], shifts)
        )
        return fn(raw, inputs["stats"], shifts)

    # ------------------------------------------- compile-ahead speculation
    def _note_speculation_ctx(self, args, capacity, call_args) -> None:
        """Remember the shape/dtype skeleton of the latest dispatch so
        the compile-ahead warm thread (:meth:`speculate_ahead`) can
        precompile the next capacity rung against the exact same input
        signature.  No buffers are retained — the skeleton is
        ``ShapeDtypeStruct`` leaves only (the real arrays may be
        donated)."""
        try:
            from tmlibrary_tpu import aotstore

            if not aotstore.speculation_enabled():
                return
            from tmlibrary_tpu import perf

            cap = int(capacity if capacity is not None
                      else args["max_objects"])
            self._spec_ctx = (args, cap, perf.abstract_args(call_args, {}))
        except Exception:
            pass

    def speculate_ahead(self, upcoming=None) -> None:
        """Compile-ahead speculation (DESIGN.md §28): precompile the
        likely next capacity rungs on a background daemon thread while
        the device chews on dispatched batches, so bucket escalation
        (and the TUNING.json-hinted rung) never pays compile on the
        critical path.  Wired as the pipelined executor's warm hook;
        no-op when disabled, before the first dispatch, or while a
        previous warm thread is still running.

        ``upcoming`` (optional) is the not-yet-launched tail of the
        batch list: when batches carry a schedule plan, their planned
        rungs are certainties, not guesses, so the worker warms those
        first and falls back to the ladder heuristics after."""
        try:
            from tmlibrary_tpu import aotstore

            if not aotstore.speculation_enabled():
                return
        except Exception:
            return
        if getattr(self, "_spec_ctx", None) is None:
            return
        prev = getattr(self, "_spec_thread", None)
        if prev is not None and prev.is_alive():
            return
        self._spec_upcoming = list(upcoming) if upcoming else []
        # NOT a daemon thread: the interpreter tearing down while XLA
        # is mid-compile aborts the whole process (C++ terminate), so
        # exit must join an in-flight speculative compile.  The worker
        # checks main-thread liveness between rungs to keep that join
        # bounded to at most one rung.
        t = threading.Thread(
            target=self._speculate_worker, name="tmx-warm", daemon=False
        )
        self._spec_thread = t
        t.start()

    def _speculate_worker(self) -> None:
        try:
            args, cap, (abs_args, abs_kwargs) = self._spec_ctx
            ceiling = int(args["max_objects"])
            from tmlibrary_tpu.capacity import (
                likely_next_rungs,
                observed_peak,
                resolve_bucket_ladder,
            )

            ladder = resolve_bucket_ladder(
                ceiling, args.get("object_buckets", "auto")
            )
            observed = None
            if len(ladder) > 1:
                observed = observed_peak(
                    self._routing_key(args, ceiling, ladder)
                )
            targets = list(likely_next_rungs(cap, ladder, observed=observed))
            from tmlibrary_tpu.tuning import tuned_object_capacity

            hint = tuned_object_capacity()
            if hint and hint in ladder and hint > cap \
                    and hint not in targets:
                targets.append(int(hint))
            # planned rungs from the schedule plan's upcoming batches are
            # certainties, not heuristics: warm them FIRST, in dispatch
            # order, then fall through to the ladder guesses
            planned: list[int] = []
            for b in getattr(self, "_spec_upcoming", []) or []:
                rung = (b.get("schedule") or {}).get("rung")
                if rung and int(rung) in ladder and int(rung) != cap \
                        and int(rung) not in planned:
                    planned.append(int(rung))
            targets = planned + [t for t in targets if t not in planned]
            if not targets:
                return
            from tmlibrary_tpu import perf
            from tmlibrary_tpu import qc as qc_mod
            from tmlibrary_tpu.jterator.pipeline import cached_batch_fn

            desc = self._description(args)
            for rung in targets:
                if not threading.main_thread().is_alive():
                    return  # shutting down: don't start another compile
                # the process-level cache, NOT self._pipeline: tracing a
                # new rung takes seconds and must not hold the instance
                # compile lock a concurrent escalation launch needs
                fn = cached_batch_fn(
                    desc, int(rung), self._window,
                    donate=None if args.get("donate_buffers", True)
                    else False,
                    reduction_strategy=args.get("reduction_strategy",
                                                "auto"),
                    qc=qc_mod.enabled(),
                )
                outcome = perf.speculate_compile(fn, abs_args, abs_kwargs)
                if outcome in ("compiled", "imported"):
                    logger.info(
                        "compile-ahead: capacity rung %d %s in the "
                        "background", rung, outcome,
                    )
        except Exception:
            logger.debug("compile-ahead speculation failed", exc_info=True)

    def _persist(self, batch: dict, result, capacity: int | None = None) -> dict:
        """Fetch one launched batch's device results and write them out."""
        # QC-on programs return (SiteResult, fused per-site image stats);
        # split the pair here so the persist path below is shape-agnostic
        qc_dev = None
        if isinstance(result, tuple):
            result, qc_dev = result
        args = batch["args"]
        sites = batch["sites"]
        tpoint, zplane = args["tpoint"], args["zplane"]
        n_valid = len(sites)
        ceiling = int(args["max_objects"])
        cap = int(capacity) if capacity is not None else ceiling
        escalations = 0
        if cap < ceiling:
            # Escalate until the routed capacity holds the batch.  A
            # count AT the cap may have been clipped there, so nothing
            # below the ceiling is ever persisted from a saturated run —
            # this is the bit-identity contract (capacity.py): below the
            # ceiling, routing can cost a re-launch one bucket up, never
            # a different result.  Ceiling saturation keeps its existing
            # warn/auto-resegment flow below.
            from tmlibrary_tpu.capacity import (
                resolve_bucket_ladder, select_capacity,
            )

            ladder = resolve_bucket_ladder(
                ceiling, args.get("object_buckets", "auto")
            )
            while cap < ceiling:
                peak = max(
                    (int(np.asarray(v)[:n_valid].max(initial=0))
                     for v in result.counts.values()),
                    default=0,
                )
                if peak < cap:
                    break
                new_cap = select_capacity(cap, ladder)
                logger.info(
                    "batch %s saturated its routed object-capacity bucket "
                    "(count hit %d) — re-running at capacity %d",
                    batch.get("index"), cap, new_cap,
                )
                escalations += 1
                cap = new_cap
                result = self._launch(batch, capacity=cap)
                if isinstance(result, tuple):
                    result, qc_dev = result
        counts = {k: np.asarray(v)[:n_valid] for k, v in result.counts.items()}
        objects = {k: np.asarray(v)[:n_valid] for k, v in result.objects.items()}
        measurements = {
            obj: {f: np.asarray(v)[:n_valid] for f, v in feats.items()}
            for obj, feats in result.measurements.items()
        }

        if self._window is not None:
            # cropped intersection frame → site frame: pad labels back with
            # the window offsets and shift positional features, so stored
            # stacks, polygons and figures all live in site coordinates
            top, bottom, left, right = self._window
            # labels (2-D (B,H,W) or volume (B,Z,H,W)) were computed in the
            # cropped frame; pad the spatial dims back to the site frame
            objects = {
                name: np.pad(
                    lab,
                    [(0, 0)] * (lab.ndim - 2) + [(top, bottom), (left, right)],
                )
                for name, lab in objects.items()
            }
            for feats in measurements.values():
                if "Morphology_centroid_y" in feats:
                    feats["Morphology_centroid_y"] = feats["Morphology_centroid_y"] + top
                    feats["Morphology_centroid_x"] = feats["Morphology_centroid_x"] + left

        # solidity is hull-based and ragged, so it is measured host-side on
        # the exported label images and joined into the morphology features
        # (reference: jtlib/features/morphology solidity via regionprops)
        from tmlibrary_tpu.native import solidity_host

        max_obj = args["max_objects"]
        for name, feats in measurements.items():
            if "Morphology_area" in feats and objects.get(name) is not None \
                    and objects[name].ndim == 3:
                feats["Morphology_solidity"] = np.stack(
                    [solidity_host(objects[name][b], max_obj)
                     for b in range(n_valid)]
                )

        # ------------------------------------------------------------ persist
        for name, labels in objects.items():
            if labels.ndim == 4:  # (B, Z, H, W) volume labels: one stack per z
                for zp in range(labels.shape[1]):
                    self.store.write_labels(labels[:, zp], sites, name,
                                            tpoint=tpoint, zplane=zp)
            else:
                self.store.write_labels(labels, sites, name,
                                        tpoint=tpoint, zplane=zplane)

        shard = f"batch_{batch['index']:03d}"
        site_meta = self._site_metadata(sites)
        for name in objects:
            table = self._feature_table(
                name, counts[name], measurements.get(name, {}), site_meta,
                args["max_objects"],
            )
            self.store.append_features(name, table, shard=shard)
            # polygon tracing is 2-D only; volume objects skip it
            if args["as_polygons"] and objects[name].ndim == 3:
                self._write_polygons(name, objects[name], sites, shard)

        if args.get("figures"):
            # segmentation-overlay artifacts (reference module Figure
            # outputs) — rendered host-side from the persisted labels on
            # the first input channel
            from tmlibrary_tpu.jterator.figures import write_figures

            desc = self._description(args)
            first_ch = next((c for c in desc.channels if not c.zstack), None)
            if first_ch is not None:
                idx = self.store.experiment.channel_index(first_ch.name)
                base = self.store.read_sites(
                    sites, cycle=args["cycle"], channel=idx,
                    tpoint=tpoint, zplane=zplane,
                )
                if first_ch.align and self.store.has_shifts(args["cycle"]):
                    # labels live in the aligned frame; shift the raw base
                    # the same way or boundaries draw offset from the cells
                    table = self.store.read_shifts(args["cycle"])
                    base = np.stack([
                        _host_shift(base[b], *table[s])
                        for b, s in enumerate(sites)
                    ])
                for name, labels in objects.items():
                    if labels.ndim == 3:
                        write_figures(
                            self.store.root / "figures", name, base,
                            labels, sites,
                        )

        summary = {
            "n_sites": n_valid,
            "objects": {k: int(v.sum()) for k, v in counts.items()},
        }
        # bucket bookkeeping: feed the router's count history, and carry
        # capacity + slot occupancy in the batch summary so the ledger
        # (tmx workflow status, registry_from_ledger) sees padding waste
        from tmlibrary_tpu.capacity import slot_occupancy

        peak = max(
            (int(v.max(initial=0)) for v in counts.values()), default=0
        )
        self._note_peak(args, peak)
        # per-site costs feed the work-model scheduler's EWMA through the
        # same persist-side stream the peak rides; the densest object
        # family is what sets a site's capacity rung
        site_counts = None
        if counts:
            site_counts = np.maximum.reduce(
                [np.asarray(v) for v in counts.values()]
            )
            self._note_site_costs(args, sites, site_counts)
            shard_objects = self._shard_objects(args, site_counts)
            if shard_objects is not None:
                # actual per-shard work under the applied site order —
                # the straggler-balance evidence a ledger alone can
                # compare against predicted_shard_work (and against an
                # unbalanced run of the same experiment)
                summary["shard_objects"] = shard_objects
        plan = batch.get("schedule") or {}
        if plan.get("rung"):
            summary["schedule_rung"] = int(plan["rung"])
            self._note_schedule(escalations)
        total_objects = sum(summary["objects"].values())
        slots = len(counts) * n_valid * cap
        summary["bucket_capacity"] = cap
        # the ladder ceiling travels with every batch summary so a ledger
        # alone can reconstruct padded-FLOPs-avoided post hoc
        # (telemetry.registry_from_ledger) — additive, PR-5 readers ignore it
        summary["bucket_ceiling"] = ceiling
        summary["slot_occupancy"] = round(slot_occupancy(total_objects, slots), 4)
        if escalations:
            summary["bucket_escalations"] = escalations
        self._note_bucket(cap, ceiling, total_objects, slots, escalations)
        # object-capacity saturation must be LOUD: clip_label_count silently
        # zeroes labels past max_objects, so a site whose count sits AT the
        # cap may have lost objects — surface it per batch in the ledger,
        # accumulate for the collect-phase warning, and leave the re-run
        # recipe in the log (round-2 VERDICT weak-spot #4)
        saturated = {
            k: int((v >= max_obj).sum()) for k, v in counts.items()
        }
        saturated = {k: n for k, n in saturated.items() if n}
        # record unconditionally: a clean re-run of a previously saturated
        # batch must CLEAR its stale entry
        self._record_saturation(batch["index"], saturated)
        if saturated:
            summary["saturated"] = saturated
            logger.warning(
                "object capacity saturated (count == max_objects == %d) for "
                "%s — objects beyond the cap were dropped; re-run the step "
                "with a higher cap: `tmx jterator cleanup && tmx jterator "
                "init --max-objects N && tmx jterator run` (max_objects is "
                "an init-time argument)",
                max_obj,
                ", ".join(f"{n} site(s) of '{k}'" for k, n in saturated.items()),
            )
        if qc_dev is not None:
            # QC rides the already-fetched arrays: fused image stats from
            # the device, numerics guards + feature sketches on the numpy
            # the persist path produced anyway.  The summary travels with
            # the batch result so the ENGINE thread appends the
            # qc_batch/qc_site ledger events (same thread discipline as
            # straggler records) — flags never fail the batch.
            from tmlibrary_tpu import qc as qc_mod
            from tmlibrary_tpu.jterator.pipeline import MODEL_QC_KEY

            image_stats = {
                ch: {m: np.asarray(v)[:n_valid] for m, v in metrics.items()}
                for ch, metrics in qc_dev.items()
            }
            # model diagnostic streams (DL segmenters' flow-magnitude /
            # probability samples) ride the qc pytree under a reserved
            # pseudo-channel; they are value STREAMS, not per-site image
            # scalars, so they route into the feature sketches (every
            # sample valid — no counts mask) under the "__model__"
            # pseudo-objects the model drift profile keys on
            model_stats = image_stats.pop(MODEL_QC_KEY, None)
            meas_for_qc = measurements
            if model_stats:
                meas_for_qc = {
                    **measurements, qc_mod.MODEL_OBJECTS: model_stats,
                }
            qc_summary = qc_mod.get_session().observe_batch(
                self.name, sites, image_stats=image_stats, counts=counts,
                measurements=meas_for_qc, saturated=bool(saturated),
            )
            if qc_summary:
                summary["qc"] = qc_summary
        self._note_sites(n_valid)
        return summary

    # ---------------------------------------------------------------- helpers
    def _site_metadata(self, sites: list[int]) -> list[dict]:
        refs = list(self.store.experiment.sites())
        out = []
        for s in sites:
            r = refs[s]
            out.append(
                {
                    "site_index": s,
                    "plate": r.plate,
                    "well_row": r.well_row,
                    "well_col": r.well_column,
                    "site_y": r.site_y,
                    "site_x": r.site_x,
                }
            )
        return out

    @staticmethod
    def _feature_table(name, counts, feats, site_meta, max_objects):
        import pandas as pd

        rows: dict[str, list] = {k: [] for k in
                                 ("site_index", "plate", "well_row", "well_col",
                                  "site_y", "site_x", "label")}
        for fname in feats:
            rows[fname] = []
        for b, meta in enumerate(site_meta):
            n = int(counts[b])
            for lab in range(1, min(n, max_objects) + 1):
                for k in ("site_index", "plate", "well_row", "well_col",
                          "site_y", "site_x"):
                    rows[k].append(meta[k])
                rows["label"].append(lab)
                for fname, arr in feats.items():
                    rows[fname].append(float(arr[b, lab - 1]))
        return pd.DataFrame(rows)

    def _write_polygons(self, name, labels, sites, shard):
        import pandas as pd

        from tmlibrary_tpu.ops.polygons import labels_to_polygons, polygons_to_table

        tables = []
        for b, site in enumerate(sites):
            polys = labels_to_polygons(labels[b])
            if polys:
                tables.append(polygons_to_table(polys, site))
        if tables:
            df = pd.concat(tables, ignore_index=True)
            out = self.store.root / "segmentations" / f"{name}_polygons_{shard}.parquet"
            df.to_parquet(out, index=False)

    def collect(self) -> dict:
        """Register mapobject types and summarize counts per object type
        (reference's collect phase creates ``MapobjectType`` rows and
        computes their polygon-zoom threshold)."""
        from tmlibrary_tpu.models.mapobject import (
            MapobjectType,
            MapobjectTypeRegistry,
            min_poly_zoom,
            plate_mosaic_shape,
        )
        from tmlibrary_tpu.ops.pyramid import n_pyramid_levels

        # resegment FIRST: the registry pass below derives min_poly_zoom
        # from mean object area, which the capped feature shards would
        # misstate for exactly the object types that saturated
        resegmented = self._resegment_saturated()

        registry = MapobjectTypeRegistry(self.store.root)
        # zoom levels are defined over the viewer pyramid, which illuminati
        # builds from the full plate mosaic — use the largest plate's
        # mosaic dimensions, not a single site's
        exp = self.store.experiment
        n_levels = 1
        for plate in exp.plates:
            n_levels = max(
                n_levels, n_pyramid_levels(*plate_mosaic_shape(exp, plate.name))
            )
        summary = {}
        for name in self.store.list_objects():
            try:
                feats = self.store.read_features(name)
                summary[name] = int(len(feats))
            except Exception:
                continue
            mean_px = 0.0
            cols = getattr(feats, "columns", [])
            # measure_morphology emits 'Morphology_area'; accept a bare
            # 'area' too for externally-written feature tables
            area_col = next(
                (c for c in ("Morphology_area", "area") if c in cols), None
            )
            if area_col is not None:
                mean_px = float(feats[area_col].mean())
            registry.register(
                MapobjectType(
                    name=name,
                    ref_type="segmented",
                    min_poly_zoom=min_poly_zoom(n_levels, mean_px),
                )
            )
        out = {"objects_total": summary}
        if resegmented:
            out["resegmented"] = resegmented
        totals = self._saturation_totals()
        if totals:
            # repeat the saturation warning at collect so it is the LAST
            # thing in the step log, not buried between batches
            out["saturated_sites"] = totals
            logger.warning(
                "object capacity was saturated during this run: %s — those "
                "sites' feature tables and label stacks are missing the "
                "objects beyond the cap; re-run with a higher "
                "--max-objects to recover them",
                ", ".join(f"'{k}': {n} site(s)" for k, n in totals.items()),
            )
        return out

    # ------------------------------------------------- saturation bookkeeping
    #: bounded escalation: up to 4 doublings of the init-time cap, never
    #: past the absolute ceiling (a runaway segmentation must not compile
    #: ever-larger programs forever)
    _RESEGMENT_DOUBLINGS = 4
    _RESEGMENT_CEILING = 4096

    def _resegment_saturated(self) -> dict:
        """Close the saturation loop without a manual step (round-3
        VERDICT next-step #7): re-run JUST the saturated batches at a
        doubled ``max_objects`` until their counts fit, the doubling
        budget runs out, or the ceiling is hit.  The raised cap lives in
        ``cap_overrides.json`` (NOT the batch file — the engine's resume
        staleness check would read a rewritten cap as a changed plan and
        wipe all outputs), is applied by :meth:`run_batch`, and survives
        for resume; each re-run goes through :meth:`run` (per-batch log
        captured) and the escalations land in the collect summary — and
        therefore the run ledger — as ``resegmented``."""
        from tmlibrary_tpu.errors import JobDescriptionError

        done: dict[str, int] = {}
        for _ in range(self._RESEGMENT_DOUBLINGS):
            state = self._saturation_state()
            if not state:
                break
            progressed = False
            for bidx_str in sorted(state):
                try:
                    batch = self.load_batch(int(bidx_str))
                except JobDescriptionError:
                    continue  # batches re-planned since; stale entry
                args = batch.get("args", {})
                if not args.get("auto_resegment", True):
                    return done  # manual mode: leave the warning flow
                if args.get("layout", "sites") == "spatial":
                    continue  # ragged mosaic path has no object cap
                cap = max(
                    int(args.get("max_objects", 256)),
                    self._cap_overrides().get(bidx_str, 0),
                )
                new_cap = min(cap * 2, self._RESEGMENT_CEILING)
                if new_cap <= cap:
                    continue  # ceiling reached; the collect warning fires
                self._write_cap_override(bidx_str, new_cap)
                logger.warning(
                    "auto-resegmenting batch %d at max_objects=%d "
                    "(saturated: %s)",
                    batch["index"], new_cap, state[bidx_str],
                )
                self.run(batch["index"])  # re-records/clears saturation
                done[bidx_str] = new_cap
                progressed = True
            if not progressed:
                break
        return done

    @property
    def _schedule_plan_path(self):
        return self.step_dir / "schedule_plan.json"

    def schedule_plan_info(self) -> dict | None:
        """The recorded packing plan's compact summary (the engine's
        ``schedule_plan`` ledger event) — re-read from the side file so
        a resume appends the SAME digest it recorded at init time, which
        is the bit-identical-boundaries proof."""
        from tmlibrary_tpu.workflow import schedule as schedule_mod

        plan = schedule_mod.load_plan(self._schedule_plan_path)
        return schedule_mod.plan_event(plan) if plan else None

    @property
    def _cap_override_path(self):
        return self.step_dir / "cap_overrides.json"

    def _cap_overrides(self) -> dict:
        import json

        try:
            return json.loads(self._cap_override_path.read_text())
        except (OSError, ValueError):
            return {}

    def _write_cap_override(self, bidx_str: str, cap: int) -> None:
        import json
        import os

        state = self._cap_overrides()
        state[bidx_str] = int(cap)
        tmp = self._cap_override_path.with_suffix(f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(state, sort_keys=True))
        os.replace(tmp, self._cap_override_path)

    @property
    def _saturation_path(self):
        return self.step_dir / "saturation.json"

    def _record_saturation(self, batch_index: int, saturated: dict) -> None:
        """Persist per-batch saturation keyed by batch index, so collect
        sees it from a fresh process (per-verb CLI runs) and a batch
        re-run overwrites — or, when clean, clears — its own entry instead
        of double-counting.  ``run --job N`` batches may execute as
        concurrent processes (cluster-style fan-out), so the
        read-modify-write is flock-serialized and the write is atomic
        (tmp + rename): no lost entries, no torn JSON."""
        import fcntl
        import json
        import os

        path = self._saturation_path
        if not saturated and not path.exists():
            return
        with open(path.with_suffix(".lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                state = json.loads(path.read_text()) if path.exists() else {}
            except ValueError:
                state = {}  # torn by a crashed writer; rebuilt from here on
            if saturated:
                state[str(batch_index)] = saturated
            else:
                state.pop(str(batch_index), None)
            tmp = path.with_suffix(f".tmp{os.getpid()}")
            tmp.write_text(json.dumps(state, sort_keys=True))
            os.replace(tmp, path)

    def _saturation_state(self) -> dict:
        """Raw per-batch saturation map: {batch_index_str: {objects: n}}."""
        import json

        path = self._saturation_path
        if not path.exists():
            return {}
        try:
            return json.loads(path.read_text())
        except ValueError:
            logger.warning(
                "saturation.json is unreadable (crashed writer?) — "
                "per-batch saturation truth remains in the run ledger"
            )
            return {}

    def _saturation_totals(self) -> dict:
        totals: dict[str, int] = {}
        for per_batch in self._saturation_state().values():
            for k, n in per_batch.items():
                totals[k] = totals.get(k, 0) + n
        return totals

    def delete_previous_output(self) -> None:
        import shutil

        for sub in ("segmentations", "features", "figures"):
            d = self.store.root / sub
            if d.exists():
                shutil.rmtree(d)
            d.mkdir()
        # stale saturation signal, cap escalations and the packing plan
        # belong to the deleted outputs (a fresh plan restarts from the
        # init-time cap; create_batches re-derives the schedule from the
        # just-harvested history)
        self._saturation_path.unlink(missing_ok=True)
        self._saturation_path.with_suffix(".lock").unlink(missing_ok=True)
        self._cap_override_path.unlink(missing_ok=True)
        self._schedule_plan_path.unlink(missing_ok=True)
