"""align: register acquisition cycles per site.

Reference parity: ``tmlib/workflow/align/`` ``ImageRegistrator`` — computes
per-site shifts of every cycle against a reference cycle (one reference
channel), stores ``SiteShift`` rows and, in collect, the ``SiteIntersection``
overlap window (SURVEY.md §2 align row).

TPU execution: FFT phase correlation batched over the site axis with vmap;
shifts exceeding ``max_shift`` are zeroed (registration failure fallback,
as in the reference).
"""

from __future__ import annotations

import numpy as np

from tmlibrary_tpu.ops.registration import (
    batch_phase_correlation_quality,
    intersection_window,
)
from tmlibrary_tpu.utils import create_partitions
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.args import Argument, ArgumentCollection
from tmlibrary_tpu.workflow.registry import register_step


@register_step("align")
class ImageRegistrator(Step):
    batch_args = ArgumentCollection(
        Argument("ref_cycle", int, default=0, help="reference cycle"),
        Argument("ref_channel", int, default=0, help="channel used to register"),
        Argument("batch_size", int, default=32, help="sites per device batch"),
        Argument("max_shift", int, default=50,
                 help="shifts larger than this are treated as failures (zeroed)"),
        Argument("min_quality", float, default=0.0,
                 help="zero shifts whose correlation peak falls below this "
                      "(0 = off); peak is 1.0 for identical shifted content"),
    )

    def create_batches(self, args):
        exp = self.store.experiment
        if exp.n_cycles < 2:
            return []
        sites = list(range(self.store.n_sites))
        return [
            {"cycle": cycle, "sites": part}
            for cycle in range(exp.n_cycles)
            if cycle != args["ref_cycle"]
            for part in create_partitions(sites, args["batch_size"])
        ]

    def run_batch(self, batch: dict) -> dict:
        import jax.numpy as jnp

        args = batch["args"]
        cycle, sites = batch["cycle"], batch["sites"]
        ref = self.store.read_sites(sites, cycle=args["ref_cycle"],
                                    channel=args["ref_channel"]).astype(np.float32)
        tgt = self.store.read_sites(sites, cycle=cycle,
                                    channel=args["ref_channel"]).astype(np.float32)
        # np.array (copy): np.asarray of a jax.Array is a read-only view
        dev_shifts, dev_quality = batch_phase_correlation_quality(
            jnp.asarray(ref), jnp.asarray(tgt)
        )
        shifts = np.array(dev_shifts)
        quality = np.asarray(dev_quality)
        bad = np.abs(shifts).max(axis=1) > args["max_shift"]
        if args["min_quality"] > 0.0:
            bad |= quality < args["min_quality"]
        shifts[bad] = 0

        # accumulate into the per-cycle shift table (idempotent slice write)
        path_exists = self.store.has_shifts(cycle)
        table = (
            self.store.read_shifts(cycle)
            if path_exists
            else np.zeros((self.store.n_sites, 2), np.int32)
        )
        table[np.asarray(sites)] = shifts
        self.store.write_shifts(table, cycle)
        return {"cycle": cycle, "n_sites": len(sites), "n_failed": int(bad.sum())}

    def collect(self) -> dict:
        exp = self.store.experiment
        args = self.batch_args.resolve(
            self.load_batch(0)["args"] if self.list_batches() else None
        )
        all_shifts = [
            self.store.read_shifts(c)
            for c in range(exp.n_cycles)
            if c != args["ref_cycle"] and self.store.has_shifts(c)
        ]
        window = intersection_window(
            np.concatenate(all_shifts) if all_shifts else np.zeros((0, 2))
        )
        self.store.write_intersection(window)
        return {"window": window}

    def delete_previous_output(self) -> None:
        for p in (self.store.root / "alignment").glob("*"):
            p.unlink()
