"""Typed step-argument system.

Reference parity: ``tmlib/workflow/args.py`` — ``Argument`` descriptors
(type, default, choices, help) grouped into ``BatchArguments`` /
``SubmissionArguments`` per step, introspected to build both the CLI and
the server's UI forms.  Here the same descriptors drive argparse and the
workflow-description YAML; "submission" arguments (cores/memory/walltime)
have no meaning without a cluster scheduler and are dropped.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Any


@dataclasses.dataclass(frozen=True)
class Argument:
    """One typed step argument."""

    name: str
    type: type
    default: Any = None
    help: str = ""
    choices: tuple | None = None
    required: bool = False


class ArgumentCollection:
    """A step's argument set; builds argparse options and validates dicts."""

    def __init__(self, *args: Argument):
        self._args = {a.name: a for a in args}

    def __iter__(self):
        return iter(self._args.values())

    def names(self) -> list[str]:
        return list(self._args)

    def to_schema(self) -> list[dict]:
        """JSON-able description of every argument (name, type, default,
        choices, help) — the introspection surface the reference uses to
        render per-step UI forms (``tmlib/workflow/args.py`` exposes the
        same metadata to tmserver)."""
        return [
            {
                "name": a.name,
                "type": a.type.__name__,
                "default": a.default,
                "required": a.required,
                "help": a.help,
                "choices": list(a.choices) if a.choices else None,
            }
            for a in self._args.values()
        ]

    def add_to_parser(self, parser: argparse.ArgumentParser) -> None:
        for a in self._args.values():
            kwargs: dict[str, Any] = {"help": a.help, "default": a.default}
            if a.type is bool:
                kwargs["action"] = argparse.BooleanOptionalAction
            else:
                kwargs["type"] = a.type
            if a.choices:
                kwargs["choices"] = list(a.choices)
            if a.required:
                kwargs["required"] = True
            parser.add_argument(f"--{a.name.replace('_', '-')}", dest=a.name, **kwargs)

    def resolve(self, given: dict[str, Any] | None) -> dict[str, Any]:
        """Merge ``given`` over defaults, rejecting unknown keys and
        validating choices."""
        given = dict(given or {})
        out: dict[str, Any] = {}
        for a in self._args.values():
            if a.name in given:
                val = given.pop(a.name)
                if val is not None and a.type is not bool:
                    val = a.type(val)
                if a.choices and val not in a.choices:
                    raise ValueError(
                        f"argument '{a.name}' must be one of {a.choices}, got {val!r}"
                    )
                out[a.name] = val
            elif a.required:
                raise ValueError(f"argument '{a.name}' is required")
            else:
                out[a.name] = a.default
        if given:
            raise ValueError(f"unknown arguments: {sorted(given)}")
        return out
