"""Step registry.

Reference parity: ``tmlib/workflow/__init__.py`` — ``register_step_api`` /
``get_step_api`` / ``get_step_args``: steps self-register under their CLI
name so the workflow engine and CLI can instantiate them by name.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Type

from tmlibrary_tpu.errors import RegistryError

if TYPE_CHECKING:
    from tmlibrary_tpu.workflow.api import Step

_STEPS: dict[str, Type["Step"]] = {}


def register_step(name: str):
    def deco(cls):
        cls.name = name
        _STEPS[name] = cls
        return cls

    return deco


def get_step(name: str) -> Type["Step"]:
    _ensure_loaded()
    try:
        return _STEPS[name]
    except KeyError:
        raise RegistryError(
            f"no step '{name}' registered (have: {sorted(_STEPS)})"
        ) from None


def list_steps() -> list[str]:
    _ensure_loaded()
    return sorted(_STEPS)


def _ensure_loaded() -> None:
    """Import the built-in step modules so their decorators run."""
    from tmlibrary_tpu.workflow import steps  # noqa: F401
