"""Step API base: plan / run / collect.

Reference parity: ``tmlib/workflow/api.py`` ``ClusterRoutines`` — every
step implements ``create_run_batches`` (plan), ``run_job`` (per-batch
work), ``collect_job`` (merge) and ``delete_previous_job_output``
(idempotent re-runs); batch descriptions are JSON files in the experiment's
workflow directory (SURVEY.md §4.2).

The TPU rebuild keeps the same three-phase shape — it is what makes
resume/idempotence work — but a "batch" feeds a sharded device program
instead of a cluster job."""

from __future__ import annotations

import abc
import contextlib
import json
import logging
import shutil
from pathlib import Path
from typing import Any

from tmlibrary_tpu.errors import JobDescriptionError
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.workflow.args import ArgumentCollection

logger = logging.getLogger(__name__)


class Step(abc.ABC):
    """Base class for workflow steps (reference ``ClusterRoutines``)."""

    #: set by @register_step
    name: str = "step"
    #: override with the step's typed arguments
    batch_args: ArgumentCollection = ArgumentCollection()

    def __init__(self, store: ExperimentStore):
        self.store = store

    # ------------------------------------------------------------- locations
    @property
    def step_dir(self) -> Path:
        d = self.store.workflow_dir / self.name
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _batch_path(self, index: int) -> Path:
        return self.step_dir / f"batch_{index:03d}.json"

    # ----------------------------------------------------------------- plan
    @abc.abstractmethod
    def create_batches(self, args: dict[str, Any]) -> list[dict]:
        """Plan run batches from resolved arguments (reference
        ``create_run_batches``).  Each batch must be JSON-serializable."""

    def init(self, args: dict[str, Any] | None = None) -> list[dict]:
        """Resolve args, plan batches, persist them (CLI verb ``init``)."""
        resolved = self.batch_args.resolve(args)
        self.delete_previous_output()
        batches = self.create_batches(resolved)
        for old in self.step_dir.glob("batch_*.json"):
            old.unlink()
        for i, batch in enumerate(batches):
            batch = dict(batch)
            batch["index"] = i
            batch["args"] = resolved
            self._batch_path(i).write_text(json.dumps(batch))
        logger.info("%s: planned %d batches", self.name, len(batches))
        return batches

    def load_batch(self, index: int) -> dict:
        path = self._batch_path(index)
        if not path.exists():
            raise JobDescriptionError(
                f"no batch {index} for step '{self.name}' — run init first"
            )
        return json.loads(path.read_text())

    def list_batches(self) -> list[int]:
        return sorted(
            int(p.stem.split("_")[1]) for p in self.step_dir.glob("batch_*.json")
        )

    # ------------------------------------------------------------------ run
    @abc.abstractmethod
    def run_batch(self, batch: dict) -> dict:
        """Execute one batch; return a JSON-serializable result summary
        (reference ``run_job``)."""

    def run(self, index: int) -> dict:
        batch = self.load_batch(index)
        with self.capture_logs(f"batch_{index:03d}"):
            result = self.run_batch(batch)
        return result or {}

    @contextlib.contextmanager
    def capture_logs(self, name: str):
        """Capture framework logging to ``<step_dir>/logs/<name>.log`` for
        the duration (reference parity: per-job stdout/stderr files in the
        experiment workflow dir, surfaced by the ``log`` CLI verb —
        SURVEY.md §6 observability row)."""
        log_dir = self.step_dir / "logs"
        log_dir.mkdir(parents=True, exist_ok=True)
        # mode="w": each capture is one run — appending would interleave a
        # re-run's lines with the previous (possibly failed) run's
        handler = logging.FileHandler(log_dir / f"{name}.log", mode="w")
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
        handler.setLevel(logging.DEBUG)
        # the package logger's level (WARNING at default CLI verbosity)
        # filters records before any handler sees them — open it to DEBUG
        # for the capture window so the file gets the full INFO trail,
        # while pinning the existing console handlers to the previous
        # effective level so terminal verbosity is unchanged
        pkg = logging.getLogger("tmlibrary_tpu")
        prev_level = pkg.level
        effective = pkg.getEffectiveLevel()
        pinned = [(h, h.level) for h in pkg.handlers]
        for h, _ in pinned:
            h.setLevel(max(h.level, effective))
        pkg.setLevel(logging.DEBUG)
        pkg.addHandler(handler)
        try:
            yield
        finally:
            pkg.removeHandler(handler)
            handler.close()
            for h, lvl in pinned:
                h.setLevel(lvl)
            pkg.setLevel(prev_level)

    # -------------------------------------------------------------- collect
    def collect(self, results: list[dict] | None = None) -> dict:
        """Merge phase after all batches ran (reference ``collect_job``).
        Default: nothing to merge.

        Steps that declare a ``results`` parameter receive the batch
        result summaries that *survived* the run — under fault quarantine
        (``resilience.py``) that may be fewer than the planned batches, so
        a merge that assumes completeness can check instead of silently
        producing a short table.  Legacy ``collect(self)`` overrides are
        still called without arguments by the engine."""
        return {}

    # ----------------------------------------------------------- idempotence
    def delete_previous_output(self) -> None:
        """Remove this step's previous outputs so re-runs are idempotent
        (reference ``delete_previous_job_output``).  Default: nothing."""

    # ------------------------------------------------------------- utilities
    def _clear_dir(self, path: Path) -> None:
        if path.exists():
            shutil.rmtree(path)
        path.mkdir(parents=True, exist_ok=True)
