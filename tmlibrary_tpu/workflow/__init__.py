"""Workflow orchestration layer.

Reference parity: ``tmlib/workflow/`` — the stage/step engine
(``workflow.py``), job fan-out (``jobs.py``), the step-API base
(``api.py`` ``ClusterRoutines``), the typed args system (``args.py``), the
step registry (``__init__.py``) and the submission manager
(``manager.py``/``submission.py``).

TPU redesign (SURVEY.md §4.1): the reference drives a GC3Pie task DAG where
every step spawns init/run/collect processes on a cluster; here the whole
stage→step graph is an in-process loop dispatching batched device programs,
with a JSON run ledger giving the same persistence/resume semantics the
reference got from DB-backed task state.
"""

from tmlibrary_tpu.workflow.registry import get_step, list_steps, register_step

__all__ = ["get_step", "list_steps", "register_step"]
