"""Deep pipelined batch executor: multi-batch in-flight depth with
threaded prefetch and persist.

XLA dispatch is asynchronous — a device call returns futures immediately
and only the host fetch blocks — so the old depth-1 generator in
``jterator.py`` already overlapped ONE batch's host IO with device
compute.  The hardware tuning sweep (``tuning/TUNING.json``) shows the
device is still starved at that depth: batch N+1's store reads serialize
against batch N-1's Parquet/polygon persists on the single host thread.
This module generalizes the overlap into an executor any step can use by
exposing the launch/persist split:

- ``prefetch_batch(batch)`` (optional) — pure host-side input loading
  (``store.read_sites``, illumstats, shift tables, mosaic stitching),
  safe to run on a worker thread ahead of dispatch.
- ``launch_batch(batch, prefetched=None) -> (effective_batch, ctx)`` —
  async device dispatch; returns un-fetched device results.  The
  effective batch may differ from the planned one (jterator's cap
  overrides), and is what ``persist_batch`` receives.
- ``block_batch(ctx)`` (optional) — block until the launched device
  arrays are ready, so the device-block phase is timed separately from
  the writes.
- ``persist_batch(effective_batch, ctx) -> result`` — fetch + write
  (feature shards, label stacks, polygons, figures).

Semantics the engine depends on (and the equivalence tests pin down):

- **Ordering**: ``run()`` yields ``(batch, result)`` strictly in
  submission order, so ledger ``batch_done``/``batch_failed`` events keep
  batch-index order and resume replay is unchanged.
- **Window drain**: a launch failure mid-window first persists and
  yields EVERY already-launched batch (not just the previous one), then
  propagates — resume granularity matches the sequential path and no
  completed work loses its ledger event.
- **Depth auto-clamp**: a ``RESOURCE_EXHAUSTED``/OOM failure with
  depth > 1 drains the window, halves the depth, reports a
  ``depth_clamped`` event through ``on_event``, and retries the failed
  batch at the lower depth instead of failing the step — HBM pressure
  from too many in-flight batches degrades throughput, not correctness.
- **Bit-identity**: dispatch happens on the calling thread in batch
  order and persists default to ONE worker draining in submission
  order, so results are bit-identical to sequential execution.

Fault plans (``faults.py``) targeting ``batch_run``/``ledger_append``
force the engine onto the sequential path *before* this executor is
constructed — those faults must land before a batch persists to mean
anything (DESIGN.md §11).  ``persist``-site plans run through the real
executor: the hook fires in the persist worker, after the device work
and before the batch's outputs are durable.
"""

from __future__ import annotations

import collections
import concurrent.futures
import contextlib
import inspect
import logging
import time
from typing import Any, Callable, Iterable, Iterator

from tmlibrary_tpu import faults, profiling, telemetry
from tmlibrary_tpu.errors import PreemptedError

logger = logging.getLogger(__name__)

#: shared no-op context for disarmed watchdog phases — one object, zero
#: per-batch allocation when the watchdog is off (zero-cost-when-disabled
#: discipline, same as telemetry's shared null instrument)
_NULL_CM = contextlib.nullcontext()

#: messages that signal HBM/host-memory pressure from too-deep pipelining
#: (XLA surfaces these as bare RuntimeError/XlaRuntimeError text)
_RESOURCE_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
)


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when the error smells like memory pressure — the one failure
    class where *reducing the in-flight depth* is the fix, not a retry at
    the same depth."""
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc).lower()
    return any(p in msg for p in _RESOURCE_PATTERNS)


def supports_pipelining(step) -> bool:
    """A step drives through :class:`PipelinedExecutor` when it exposes
    the launch/persist split."""
    return hasattr(step, "launch_batch") and hasattr(step, "persist_batch")


def resolve_pipeline_depth(
    explicit: int | None = None, backend: str | None = None
) -> tuple[int, str]:
    """The in-flight depth to run and where it came from.

    Precedence (highest first): an explicit request (CLI
    ``--pipeline-depth`` / ``Workflow(pipeline_depth=...)``), the
    install config (``TM_PIPELINE_DEPTH`` env / INI ``pipeline_depth``),
    the machine-written tuning sweep's ``best_pipeline`` (device
    backends only — the sweep measured the device), then a safe
    per-backend default: 8 on device, 2 on CPU (dispatch is cheap there
    and a shallow window still overlaps persist IO with compute without
    holding many batches of host arrays).

    Returns ``(depth, source)`` with source in ``cli | config | tuning |
    default`` so the chosen depth's provenance can be logged and
    recorded in the run ledger.
    """
    if explicit is not None and int(explicit) > 0:
        return max(1, int(explicit)), "cli"
    from tmlibrary_tpu.config import _setting

    try:
        configured = int(_setting("pipeline_depth", "0"))
    except ValueError:
        configured = 0
    if configured > 0:
        return configured, "config"
    if backend is None:
        import jax

        backend = jax.default_backend()
    if backend != "cpu":
        from tmlibrary_tpu.tuning import tuned_pipeline_depth

        tuned = tuned_pipeline_depth()
        if tuned:
            return tuned, "tuning"
        return 8, "default"
    return 2, "default"


def prefetch_iter(
    items: Iterable[Any],
    load: Callable[[Any], Any],
    depth: int = 2,
) -> Iterator[Any]:
    """Yield ``load(item)`` for every item IN ORDER, with up to ``depth``
    loads running ahead on worker threads.

    This is the executor's prefetch stage as a standalone primitive, for
    steps whose unit of work is smaller than a batch — corilla's
    chunk-scan loop reads site chunks through it so store IO for chunk
    N+1 hides behind chunk N's device scan.  Order (and therefore any
    order-dependent fold over the results) is preserved exactly; a
    loader exception surfaces at the failing item's position.
    """
    items = list(items)
    depth = max(1, int(depth))
    if len(items) <= 1:
        for item in items:
            yield load(item)
        return
    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=min(depth, len(items)), thread_name_prefix="tmx-prefetch"
    )
    futures: collections.deque = collections.deque()
    try:
        pos = 0
        while pos < len(items) or futures:
            while pos < len(items) and len(futures) < depth:
                futures.append(pool.submit(load, items[pos]))
                pos += 1
            yield futures.popleft().result()
    finally:
        for f in futures:
            f.cancel()
        pool.shutdown(wait=True)


class PipelinedExecutor:
    """Bounded in-flight window over a step's launch/persist split.

    ``run(batches)`` is a generator of ``(batch, result)`` in submission
    order.  ``on_event(**event)`` receives ``depth_clamped`` events (the
    engine appends them to the run ledger); ``stats`` is an optional
    :class:`tmlibrary_tpu.profiling.PipelineStats` collecting the
    per-batch phase timers.
    """

    def __init__(
        self,
        step,
        depth: int | None = None,
        depth_source: str | None = None,
        persist_workers: int = 1,
        on_event: Callable[..., None] | None = None,
        stats=None,
        should_stop: Callable[[], bool] | None = None,
        watchdog=None,
        warm_hook: Callable[[], None] | None = None,
    ):
        if depth is None:
            depth, depth_source = resolve_pipeline_depth()
        self.step = step
        self.depth = max(1, int(depth))
        self.depth_source = depth_source or "explicit"
        # >1 persist workers would reorder writes across batches; every
        # persisted artifact is batch-sharded so that is SAFE, but one
        # worker keeps the write order deterministic and is already off
        # the critical path — more only helps when persist dominates
        self.persist_workers = max(1, int(persist_workers))
        self.on_event = on_event
        self.stats = stats
        #: graceful drain: polled before each launch — when it flips the
        #: window drains (every launched batch persists + yields) and a
        #: :class:`PreemptedError` carries the drain summary out; both
        #: default to None so the executor costs nothing extra when the
        #: drain/watchdog layers are off
        self.should_stop = should_stop
        #: resilience.PhaseWatchdog (or None): deadlines over the
        #: launch/block/persist phases
        self.watchdog = watchdog
        #: compile-ahead speculation hook (aotstore plane): fired ONCE,
        #: right after the first batch's launch returns — the device is
        #: busy, the prefetch workers own the host IO, and the window is
        #: filling, so this is the prefetch-idle moment to start warming
        #: the likely next capacity rungs on a background thread.  The
        #: hook manages its own thread; a failure is swallowed (warming
        #: is an optimization, never a correctness dependency)
        self.warm_hook = warm_hook
        self._warmed = False

    # ------------------------------------------------------------------ run
    def run(self, batches: Iterable[dict]) -> Iterator[tuple[dict, dict]]:
        batches = list(batches)
        pos = 0
        while pos < len(batches):
            try:
                for out in self._run_window(batches[pos:]):
                    pos += 1
                    yield out
                return
            except Exception as exc:  # noqa: BLE001 — classified below
                if self.depth > 1 and is_resource_exhausted(exc):
                    new_depth = max(1, self.depth // 2)
                    failing = batches[pos]["index"] if pos < len(batches) else None
                    logger.warning(
                        "pipelined executor: %s at depth %d — clamping to "
                        "depth %d and retrying batch %s",
                        exc, self.depth, new_depth, failing,
                    )
                    if self.on_event is not None:
                        self.on_event(
                            event="depth_clamped", from_depth=self.depth,
                            to_depth=new_depth, batch=failing, error=str(exc),
                        )
                    if self.stats is not None:
                        self.stats.record_clamp(self.depth, new_depth)
                    self.depth = new_depth
                    continue  # _run_window drained: pos is the failed batch
                raise

    # ---------------------------------------------------------------- spans
    def _flush_spans(self, batch: dict) -> None:
        """Emit the buffered phase timings for a completed batch as
        ``span`` events — on the calling (engine) thread, right before the
        batch's ``(batch, result)`` is yielded, so every ledger append
        stays on one thread and span events precede ``batch_done``."""
        if self.stats is None or self.on_event is None:
            return
        idx = batch.get("index")
        if idx is None:
            return
        for phase, seconds, t0 in self.stats.pop_batch_spans(idx):
            self.on_event(
                event="span", span=phase, batch=idx,
                t0=round(t0, 6), elapsed=round(seconds, 6),
                resource=profiling.PHASE_RESOURCE.get(phase, "host"),
            )

    # --------------------------------------------------------------- window
    def _run_window(self, batches: list[dict]) -> Iterator[tuple[dict, dict]]:
        step = self.step
        stats = self.stats
        step_name = getattr(step, "name", "") or "unknown"
        watchdog = self.watchdog

        def _arm(phase: str, idx):
            # shared null context when no watchdog: zero per-batch cost
            return (_NULL_CM if watchdog is None
                    else watchdog.arm(phase, step=step_name, batch=idx))

        has_prefetch = hasattr(step, "prefetch_batch")
        prefetcher = None
        if has_prefetch and len(batches) > 1:
            prefetcher = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(self.depth, 4, len(batches)),
                thread_name_prefix="tmx-prefetch",
            )
        persister = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.persist_workers, thread_name_prefix="tmx-persist"
        )
        # launched-but-not-yet-yielded batches, in submission order
        window: collections.deque = collections.deque()
        prefetched: dict[int, concurrent.futures.Future] = {}

        def persist_task(eff: dict, ctx, idx: int) -> dict:
            if hasattr(step, "block_batch"):
                w0 = time.time()
                t0 = time.perf_counter()
                with _arm("block", idx):
                    step.block_batch(ctx)
                if stats is not None:
                    stats.record("device_block", time.perf_counter() - t0,
                                 batch=idx, t0=w0)
            w0 = time.time()
            t0 = time.perf_counter()
            with _arm("persist", idx):
                # persist-site faults land here: after the device work,
                # before the outputs are durable (kill-mid-persist,
                # sigterm, hang) — inside the armed phase so an injected
                # hang exercises the watchdog like a real wedged write
                faults.maybe_fire("persist", step=step_name, batch=idx)
                result = step.persist_batch(eff, ctx)
            if stats is not None:
                stats.record("persist", time.perf_counter() - t0,
                             batch=idx, t0=w0)
                stats.batch_done()
            return result

        def note_inflight() -> None:
            # live window depth for `tmx top` (gauge only — no ledger
            # traffic; this runs on the engine thread either way)
            if telemetry.enabled():
                telemetry.get_registry().gauge(
                    "tmx_pipeline_inflight",
                    step=getattr(step, "name", "") or "unknown",
                ).set(len(window))

        def pop_one() -> tuple[dict, dict]:
            batch, fut = window.popleft()
            note_inflight()
            result = fut.result()
            self._flush_spans(batch)
            return batch, result

        try:
            for i, batch in enumerate(batches):
                if self.should_stop is not None and self.should_stop():
                    # graceful drain: stop admitting batches, let every
                    # already-launched one persist + yield (the caller
                    # ledgers each), then surface the drain summary.  The
                    # ledger boundary is exactly a clean run's after the
                    # same batches: resume continues bit-identically.
                    n0 = len(window)
                    drained = 0
                    while window:
                        yield pop_one()
                        drained += 1
                    raise PreemptedError(
                        f"preempted before batch {batch.get('index', i)}: "
                        f"drained {drained}/{n0} in-flight, abandoned "
                        f"{len(batches) - i} un-launched",
                        step=step_name, in_flight=n0, drained=drained,
                        abandoned=len(batches) - i,
                    )
                if prefetcher is not None:
                    # keep up to `depth` loads ahead of the dispatch point
                    for j in range(i, min(i + self.depth, len(batches))):
                        if j not in prefetched:
                            prefetched[j] = prefetcher.submit(
                                step.prefetch_batch, batches[j]
                            )
                bidx = batch.get("index", i)
                try:
                    pre = None
                    if i in prefetched:
                        w0 = time.time()
                        t0 = time.perf_counter()
                        pre = prefetched.pop(i).result()
                        if stats is not None:
                            stats.record(
                                "prefetch_wait", time.perf_counter() - t0,
                                batch=bidx, t0=w0,
                            )
                    w0 = time.time()
                    t0 = time.perf_counter()
                    with _arm("launch", bidx):
                        eff, ctx = step.launch_batch(batch, pre)
                    if stats is not None:
                        stats.record("dispatch", time.perf_counter() - t0,
                                     batch=bidx, t0=w0)
                    if self.warm_hook is not None and not self._warmed:
                        self._warmed = True
                        try:
                            # plan-aware warming: a hook that takes a
                            # parameter gets the un-launched tail, so
                            # schedule-planned rungs warm as certainties
                            # rather than ladder guesses; zero-arg hooks
                            # keep their existing contract
                            try:
                                takes_upcoming = bool(
                                    inspect.signature(
                                        self.warm_hook
                                    ).parameters
                                )
                            except (TypeError, ValueError):
                                takes_upcoming = False
                            if takes_upcoming:
                                self.warm_hook(batches[i + 1:])
                            else:
                                self.warm_hook()
                        except Exception:
                            logger.debug("warm hook failed", exc_info=True)
                except Exception:
                    # drain the WHOLE window: every already-launched batch
                    # persists (and the caller ledgers it) before the
                    # failure propagates — with depth > 1 flushing only
                    # the previous batch would drop completed work
                    while window:
                        yield pop_one()
                    raise
                window.append((batch, persister.submit(
                    persist_task, batch if eff is None else eff, ctx, bidx
                )))
                note_inflight()
                while len(window) > self.depth:
                    yield pop_one()
            while window:
                yield pop_one()
        finally:
            for f in prefetched.values():
                f.cancel()
            if prefetcher is not None:
                prefetcher.shutdown(wait=False)
            # wait=True: no persist worker may still be writing while the
            # engine's sequential fallback re-runs the failed batch
            persister.shutdown(wait=True)
