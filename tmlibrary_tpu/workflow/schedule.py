"""Work-aware site scheduling: cost-model batch packing and
straggler-balanced device sharding (DESIGN.md §29).

Directory-order batching wastes two ways: one dense site drags a whole
batch to a big capacity rung (slot occupancy stuck near 0.47 even with
bucketing), and one dense shard stalls every device in the mesh
(``straggler_skew_s`` on the shard_map path).  Both are placement
problems, so both are solved by the same three-part plan:

1. **Per-site cost prediction** — per-site observed object counts from
   prior runs (persisted feature shards harvested before
   ``delete_previous_output``, plus the live per-site EWMA
   ``capacity.note_site_counts`` accumulates from every completed
   batch); sites with no history fall back to the routing-key peak
   (``capacity.observed_peak``), then the capacity ceiling.
2. **Rung-homogeneous batch packing** — sites sorted by predicted count
   (greedy LPT flavor) and sliced into the SAME batch-size multiset
   directory order would have produced, so sparse batches route to
   small rungs while every compiled input signature stays one the
   unpacked run already owns (the zero-new-compiles contract).
3. **Straggler-balanced shard assignment** — within each batch, sites
   are permuted so each contiguous device shard carries near-equal
   predicted work (``parallel.mesh.balanced_shard_order``).

The plan is a pure function of (site list, history snapshot, ladder,
batch size, mesh width, description digest) — no wall clock, no
randomness — recorded as a ``schedule_plan`` ledger event and a
``schedule_plan.json`` side file so ``--resume`` re-derives bit-identical
batch boundaries.  Per-site results persist idempotently by site index,
so packing on/off is bit-identical per site (tests/test_schedule.py).

Resolution order for the mode (highest first): the step's explicit
``schedule`` arg when not ``"auto"``, the ``TMX_SCHEDULE`` env (the CLI
``--schedule`` knob), the install config (``TM_SCHEDULE`` / INI
``schedule``), the provenance-gated TUNING.json verdict
(``tuning.tuned_schedule``), then ``"auto"`` (packing on).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from pathlib import Path

#: accepted mode spellings; "pack"/"on" force packing, "off" disables,
#: "auto" defers down the precedence chain (and ultimately packs)
SCHEDULE_MODES = ("auto", "pack", "off")

_ON_VALUES = ("pack", "on", "1", "true", "yes")
_OFF_VALUES = ("off", "none", "0", "false", "no")

#: plan format version (schedule_plan.json / the ledger event)
PLAN_VERSION = 1


def _normalize(value) -> str | None:
    """Canonical mode for a raw knob value, or None when unset/auto."""
    text = str(value or "").strip().lower()
    if not text or text == "auto":
        return None
    if text in _ON_VALUES:
        return "pack"
    if text in _OFF_VALUES:
        return "off"
    raise ValueError(
        f"schedule mode '{value}' is not one of {SCHEDULE_MODES}"
    )


def resolve_schedule(explicit: str | None = None) -> tuple[str, str]:
    """The effective schedule mode and where it came from.

    Precedence (highest first): an explicit request (the step's
    ``schedule`` batch arg / a plumbed parameter), the ``TMX_SCHEDULE``
    env (the CLI ``--schedule`` knob), the install config
    (``TM_SCHEDULE`` / INI ``schedule``), the machine-written tuning
    verdict (:func:`tmlibrary_tpu.tuning.tuned_schedule` — provenance
    gated, backend scoped), then the default ``pack``: the plan
    degenerates to directory order with no history, so auto costs
    nothing on a cold start.

    Returns ``(mode, source)`` with mode in ``pack | off`` and source in
    ``cli | env | config | tuning | default``.
    """
    mode = _normalize(explicit)
    if mode is not None:
        return mode, "cli"
    mode = _normalize(os.environ.get("TMX_SCHEDULE"))
    if mode is not None:
        return mode, "env"
    from tmlibrary_tpu.config import _setting

    mode = _normalize(_setting("schedule", "auto"))
    if mode is not None:
        return mode, "config"
    from tmlibrary_tpu.tuning import tuned_schedule

    mode = _normalize(tuned_schedule())
    if mode is not None:
        return mode, "tuning"
    return "pack", "default"


def schedule_enabled(mode: str) -> bool:
    """True when ``mode`` packs (everything except ``off``)."""
    return str(mode or "").strip().lower() not in _OFF_VALUES


# --------------------------------------------------------------- predictor
def predict_site_counts(
    key: str, sites: list[int], prior: float,
) -> list[float]:
    """Predicted per-site object counts: the EWMA history entry when one
    exists (``capacity.site_count_snapshot``), else ``prior`` — the
    cold-start fallback the caller derives from the routing-key peak or
    the capacity ceiling.  Pure read; never mutates history."""
    from tmlibrary_tpu.capacity import site_count_snapshot

    table = site_count_snapshot(key)
    prior = float(prior)
    return [float(table.get(int(s), prior)) for s in sites]


def harvest_store_counts(store) -> dict[int, int]:
    """Per-site object counts from a PRIOR run's persisted feature
    shards: for every objects family under ``features/``, the number of
    feature rows per ``site_index``; per site, the max over families
    (the densest family is what sets the capacity rung).  Returns ``{}``
    when nothing is persisted — cold start is a supported state, never
    an error."""
    counts: dict[int, int] = {}
    try:
        features_root = Path(store.root) / "features"
        if not features_root.is_dir():
            return {}
        import pandas as pd

        for family_dir in sorted(features_root.iterdir()):
            if not family_dir.is_dir():
                continue
            for shard in sorted(family_dir.glob("*.parquet")):
                try:
                    table = pd.read_parquet(shard, columns=["site_index"])
                except Exception:
                    continue
                for site, n in table["site_index"].value_counts().items():
                    site = int(site)
                    counts[site] = max(counts.get(site, 0), int(n))
    except Exception:
        return {}
    return counts


# ----------------------------------------------------------------- packing
def contiguous_shard_work(
    weights: list[float], n_shards: int,
) -> list[float]:
    """Per-shard predicted work under the PLAIN contiguous split (the
    pre-balancing layout) — the "before" half of the skew comparison.
    Padding lanes (appended at the end, zero real work) are accounted
    like :func:`parallel.mesh.balanced_shard_order` does."""
    n = len(weights)
    n_shards = max(1, int(n_shards))
    if n_shards == 1 or n <= 1:
        return [float(sum(weights))]
    chunk = -(-n // n_shards)
    return [
        float(sum(weights[s * chunk:(s + 1) * chunk]))
        for s in range(n_shards)
    ]


def _skew(loads: list[float]) -> float:
    return (max(loads) - min(loads)) if len(loads) > 1 else 0.0


def pack_plan(
    sites: list[int],
    predicted: list[float],
    batch_size: int,
    ladder: tuple[int, ...],
    n_devices: int,
    seed: str,
    mode: str = "pack",
    source: str = "default",
) -> dict:
    """The deterministic packing plan: batches (site lists), per-batch
    predicted capacity rung, and per-batch balanced shard loads.

    Packing preserves the batch-size multiset directory order would have
    produced (``ceil(n / batch_size)`` batches, all but the last full),
    so every compiled input signature — (padded batch, rung) — is one
    the unpacked run compiles too; no new signatures are ever minted
    (the zero-new-compiles contract, pinned by ci_schedule_smoke).
    Sites are ordered by predicted count descending (LPT flavor, ties on
    site index) and sliced consecutively: each batch's rung is set by
    its densest member, which is adjacent in sorted order, so rung
    mixing inside a batch is minimal by construction.  ``seed`` (the
    description digest) joins the plan digest so two descriptions never
    share a plan identity.
    """
    from tmlibrary_tpu.capacity import select_capacity
    from tmlibrary_tpu.parallel.mesh import balanced_shard_order

    n = len(sites)
    batch_size = max(1, int(batch_size))
    n_devices = max(1, int(n_devices))
    order = sorted(range(n), key=lambda i: (-float(predicted[i]), sites[i]))
    batches = []
    for start in range(0, n, batch_size):
        idxs = order[start:start + batch_size]
        bsites = [int(sites[i]) for i in idxs]
        bpred = [float(predicted[i]) for i in idxs]
        peak = max(bpred) if bpred else 0.0
        rung = select_capacity(int(math.ceil(peak)), ladder)
        naive_work = contiguous_shard_work(bpred, n_devices)
        balanced, work = balanced_shard_order(bsites, bpred, n_devices)
        pred_by_site = dict(zip(bsites, bpred))
        balanced_pred = [pred_by_site[s] for s in balanced]
        batches.append({
            "sites": balanced,
            "predicted": [round(p, 3) for p in balanced_pred],
            "rung": int(rung),
            "shard_work": [round(w, 3) for w in work],
            "shard_work_naive": [round(w, 3) for w in naive_work],
        })
    plan = {
        "version": PLAN_VERSION,
        "mode": mode,
        "source": source,
        "seed": str(seed),
        "batch_size": batch_size,
        "n_devices": n_devices,
        "ladder": [int(c) for c in ladder],
        "n_sites": n,
        "history": {
            str(int(sites[i])): round(float(predicted[i]), 3)
            for i in range(n)
        },
        "batches": batches,
    }
    plan["digest"] = plan_digest(plan)
    return plan


def plan_digest(plan: dict) -> str:
    """Content digest of a plan (digest field excluded): the resume
    convergence check — a re-derived plan matches the recorded
    ``schedule_plan`` ledger event iff the digests match."""
    body = {k: v for k, v in plan.items() if k != "digest"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def plan_event(plan: dict) -> dict:
    """The compact ``schedule_plan`` ledger-event payload: plan identity
    plus the predicted before/after occupancy and shard-skew the packing
    claims — so a ledger alone shows what the plan promised, and the
    batch_done stream shows what it delivered."""
    batches = plan.get("batches") or []
    ladder = plan.get("ladder") or []
    ceiling = ladder[-1] if ladder else 0
    pred_total = sum(sum(b.get("predicted") or []) for b in batches)
    packed_slots = sum(
        b["rung"] * len(b.get("sites") or []) for b in batches
    )
    # the unpacked counterfactual: every batch at the rung the GLOBAL
    # predicted peak selects (what peak-routing converges to)
    peak = max(
        (max(b.get("predicted") or [0.0]) for b in batches), default=0.0
    )
    from tmlibrary_tpu.capacity import select_capacity

    flat_rung = (
        select_capacity(int(math.ceil(peak)), tuple(ladder))
        if ladder else ceiling
    )
    flat_slots = sum(
        flat_rung * len(b.get("sites") or []) for b in batches
    )
    skew_packed = sum(_skew(b.get("shard_work") or [0.0]) for b in batches)
    skew_naive = sum(
        _skew(b.get("shard_work_naive") or [0.0]) for b in batches
    )
    rungs: dict[str, int] = {}
    for b in batches:
        rungs[str(b["rung"])] = rungs.get(str(b["rung"]), 0) + 1
    return {
        "plan_digest": plan.get("digest"),
        "mode": plan.get("mode"),
        "source": plan.get("source"),
        "n_batches": len(batches),
        "n_sites": int(plan.get("n_sites") or 0),
        "n_devices": int(plan.get("n_devices") or 1),
        "rungs": rungs,
        "pred_occupancy_packed": round(
            pred_total / packed_slots, 4) if packed_slots else 0.0,
        "pred_occupancy_unpacked": round(
            pred_total / flat_slots, 4) if flat_slots else 0.0,
        "pred_skew_packed": round(skew_packed, 3),
        "pred_skew_unpacked": round(skew_naive, 3),
    }


# -------------------------------------------------------------- plan file
def write_plan(path, plan: dict | None) -> None:
    """Persist the plan side file atomically (None removes it — a
    schedule-off re-init must not leave a stale plan behind)."""
    path = Path(path)
    if plan is None:
        path.unlink(missing_ok=True)
        return
    tmp = path.with_suffix(f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(plan, sort_keys=True))
    os.replace(tmp, path)


def load_plan(path) -> dict | None:
    """The recorded plan, or None when absent/unreadable (a torn write
    degrades to "no plan", never to an error on the resume path)."""
    try:
        plan = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None
    return plan if isinstance(plan, dict) and plan.get("batches") else None
