"""Workflow engine: stage/step DAG execution with ledger-backed resume.

Reference parity: ``tmlib/workflow/workflow.py`` (``Workflow`` →
``WorkflowStage`` → ``WorkflowStep`` = init → run → collect, driven through
GC3Pie ``next()`` transitions), ``description.py`` (YAML-serializable
workflow description validated against the step registry),
``dependencies.py`` (canonical stage order) and
``manager.py``/``submission.py`` (DB-backed submission state + ``resume``).

TPU redesign (SURVEY.md §4.1): no process fan-out — stages iterate in one
process dispatching batched device programs; the JSON-lines run ledger
replaces the ``Submission``/``Task`` tables: every init/run/collect event
is appended with timing, and ``resume`` replays the ledger to skip
completed work.  Idempotence still comes from each step's
``delete_previous_output`` + deterministic batch plans, exactly the
reference's contract.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import inspect
import json
import logging
import os
import sys
import time
import zlib
from pathlib import Path
from typing import Any

import yaml

from tmlibrary_tpu import faults, telemetry
from tmlibrary_tpu.atomicio import atomic_write_text
from tmlibrary_tpu.errors import FaultInjected, PreemptedError, WorkflowError
from tmlibrary_tpu.log import warn_once
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.resilience import (
    PERMANENT,
    ResilienceConfig,
    RetryOutcome,
    RetryPolicy,
    classify,
    preemption_reason,
    preemption_requested,
    retry_call,
    watchdog_from_config,
)
from tmlibrary_tpu.profiling import PipelineStats
from tmlibrary_tpu.workflow.pipelined import (
    PipelinedExecutor,
    resolve_pipeline_depth,
    supports_pipelining,
)
from tmlibrary_tpu.workflow.registry import get_step, list_steps

logger = logging.getLogger(__name__)

#: workflow-type stage DAGs (reference ``tmlib/workflow/dependencies.py``:
#: ``CanonicalWorkflowDependencies`` and ``MultiplexingWorkflowDependencies``)
#: — conversion → preprocessing → pyramid → analysis; the multiplexing type
#: adds inter-cycle registration (``align``) to the preprocessing stage.
WORKFLOW_TYPES: dict[str, list[tuple[str, list[str]]]] = {
    "canonical": [
        ("image_conversion", ["metaconfig", "imextract"]),
        ("image_preprocessing", ["corilla"]),
        ("pyramid_creation", ["illuminati"]),
        ("image_analysis", ["jterator"]),
    ],
    "multiplexing": [
        ("image_conversion", ["metaconfig", "imextract"]),
        ("image_preprocessing", ["corilla", "align"]),
        ("pyramid_creation", ["illuminati"]),
        ("image_analysis", ["jterator"]),
    ],
}

#: back-compat alias: the widest stage DAG (multiplexing superset)
CANONICAL_STAGES = WORKFLOW_TYPES["multiplexing"]


@dataclasses.dataclass
class WorkflowStepDescription:
    name: str
    args: dict[str, Any] = dataclasses.field(default_factory=dict)
    active: bool = True


@dataclasses.dataclass
class WorkflowStageDescription:
    name: str
    steps: list[WorkflowStepDescription]


@dataclasses.dataclass
class WorkflowDescription:
    """YAML-serializable workflow plan (reference ``WorkflowDescription``)."""

    stages: list[WorkflowStageDescription]

    def validate(self) -> None:
        known = set(list_steps())
        for stage in self.stages:
            for step in stage.steps:
                if step.name not in known:
                    raise WorkflowError(
                        f"workflow references unknown step '{step.name}' "
                        f"(registered: {sorted(known)})"
                    )

    def active_steps(self) -> list[WorkflowStepDescription]:
        return [s for st in self.stages for s in st.steps if s.active]

    # ------------------------------------------------------------- serialize
    def to_dict(self) -> dict:
        return {
            "stages": [
                {
                    "name": st.name,
                    "steps": [
                        {"name": s.name, "args": s.args, "active": s.active}
                        for s in st.steps
                    ],
                }
                for st in self.stages
            ]
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkflowDescription":
        return cls(
            stages=[
                WorkflowStageDescription(
                    name=st["name"],
                    steps=[
                        WorkflowStepDescription(
                            name=s["name"],
                            args=s.get("args", {}) or {},
                            active=bool(s.get("active", True)),
                        )
                        for st_s in [st.get("steps", [])]
                        for s in st_s
                    ],
                )
                for st in d.get("stages", [])
            ]
        )

    @classmethod
    def load(cls, path: Path) -> "WorkflowDescription":
        return cls.from_dict(yaml.safe_load(Path(path).read_text()))

    def save(self, path: Path) -> None:
        Path(path).write_text(yaml.safe_dump(self.to_dict(), sort_keys=False))

    @classmethod
    def for_type(
        cls,
        workflow_type: str,
        step_args: dict[str, dict] | None = None,
    ) -> "WorkflowDescription":
        """Build a description for a registered workflow type
        (``canonical`` | ``multiplexing``); ``step_args`` maps step name →
        args, and only steps with args are active (inactive steps stay in
        the plan so they can be toggled on later)."""
        if workflow_type not in WORKFLOW_TYPES:
            raise WorkflowError(
                f"unknown workflow type '{workflow_type}' "
                f"(registered: {sorted(WORKFLOW_TYPES)})"
            )
        step_args = step_args or {}
        return cls(
            stages=[
                WorkflowStageDescription(
                    name=stage,
                    steps=[
                        WorkflowStepDescription(
                            name=s,
                            args=step_args.get(s, {}),
                            active=s in step_args,
                        )
                        for s in steps
                    ],
                )
                for stage, steps in WORKFLOW_TYPES[workflow_type]
            ]
        )

    @classmethod
    def canonical(cls, step_args: dict[str, dict] | None = None) -> "WorkflowDescription":
        """The four-stage workflow, auto-typed: requesting ``align`` args
        selects the multiplexing variant (the only type that runs
        inter-cycle registration)."""
        wtype = "multiplexing" if "align" in (step_args or {}) else "canonical"
        return cls.for_type(wtype, step_args)


#: separator introducing the per-line checksum :meth:`RunLedger.append`
#: seals every event line with (the last key of the JSON object)
_CRC_SEP = ', "crc": "'


class RunLedger:
    """Append-only JSON-lines event log (replaces the reference's
    ``Submission``/``Task`` tables).

    Crash consistency (DESIGN.md §19): every appended line is *sealed*
    with a CRC-32 of the event body embedded as its last JSON key, so a
    torn write (process killed mid-append) is detectable even when the
    torn prefix happens to be valid JSON.  Readers skip unverifiable
    lines; the *writer* additionally truncates a torn tail back to the
    last intact line boundary before its first append
    (:meth:`recover`), so a crashed run's ledger converges to exactly
    the clean-run prefix.  Seed-era ledgers without CRCs stay fully
    readable — the checksum is only enforced where present.

    ``fsync=True`` makes every append crash-durable at the cost of one
    fsync per event; without it a crash mid-append can leave a truncated
    trailing line, which :meth:`events` skips with a warning instead of
    poisoning every later ``resume``/``status`` call."""

    def __init__(self, path: Path, fsync: bool = False,
                 host: str | None = None):
        self.path = Path(path)
        self.fsync = fsync
        #: fleet attribution: when set, every appended event carries a
        #: ``host`` field so interleaved multi-host ledgers stay
        #: separable in ``registry_from_ledger`` / ``tmx metrics``
        self.host = host
        #: (mtime_ns, size) → parsed events; ``status()`` and
        #: ``completed_batches()`` poll :meth:`events` repeatedly and the
        #: file only grows via :meth:`append`, so re-parsing the whole
        #: JSON-lines file on every call is pure waste
        self._cache: tuple[tuple[int, int], list[dict]] | None = None
        #: torn-tail recovery runs once, lazily, before the first append
        self._recovered = False
        #: per-step completed-batch sets maintained by
        #: :meth:`append_batch_done` so idempotence checks don't re-parse
        #: the whole ledger once per batch
        self._done_cache: dict[str, set[int]] = {}

    # ------------------------------------------------------------- sealing
    @staticmethod
    def _seal(body: str) -> str:
        """Append the CRC-32 of ``body`` as its trailing JSON key.  The
        sealed line is still one valid JSON object, so older checkouts
        (and any JSON-lines tooling) read it unchanged."""
        crc = zlib.crc32(body.encode())
        return f'{body[:-1]}{_CRC_SEP}{crc:08x}"}}'

    @staticmethod
    def _line_ok(line: str) -> bool:
        """True when the line parses — and, if sealed, verifies.  The
        CRC is recomputed over the exact bytes that were sealed (the
        line with its checksum key stripped), not a re-serialization, so
        verification is byte-exact."""
        head, sep, tail = line.rpartition(_CRC_SEP)
        if sep and tail.endswith('"}'):
            if f"{zlib.crc32((head + '}').encode()):08x}" != tail[:-2]:
                return False
            line = head + "}"
        try:
            json.loads(line)
        except json.JSONDecodeError:
            return False
        return True

    def recover(self) -> int:
        """Truncate a torn tail (crash/kill mid-append) back to the last
        intact line boundary; returns the number of bytes dropped.

        WRITER PATH ONLY — called automatically before the first
        :meth:`append`.  Read-only consumers polling a *live* ledger
        from another process (``tmx top``, ``status``) must never
        truncate a file someone else is mid-append on; they skip
        unverifiable lines in :meth:`events` instead."""
        self._recovered = True
        try:
            data = self.path.read_bytes()
        except OSError:
            return 0
        good = len(data)
        while good > 0:
            nl = data.rfind(b"\n", 0, good)
            if nl == good - 1:
                # newline-terminated tail line: keep it if intact,
                # otherwise walk back one more line
                start = data.rfind(b"\n", 0, nl) + 1
                frag = data[start:nl]
                if not frag.strip() or self._line_ok(
                    frag.decode("utf-8", errors="replace")
                ):
                    break
                good = start
            else:
                # unterminated fragment — the signature of a torn append
                good = nl + 1
        dropped = len(data) - good
        if dropped:
            logger.warning(
                "ledger %s: truncating %d bytes of torn tail (crash "
                "mid-append) back to the last intact event boundary",
                self.path, dropped,
            )
            with open(self.path, "rb+") as f:
                f.truncate(good)
            self._cache = None
            self._done_cache.clear()
        return dropped

    def append(self, **event) -> None:
        if not self._recovered:
            self.recover()
        event["ts"] = time.time()
        if self.host is not None:
            event.setdefault("host", self.host)
        # One edit point labels every event (spans, batch_done, job
        # lifecycle, compile) with the ambient trace context: the serve
        # daemon installs trace_id/job/tenant around each execution, so a
        # single trace_id links enqueue → admission → run → phase without
        # threading labels through every emitter.  setdefault keeps
        # explicitly-labeled events (e.g. multi-tenant merges) intact.
        for k, v in telemetry.trace_context().items():
            event.setdefault(k, v)
        telemetry.flight_record(event)
        line = self._seal(json.dumps(event))
        spec = faults.match("ledger_append", step=event.get("step"),
                            event=event.get("event"))
        self._cache = None
        if event.get("event") == "init_done":
            # a re-init invalidates earlier batch completions
            self._done_cache.clear()
        with open(self.path, "a") as f:
            if spec is not None:
                # simulate the process dying mid-write: half a line, no
                # newline, then the injected crash propagates
                f.write(line[: max(1, len(line) // 2)])
                f.flush()
                faults.raise_for(spec, "ledger_append", event)
            f.write(line + "\n")
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())

    def append_batch_done(self, step: str, batch: int, **fields) -> bool:
        """Idempotent ``batch_done``: recording a batch whose completion
        is already in the ledger (a resume that re-ran work which had
        persisted, a drained window re-observed) is a detected no-op, so
        replay-derived state (``completed_batches``, ledger metrics)
        never double-counts.  Returns True when the event was appended."""
        done = self._done_cache.get(step)
        if done is None:
            done = self._done_cache[step] = set(self.completed_batches(step))
        if batch in done:
            logger.info(
                "ledger: batch_done for %s batch %d already recorded — "
                "idempotent no-op", step, batch,
            )
            return False
        self.append(step=step, event="batch_done", batch=batch, **fields)
        done.add(batch)
        return True

    def events(self) -> list[dict]:
        """Parsed ledger events; treat the returned list as read-only
        (it is cached until the file changes on disk).  Sealed lines
        failing their CRC are skipped exactly like unparseable ones; the
        ``crc`` key itself is stripped so consumers see the event as it
        was appended."""
        try:
            st = self.path.stat()
        except OSError:
            return []
        key = (st.st_mtime_ns, st.st_size)
        cached = self._cache
        if cached is not None and cached[0] == key:
            return cached[1]
        out = []
        for lineno, line in enumerate(self.path.read_text().splitlines(), 1):
            if not line.strip():
                continue
            if not self._line_ok(line):
                warn_once(
                    logger, f"{self.path}:{lineno}",
                    "ledger %s line %d is torn or corrupt (invalid JSON "
                    "or failed CRC — crash mid-append?) — skipping it; "
                    "resume treats the event as never recorded",
                    str(self.path), lineno,
                )
                continue
            parsed = json.loads(line)
            parsed.pop("crc", None)
            out.append(parsed)
        self._cache = (key, out)
        return out

    def completed_steps(self) -> set[str]:
        return {e["step"] for e in self.events() if e.get("event") == "step_done"}

    def completed_batches(self, step: str) -> set[int]:
        done = set()
        for e in self.events():
            if e.get("step") != step:
                continue
            if e.get("event") == "batch_done":
                done.add(e["batch"])
            elif e.get("event") == "init_done":
                # a re-init invalidates earlier batch completions
                done.clear()
        return done

    def quarantined_batches(self, step: str) -> set[int]:
        """Batches recorded ``batch_failed`` and not completed since; a
        re-init clears the set like it clears completions."""
        q: set[int] = set()
        for e in self.events():
            if e.get("step") != step:
                continue
            if e.get("event") == "batch_failed":
                q.add(e["batch"])
            elif e.get("event") == "batch_done":
                q.discard(e["batch"])
            elif e.get("event") == "init_done":
                q.clear()
        return q

    def last_description_hash(self) -> str | None:
        h = None
        for e in self.events():
            if e.get("event") == "run_started":
                h = e.get("description_hash", h)
        return h

    def status(self) -> dict[str, Any]:
        steps: dict[str, dict] = {}
        for e in self.events():
            s = e.get("step")
            if not s:
                continue
            entry = steps.setdefault(
                s, {"state": "pending", "batches_done": 0, "n_batches": None,
                    "elapsed": 0.0, "quarantined": []}
            )
            if e["event"] == "init_done":
                entry.update(state="running", n_batches=e.get("n_batches"),
                             batches_done=0, quarantined=[])
            elif e["event"] == "batch_done":
                entry["batches_done"] += 1
                entry["elapsed"] += e.get("elapsed", 0.0)
                if e.get("batch") in entry["quarantined"]:
                    entry["quarantined"].remove(e["batch"])
                # object-capacity bucket routing (capacity.py): the batch
                # summary self-describes its routed capacity and slot
                # occupancy — aggregate so `tmx workflow status` shows
                # padding waste without re-reading any outputs
                result = e.get("result") or {}
                cap = result.get("bucket_capacity")
                if cap is not None:
                    buckets = entry.setdefault(
                        "buckets",
                        {"routed": {}, "escalations": 0,
                         "occupancy_sum": 0.0, "occupancy_n": 0},
                    )
                    key = str(cap)
                    buckets["routed"][key] = buckets["routed"].get(key, 0) + 1
                    buckets["escalations"] += int(
                        result.get("bucket_escalations", 0)
                    )
                    occ = result.get("slot_occupancy")
                    if occ is not None:
                        buckets["occupancy_sum"] += float(occ)
                        buckets["occupancy_n"] += 1
                # QC summary fields are run-cumulative at append time,
                # so last-write-wins mirrors the live registry gauges
                qc = result.get("qc")
                if isinstance(qc, dict):
                    entry["qc"] = {
                        "flagged": qc.get("flagged_total", 0),
                        "nan_columns": qc.get("nan_columns", 0),
                        "worst_focus": qc.get("worst_focus"),
                        "count_z_max": qc.get("count_z_max"),
                    }
            elif e["event"] == "qc_budget_exceeded":
                entry.setdefault("qc", {})["budget_exceeded"] = True
            elif e["event"] == "batch_failed":
                if e.get("batch") not in entry["quarantined"]:
                    entry["quarantined"].append(e.get("batch"))
            elif e["event"] == "step_partial":
                entry["state"] = "partial"
                if e.get("pipeline_stats"):
                    entry["pipeline_stats"] = e["pipeline_stats"]
            elif e["event"] == "step_done":
                entry["state"] = "done"
                if e.get("pipeline_stats"):
                    entry["pipeline_stats"] = e["pipeline_stats"]
            elif e["event"] == "step_failed":
                entry["state"] = "failed"
                entry["error"] = e.get("error")
            elif e["event"] == "depth_clamped":
                entry.setdefault("depth_clamps", []).append(
                    {"from": e.get("from_depth"), "to": e.get("to_depth")}
                )
            elif e["event"] == "watchdog":
                entry["watchdog_fires"] = entry.get("watchdog_fires", 0) + 1
            elif e["event"] == "run_preempted":
                entry["preempted"] = True
        return steps

    def degraded_backend(self) -> dict | None:
        """The most recent ``backend_degraded`` event, if any."""
        last = None
        for e in self.events():
            if e.get("event") == "backend_degraded":
                last = e
        return last

    def preempted(self) -> dict | None:
        """The trailing ``run_preempted`` event when the most recent run
        ended in a graceful drain; a later ``run_started`` (the resume)
        clears it, so status surfaces PREEMPTED only while it is true."""
        last = None
        for e in self.events():
            if e.get("event") == "run_preempted":
                last = e
            elif e.get("event") == "run_started":
                last = None
        return last


class Workflow:
    """Execute a workflow description against an experiment store.

    Fault tolerance (``resilience.py``): each batch runs under the retry
    policy; a batch that keeps failing is *quarantined* (a
    ``batch_failed`` ledger event) while the step continues, and the
    step only fails once quarantined batches exceed the configured
    budget.  ``resume`` re-attempts quarantined batches first.  A device
    health guard probes the device path before every step and degrades
    to the CPU backend when the relay is down."""

    def __init__(self, store: ExperimentStore,
                 description: WorkflowDescription,
                 resilience: ResilienceConfig | None = None,
                 pipeline_depth: int | None = None,
                 should_stop=None, stop_reason=None):
        from tmlibrary_tpu.config import cfg

        description.validate()
        self.store = store
        self.description = description
        #: cooperative-cancellation hooks, polled at every step and batch
        #: boundary (and inside the pipelined executor's launch loop).
        #: Default: the process-wide preemption flag.  ``tmx serve``
        #: passes a composite that also trips on the per-job deadline,
        #: so an expired job cancels at the next batch boundary with
        #: ``PreemptedError(reason="deadline")`` instead of running to
        #: completion.
        self._should_stop = (should_stop if should_stop is not None
                             else preemption_requested)
        self._stop_reason = (stop_reason if stop_reason is not None
                             else preemption_reason)
        self.ledger = RunLedger(
            store.workflow_dir / "ledger.jsonl",
            fsync=cfg.ledger_fsync,
            # single-host runs keep host-free events (seed-compatible
            # ledgers, bit-identical telemetry-off behaviour); fleet runs
            # attribute every event to this host
            host=(telemetry.host_id() if telemetry.fleet_active() else None),
        )
        self.resilience = (resilience if resilience is not None
                           else ResilienceConfig.from_library_config())
        #: explicit in-flight depth for the pipelined executor; None means
        #: resolve per step (config > tuning > per-backend default)
        self.pipeline_depth = pipeline_depth
        #: resilience.PhaseWatchdog for this run (built in :meth:`run`,
        #: None when disabled — the zero-cost default)
        self._watchdog = None

    # ------------------------------------------------------------- identity
    def description_hash(self) -> str:
        """Stable digest of the whole workflow description, recorded in
        ``run_started`` so resume detects drift anywhere in the plan —
        not just in the per-step ``args`` the batch files capture."""
        canon = json.dumps(self.description.to_dict(), sort_keys=True)
        return hashlib.sha256(canon.encode()).hexdigest()[:16]

    # ------------------------------------------------------------------ run
    def run(self, resume: bool = False) -> dict:
        """Run all active steps in order; with ``resume=True`` skip completed
        steps and completed batches of the interrupted step (reference
        ``resume`` CLI verb backed by DB task state)."""
        if not resume and self.ledger.path.exists():
            self.ledger.path.unlink()
        desc_hash = self.description_hash()
        if resume:
            prev = self.ledger.last_description_hash()
            if prev is not None and prev != desc_hash:
                logger.warning(
                    "resume: workflow description changed since the last "
                    "run (%s -> %s) — steps whose args changed will "
                    "re-plan; review the plan if that is unexpected",
                    prev, desc_hash,
                )
                self.ledger.append(event="description_drift",
                                   previous=prev, current=desc_hash)
        self.ledger.append(event="run_started", description_hash=desc_hash,
                           resume=resume)
        # cold-start attribution: wall clock from run start to the first
        # persisted batch of a device-dispatching step (the time XLA
        # compiles dominate on a cold process — the aotstore warm-start
        # plane exists to shrink it)
        self._run_wall_t0 = time.time()
        self._first_batch_noted = False
        telemetry.get_registry().counter("tmx_runs_total").inc()
        sampler = self._start_sampler()
        guard = self.resilience.guard if self.resilience.enabled else None
        if guard is not None:
            guard.ensure_backend(self.ledger, where="run")
        # None when disabled: no monitor thread, no arming, no events
        self._watchdog = watchdog_from_config(
            on_fire=guard.note_watchdog_fire if guard is not None else None
        )
        done_steps = self.ledger.completed_steps() if resume else set()
        summary = {}
        try:
            with telemetry.span("run", emit=self.ledger.append):
                for stage in self.description.stages:
                    for sd in stage.steps:
                        if not sd.active:
                            continue
                        if sd.name in done_steps:
                            logger.info(
                                "resume: skipping completed step %s", sd.name
                            )
                            continue
                        if self._should_stop():
                            # the drain request landed between steps (or
                            # during the previous step's collect): the
                            # boundary is already clean — record it and
                            # stop admitting steps
                            self._note_preempted(PreemptedError(
                                f"preempted before step '{sd.name}'",
                                step=sd.name, reason=self._stop_reason(),
                            ))
                        if guard is not None:
                            guard.ensure_backend(self.ledger, where=sd.name)
                        with telemetry.span(
                            "step",
                            emit=functools.partial(self.ledger.append,
                                                   step=sd.name),
                        ):
                            summary[sd.name] = self._run_step(sd, resume)
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
                self._drain_watchdog()
                self._watchdog = None
            if sampler is not None:
                sampler.stop()
            self._drain_compile_spans()
            exc = sys.exc_info()[1]
            if exc is not None and not isinstance(exc, PreemptedError) \
                    and not (isinstance(exc, FaultInjected) and exc.fatal):
                # unhandled crash: preserve the last-N event ring for the
                # post-mortem (preemption dumps in _note_preempted; a
                # FATAL injected fault simulates hard process death — a
                # dead process writes nothing)
                telemetry.flight_dump(
                    telemetry.flightrec_path(self.store.workflow_dir),
                    reason=f"crash:{type(exc).__name__}",
                )
            self._write_metrics_snapshot()
        return summary

    def _drain_watchdog(self, step_name: str | None = None) -> None:
        """Append queued ``watchdog`` events — on the engine thread, the
        only thread allowed to touch the ledger (the monitor thread just
        queues)."""
        wd = self._watchdog
        if wd is None:
            return
        fired = False
        for ev in wd.drain_events():
            if step_name is not None:
                ev.setdefault("step", step_name)
            self.ledger.append(**ev)
            fired = True
        if fired:
            # a watchdog fire is one of the flight-recorder dump triggers:
            # the hang's surrounding events are exactly what a post-mortem
            # needs, and they may be gone from the ring by process exit
            telemetry.flight_dump(
                telemetry.flightrec_path(self.store.workflow_dir),
                reason="watchdog", extra={"step": step_name},
            )

    def _drain_compile_spans(self, step_name: str | None = None) -> None:
        """Append buffered compile spans from perf.py — buffered because
        ``record_compile`` can run on persist-worker threads (jterator
        bucket escalation) and only the engine thread may touch the
        ledger.  No-op (and empties nothing) when telemetry is off."""
        if not telemetry.enabled():
            return
        from tmlibrary_tpu import perf
        for sp in perf.pop_compile_spans():
            if step_name is not None:
                sp.setdefault("step", step_name)
            self.ledger.append(event="span", span="compile", **sp)

    def _note_preempted(self, exc: PreemptedError) -> None:
        """Record the drain boundary durably (``run_preempted`` event +
        counter) and re-raise — the CLI maps this to the pinned
        ``EXIT_PREEMPTED`` code so schedulers re-launch with ``resume``."""
        self._drain_watchdog(exc.step)
        self.ledger.append(
            event="run_preempted", step=exc.step, reason=exc.reason,
            in_flight=exc.in_flight, drained=exc.drained,
            abandoned=exc.abandoned,
        )
        telemetry.flight_dump(
            telemetry.flightrec_path(self.store.workflow_dir),
            reason=f"preempted:{exc.reason}", extra={"step": exc.step},
        )
        telemetry.get_registry().counter("tmx_preemptions_total").inc()
        logger.warning(
            "run preempted (%s) at step '%s': drained %d/%d in-flight "
            "batches, abandoned %d un-launched — resume with "
            "`tmx workflow resume`", exc.reason, exc.step, exc.drained,
            exc.in_flight, exc.abandoned,
        )
        raise exc

    def _write_metrics_snapshot(self) -> None:
        """Persist the live registry next to the ledger so ``tmx metrics``
        exports the run's exact counters without re-deriving — written on
        failure too (a failed run's metrics are the interesting ones).
        All writes are atomic (tmp + rename, ``atomicio``): a kill
        mid-snapshot leaves the previous snapshot intact, never half a
        JSON file."""
        self._write_qc_profile()
        if not telemetry.enabled():
            return
        try:
            rendered = telemetry.render_json(
                telemetry.get_registry().snapshot()
            )
            # per-host snapshot always (fleet merge input); the legacy
            # single-file name stays for host0 so existing tooling and
            # single-host runs see no change
            atomic_write_text(
                telemetry.snapshot_path(self.store.workflow_dir), rendered
            )
            if telemetry.host_id() == "host0":
                atomic_write_text(
                    self.store.workflow_dir / "metrics.json", rendered
                )
        except OSError:
            logger.debug("metrics snapshot write failed", exc_info=True)
        try:
            # same snapshot, durably: one timestamped sample per series
            # into the per-host tsdb segment (`tmx timeline` feeds on it)
            from tmlibrary_tpu import timeseries

            timeseries.flush_registry(self.store.workflow_dir)
        except Exception:
            logger.debug("tsdb flush failed", exc_info=True)
        try:
            # per-program roofline/compile attribution for `tmx perf`
            from tmlibrary_tpu import perf

            snap = perf.perf_snapshot()
            if snap["programs"]:
                atomic_write_text(
                    self.store.workflow_dir / "perf.json",
                    json.dumps(snap, indent=2) + "\n",
                )
        except OSError:
            logger.debug("perf snapshot write failed", exc_info=True)

    def _write_qc_profile(self) -> None:
        """Persist the run's QC profile (``qc.<host>.json``, plus the
        plain ``qc.json`` convenience copy on host0) — same layout
        discipline as the metrics snapshots.  QC has its own gate, so
        this writes even when telemetry is disabled."""
        from tmlibrary_tpu import qc as qc_mod

        profile = qc_mod.get_session().snapshot()
        if not profile:
            return  # QC off, or nothing observed
        try:
            qc_mod.write_profile(
                qc_mod.profile_path(self.store.workflow_dir), profile
            )
            if telemetry.host_id() == "host0":
                qc_mod.write_profile(
                    self.store.workflow_dir / "qc.json", profile
                )
        except OSError:
            logger.debug("qc profile write failed", exc_info=True)

    def _start_sampler(self):
        """Start the resource sampler thread for this run when telemetry
        is on and a sample period is configured; the heartbeat file lands
        next to the ledger so ``tmx workflow status`` and
        ``scripts/tpu_watch.py`` can spot a hung run."""
        from tmlibrary_tpu.config import cfg

        period = float(getattr(cfg, "resource_sample_period", 0) or 0)
        if not telemetry.enabled() or period <= 0:
            return None
        return telemetry.ResourceSampler(
            period,
            heartbeat_path=telemetry.heartbeat_path(self.store.workflow_dir),
        ).start()

    def _note_straggler(self, step_name: str, batch_index, result) -> None:
        """Emit a ``straggler`` ledger event when a batch summary carries
        device wall times whose max−min skew crosses the threshold.

        Runs on the engine thread right after the ``batch_done`` append —
        executor worker threads must never touch the ledger, so the device
        timings ride the batch result dict instead of being appended from
        ``block_batch``.  The live-registry counter is already bumped by
        :func:`telemetry.record_device_times` at block time; this only
        records the durable evidence."""
        if not telemetry.enabled() or not isinstance(result, dict):
            return
        times = result.get("device_wall_times")
        skew = result.get("straggler_skew_s")
        if not times or skew is None:
            return
        slowest = max(float(t) for t in times.values())
        if float(skew) <= telemetry.straggler_threshold(slowest):
            return
        extra = {}
        # scheduler's predicted per-shard work rides the same event so
        # the anomaly plane (canary.py) can tell data skew — predicted
        # AND actual both skewed — from a slow device (actual only)
        if result.get("predicted_shard_work"):
            extra["predicted_shard_work"] = [
                float(w) for w in result["predicted_shard_work"]
            ]
            extra["predicted_skew"] = float(result.get("predicted_skew", 0.0))
        self.ledger.append(
            step=step_name, event="straggler", batch=batch_index,
            skew_s=float(skew), device_wall_times=times, **extra,
        )

    def _note_qc(self, step_name: str, batch_index, result) -> int:
        """Emit ``qc_batch`` (+ one ``qc_site`` per flagged site) ledger
        events when a batch summary carries a QC summary.

        Same thread discipline as :meth:`_note_straggler`: the QC
        evidence rides the batch result dict from the persist worker,
        and only the engine thread appends to the ledger.  QC flags are
        observability, not control flow — they reuse the quarantine
        machinery's *ledger* surface without ever failing a batch.
        Returns the number of sites flagged by this batch."""
        if not isinstance(result, dict):
            return 0
        summary = result.get("qc")
        if not isinstance(summary, dict):
            return 0
        flagged = summary.get("flagged_sites") or []
        self.ledger.append(
            step=step_name, event="qc_batch", batch=batch_index,
            summary={k: v for k, v in summary.items()
                     if k != "flagged_sites"},
        )
        for site in flagged:
            self.ledger.append(
                step=step_name, event="qc_site", batch=batch_index,
                **{k: v for k, v in site.items() if k != "step"},
            )
        return len(flagged)

    # ---------------------------------------------------------- batch level
    def _exec_batch(self, step, batch: dict) -> dict:
        faults.maybe_fire("batch_run", step=step.name, batch=batch["index"])
        return step.run_batch(batch)

    def _retry_after(self, step, batch: dict, first_exc: Exception,
                     policy: RetryPolicy) -> RetryOutcome:
        """Fold an already-observed failure into the retry budget and run
        the remaining attempts sequentially."""
        cls = classify(first_exc)
        if cls is PERMANENT or policy.max_attempts <= 1:
            return RetryOutcome(error=first_exc, attempts=1,
                                classification=cls)
        remaining = dataclasses.replace(
            policy, max_attempts=policy.max_attempts - 1
        )
        out = retry_call(
            lambda: self._exec_batch(step, batch), remaining,
            describe=f"{step.name} batch {batch['index']}",
        )
        out.attempts += 1
        return out

    def _iter_outcomes(self, step, pending: list[dict],
                       policy: RetryPolicy,
                       pstats: PipelineStats | None = None):
        """Yield ``(batch, RetryOutcome)`` for every pending batch.

        Prefers the deep pipelined executor (``pstats`` carries the
        resolved depth) for steps exposing the launch/persist split, then
        the step's own ``run_batches_pipelined`` generator; after a
        pipeline fault the failing batch is retried and the remainder
        degrades to sequential execution — per-batch isolation beats
        overlap once the device is flaky.  With a fault plan targeting a
        pre-persist site armed the sequential path is used from the
        start, so those faults fire *before* a batch persists (the
        pipelined paths persist a batch before the engine sees it);
        ``persist``-site plans keep the real executor.  Both paths poll
        the preemption flag at batch boundaries and surface a drain as
        :class:`PreemptedError` — never as a batch failure."""
        gen = None
        if pstats is not None and pending:
            executor = PipelinedExecutor(
                step, depth=pstats.depth, depth_source=pstats.source,
                on_event=lambda **ev: self.ledger.append(
                    step=step.name, **ev
                ),
                stats=pstats,
                should_stop=self._should_stop,
                watchdog=self._watchdog,
                # compile-ahead speculation (aotstore plane): steps that
                # expose the hook warm the likely next capacity rungs on
                # a background thread once the window starts filling
                warm_hook=getattr(step, "speculate_ahead", None),
            )
            gen = executor.run(pending)
        elif (hasattr(step, "run_batches_pipelined") and pending
                and not faults.sequential_forced()):
            gen = iter(step.run_batches_pipelined(pending))
        pos = 0
        while pos < len(pending):
            if gen is not None:
                try:
                    batch, result = next(gen)
                except StopIteration:
                    break
                except Exception as e:
                    if isinstance(e, FaultInjected) and e.fatal:
                        raise
                    if isinstance(e, PreemptedError):
                        raise  # drained cleanly — not a batch failure
                    # the pipeline died mid-flight: the first unyielded
                    # batch is the one it was working on
                    logger.warning(
                        "%s: pipelined runner failed at batch %d — "
                        "degrading to sequential execution",
                        step.name, pending[pos]["index"],
                    )
                    gen = None
                    yield pending[pos], self._retry_after(
                        step, pending[pos], e, policy
                    )
                    pos += 1
                    continue
                yield batch, RetryOutcome(value=result, attempts=1)
                pos += 1
            else:
                batch = pending[pos]
                if self._should_stop():
                    raise PreemptedError(
                        f"preempted before batch {batch['index']} of "
                        f"'{step.name}': abandoned {len(pending) - pos} "
                        f"pending batches",
                        step=step.name, abandoned=len(pending) - pos,
                        reason=self._stop_reason(),
                    )
                try:
                    yield batch, RetryOutcome(
                        value=self._exec_batch(step, batch), attempts=1
                    )
                except Exception as e:
                    if isinstance(e, FaultInjected) and e.fatal:
                        raise
                    yield batch, self._retry_after(step, batch, e, policy)
                pos += 1

    @staticmethod
    def _call_collect(step, results: list[dict]):
        """Pass the surviving batch results to ``collect`` when the step
        accepts them (newer signature); legacy ``collect(self)`` steps
        keep working."""
        try:
            params = inspect.signature(step.collect).parameters
        except (TypeError, ValueError):
            params = {}
        if "results" in params:
            return step.collect(results=results)
        return step.collect()

    # ----------------------------------------------------------- step level
    def _run_step(self, sd: WorkflowStepDescription, resume: bool) -> dict:
        step_cls = get_step(sd.name)
        step = step_cls(self.store)
        res = self.resilience
        policy = (res.policy if res.enabled
                  else RetryPolicy(max_attempts=1, base_delay=0.0))
        t0 = time.time()
        current_batch: int | None = None
        try:
            existing = step.list_batches() if resume else []
            quarantined: set[int] = set()
            if existing:
                batches = [step.load_batch(i) for i in existing]
                done = self.ledger.completed_batches(sd.name)
                quarantined = self.ledger.quarantined_batches(sd.name)
                # if the description's args changed since the batches were
                # planned, the old plan is stale — re-init from scratch
                if batches and step.batch_args.resolve(sd.args) != batches[0]["args"]:
                    logger.info("resume: args changed for %s, re-planning", sd.name)
                    existing = []
            if not existing:
                batches = step.init(sd.args)
                batches = [step.load_batch(i) for i in range(len(batches))]
                done = set()
                quarantined = set()
                self.ledger.append(step=sd.name, event="init_done",
                                   n_batches=len(batches))
            # durable schedule-plan provenance: whenever the step planned
            # its batches with the work-model scheduler, the plan digest
            # (and its predicted occupancy/skew deltas) lands in the
            # ledger — on --resume the same event re-appends from the
            # plan side file, so convergence is auditable from the
            # ledger alone (bit-identical digests across attempts)
            plan_info = getattr(step, "schedule_plan_info", None)
            if callable(plan_info):
                try:
                    info = plan_info()
                except Exception:
                    info = None
                if info:
                    self.ledger.append(
                        step=sd.name, event="schedule_plan", **info
                    )
            pending = [b for b in batches if b["index"] not in done]
            # quarantined batches first: the most suspect work re-runs at
            # the start of the resume, while everything else still follows
            pending.sort(key=lambda b: (b["index"] not in quarantined,
                                        b["index"]))
            if quarantined:
                logger.info("resume: re-attempting quarantined batches %s "
                            "of %s first", sorted(quarantined), sd.name)
            results: list[dict] = []
            failed: list[dict] = []
            budget = res.failure_budget(len(batches)) if res.enabled else 0
            # QC flag budget: a warn-only threshold over the step's
            # planned site count (resilience.qc_flag_budget fraction)
            qc_flagged = 0
            qc_budget_noted = False
            qc_sites_total = sum(len(b.get("sites") or []) for b in batches)
            qc_site_budget = (
                int(res.qc_flag_budget * qc_sites_total)
                if res.enabled and qc_sites_total else 0
            )
            pstats = None
            if (pending and supports_pipelining(step)
                    and not faults.sequential_forced()):
                depth, source = resolve_pipeline_depth(
                    explicit=self.pipeline_depth
                )
                pstats = PipelineStats(depth, source, step=sd.name)
                logger.info(
                    "%s: pipelined executor, in-flight depth %d (source: "
                    "%s)", sd.name, depth, source,
                )
            metrics = telemetry.get_registry()
            bt0 = time.time()
            with step.capture_logs("run"):  # per-step log file (§6)
                for batch, outcome in self._iter_outcomes(step, pending,
                                                          policy, pstats):
                    current_batch = batch["index"]
                    self._drain_watchdog(sd.name)
                    self._drain_compile_spans(sd.name)
                    if outcome.ok:
                        b_elapsed = time.time() - bt0
                        if telemetry.enabled():
                            self.ledger.append(
                                step=sd.name, event="span", span="batch",
                                batch=batch["index"], t0=round(bt0, 6),
                                elapsed=round(b_elapsed, 6),
                            )
                        self.ledger.append_batch_done(
                            sd.name, batch["index"],
                            elapsed=b_elapsed,
                            attempts=outcome.attempts,
                            result=outcome.value)
                        # only device-dispatching steps (the launch/
                        # block/persist protocol — where the XLA
                        # compiles live) count: a metaconfig batch
                        # landing in milliseconds would mask the
                        # cold-start this metric exists to expose
                        if (not getattr(self, "_first_batch_noted", True)
                                and getattr(self, "_run_wall_t0", None)
                                and hasattr(step, "launch_batch")):
                            self._first_batch_noted = True
                            ttfb = time.time() - self._run_wall_t0
                            # NOT batch= : any step+batch event mints a
                            # batch node in build_span_tree, and this
                            # marker is an instant, not a span
                            self.ledger.append(
                                step=sd.name, event="first_batch",
                                first_batch_index=batch["index"],
                                time_to_first_batch_s=round(ttfb, 6),
                            )
                            metrics.gauge(
                                "tmx_time_to_first_batch_seconds"
                            ).set(round(ttfb, 6))
                        self._note_straggler(sd.name, batch["index"],
                                             outcome.value)
                        qc_flagged += self._note_qc(sd.name, batch["index"],
                                                    outcome.value)
                        if (qc_site_budget and not qc_budget_noted
                                and qc_flagged > qc_site_budget):
                            # the QC flag budget warns, it never fails:
                            # bad inputs are a human decision, not a
                            # scheduler one (quarantine stays reserved
                            # for execution failures)
                            qc_budget_noted = True
                            self.ledger.append(
                                step=sd.name, event="qc_budget_exceeded",
                                flagged=qc_flagged, budget=qc_site_budget,
                            )
                            metrics.counter(
                                "tmx_qc_budget_exceeded_total",
                                step=sd.name).inc()
                            logger.warning(
                                "%s: QC flagged %d sites — more than the "
                                "configured budget (%d); inspect with "
                                "`tmx qc`", sd.name, qc_flagged,
                                qc_site_budget,
                            )
                        metrics.counter("tmx_batches_done_total",
                                        step=sd.name).inc()
                        metrics.histogram("tmx_batch_seconds",
                                          step=sd.name).observe(b_elapsed)
                        if outcome.attempts > 1:
                            metrics.counter("tmx_batch_retries_total",
                                            step=sd.name).inc(
                                                outcome.attempts - 1)
                        results.append(outcome.value)
                        bt0 = time.time()
                        continue
                    failure = {
                        "batch": batch["index"],
                        "error": str(outcome.error),
                        "exception": type(outcome.error).__name__,
                        "attempts": outcome.attempts,
                        "classification": outcome.classification,
                    }
                    self.ledger.append(step=sd.name, event="batch_failed",
                                       **failure)
                    metrics.counter("tmx_batches_failed_total",
                                    step=sd.name).inc()
                    metrics.counter("tmx_batches_quarantined_total",
                                    step=sd.name).inc()
                    failed.append(failure)
                    bt0 = time.time()
                    if len(failed) > budget:
                        raise WorkflowError(
                            f"step '{sd.name}': {len(failed)} failed "
                            f"batches exceeds the quarantine budget "
                            f"({budget} of {len(batches)})"
                        ) from outcome.error
                    logger.error(
                        "%s: batch %d quarantined after %d attempt(s) "
                        "(%s: %s) — step continues (%d/%d budget used)",
                        sd.name, batch["index"], outcome.attempts,
                        failure["exception"], failure["error"],
                        len(failed), budget,
                    )
                # collect is part of the step execution the log file
                # covers; it sees only the surviving results
                collected = self._call_collect(step, results)
            self._drain_compile_spans(sd.name)
            metrics.histogram("tmx_step_seconds", step=sd.name).observe(
                time.time() - t0
            )
            extra = ({"pipeline_stats": pstats.summary()}
                     if pstats is not None else {})
            if failed:
                # no step_done: resume re-attempts the quarantined
                # batches first, then re-collects
                self.ledger.append(
                    step=sd.name, event="step_partial",
                    elapsed=time.time() - t0, collected=collected,
                    quarantined=sorted(f["batch"] for f in failed),
                    **extra,
                )
                metrics.counter("tmx_steps_partial_total",
                                step=sd.name).inc()
                return {"n_batches": len(batches), "collected": collected,
                        "quarantined": sorted(f["batch"] for f in failed)}
            self.ledger.append(step=sd.name, event="step_done",
                               elapsed=time.time() - t0, collected=collected,
                               **extra)
            metrics.counter("tmx_steps_done_total", step=sd.name).inc()
            return {"n_batches": len(batches), "collected": collected}
        except PreemptedError as e:
            # a drain, not a failure: the ledger boundary is clean, so no
            # step_failed — record the drain summary and surface the
            # pinned-exit-code path (cli → EXIT_PREEMPTED → resume)
            if e.step is None:
                e.step = sd.name
            if e.reason == "signal":
                # the executor's drain path doesn't know which signal
                # (or deadline) tripped the flag — the stop-reason hook
                # does
                e.reason = self._stop_reason()
            self._note_preempted(e)
        except FaultInjected as e:
            if e.fatal:
                raise  # simulated hard crash: no further ledger writes
            self.ledger.append(step=sd.name, event="step_failed",
                               error=str(e), exception=type(e).__name__,
                               batch=current_batch)
            telemetry.get_registry().counter("tmx_steps_failed_total",
                                             step=sd.name).inc()
            raise WorkflowError(f"step '{sd.name}' failed: {e}") from e
        except WorkflowError as e:
            # e.g. the quarantine budget overflow above; keep the original
            # exception class visible in the ledger via __cause__
            self.ledger.append(step=sd.name, event="step_failed",
                               error=str(e),
                               exception=type(e.__cause__ or e).__name__,
                               batch=current_batch)
            telemetry.get_registry().counter("tmx_steps_failed_total",
                                             step=sd.name).inc()
            raise
        except Exception as e:
            self.ledger.append(step=sd.name, event="step_failed",
                               error=str(e), exception=type(e).__name__,
                               batch=current_batch)
            telemetry.get_registry().counter("tmx_steps_failed_total",
                                             step=sd.name).inc()
            raise WorkflowError(f"step '{sd.name}' failed: {e}") from e
