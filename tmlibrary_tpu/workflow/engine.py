"""Workflow engine: stage/step DAG execution with ledger-backed resume.

Reference parity: ``tmlib/workflow/workflow.py`` (``Workflow`` →
``WorkflowStage`` → ``WorkflowStep`` = init → run → collect, driven through
GC3Pie ``next()`` transitions), ``description.py`` (YAML-serializable
workflow description validated against the step registry),
``dependencies.py`` (canonical stage order) and
``manager.py``/``submission.py`` (DB-backed submission state + ``resume``).

TPU redesign (SURVEY.md §4.1): no process fan-out — stages iterate in one
process dispatching batched device programs; the JSON-lines run ledger
replaces the ``Submission``/``Task`` tables: every init/run/collect event
is appended with timing, and ``resume`` replays the ledger to skip
completed work.  Idempotence still comes from each step's
``delete_previous_output`` + deterministic batch plans, exactly the
reference's contract.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any

import yaml

from tmlibrary_tpu.errors import WorkflowError
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.workflow.registry import get_step, list_steps

logger = logging.getLogger(__name__)

#: workflow-type stage DAGs (reference ``tmlib/workflow/dependencies.py``:
#: ``CanonicalWorkflowDependencies`` and ``MultiplexingWorkflowDependencies``)
#: — conversion → preprocessing → pyramid → analysis; the multiplexing type
#: adds inter-cycle registration (``align``) to the preprocessing stage.
WORKFLOW_TYPES: dict[str, list[tuple[str, list[str]]]] = {
    "canonical": [
        ("image_conversion", ["metaconfig", "imextract"]),
        ("image_preprocessing", ["corilla"]),
        ("pyramid_creation", ["illuminati"]),
        ("image_analysis", ["jterator"]),
    ],
    "multiplexing": [
        ("image_conversion", ["metaconfig", "imextract"]),
        ("image_preprocessing", ["corilla", "align"]),
        ("pyramid_creation", ["illuminati"]),
        ("image_analysis", ["jterator"]),
    ],
}

#: back-compat alias: the widest stage DAG (multiplexing superset)
CANONICAL_STAGES = WORKFLOW_TYPES["multiplexing"]


@dataclasses.dataclass
class WorkflowStepDescription:
    name: str
    args: dict[str, Any] = dataclasses.field(default_factory=dict)
    active: bool = True


@dataclasses.dataclass
class WorkflowStageDescription:
    name: str
    steps: list[WorkflowStepDescription]


@dataclasses.dataclass
class WorkflowDescription:
    """YAML-serializable workflow plan (reference ``WorkflowDescription``)."""

    stages: list[WorkflowStageDescription]

    def validate(self) -> None:
        known = set(list_steps())
        for stage in self.stages:
            for step in stage.steps:
                if step.name not in known:
                    raise WorkflowError(
                        f"workflow references unknown step '{step.name}' "
                        f"(registered: {sorted(known)})"
                    )

    def active_steps(self) -> list[WorkflowStepDescription]:
        return [s for st in self.stages for s in st.steps if s.active]

    # ------------------------------------------------------------- serialize
    def to_dict(self) -> dict:
        return {
            "stages": [
                {
                    "name": st.name,
                    "steps": [
                        {"name": s.name, "args": s.args, "active": s.active}
                        for s in st.steps
                    ],
                }
                for st in self.stages
            ]
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkflowDescription":
        return cls(
            stages=[
                WorkflowStageDescription(
                    name=st["name"],
                    steps=[
                        WorkflowStepDescription(
                            name=s["name"],
                            args=s.get("args", {}) or {},
                            active=bool(s.get("active", True)),
                        )
                        for st_s in [st.get("steps", [])]
                        for s in st_s
                    ],
                )
                for st in d.get("stages", [])
            ]
        )

    @classmethod
    def load(cls, path: Path) -> "WorkflowDescription":
        return cls.from_dict(yaml.safe_load(Path(path).read_text()))

    def save(self, path: Path) -> None:
        Path(path).write_text(yaml.safe_dump(self.to_dict(), sort_keys=False))

    @classmethod
    def for_type(
        cls,
        workflow_type: str,
        step_args: dict[str, dict] | None = None,
    ) -> "WorkflowDescription":
        """Build a description for a registered workflow type
        (``canonical`` | ``multiplexing``); ``step_args`` maps step name →
        args, and only steps with args are active (inactive steps stay in
        the plan so they can be toggled on later)."""
        if workflow_type not in WORKFLOW_TYPES:
            raise WorkflowError(
                f"unknown workflow type '{workflow_type}' "
                f"(registered: {sorted(WORKFLOW_TYPES)})"
            )
        step_args = step_args or {}
        return cls(
            stages=[
                WorkflowStageDescription(
                    name=stage,
                    steps=[
                        WorkflowStepDescription(
                            name=s,
                            args=step_args.get(s, {}),
                            active=s in step_args,
                        )
                        for s in steps
                    ],
                )
                for stage, steps in WORKFLOW_TYPES[workflow_type]
            ]
        )

    @classmethod
    def canonical(cls, step_args: dict[str, dict] | None = None) -> "WorkflowDescription":
        """The four-stage workflow, auto-typed: requesting ``align`` args
        selects the multiplexing variant (the only type that runs
        inter-cycle registration)."""
        wtype = "multiplexing" if "align" in (step_args or {}) else "canonical"
        return cls.for_type(wtype, step_args)


class RunLedger:
    """Append-only JSON-lines event log (replaces the reference's
    ``Submission``/``Task`` tables)."""

    def __init__(self, path: Path):
        self.path = Path(path)

    def append(self, **event) -> None:
        event["ts"] = time.time()
        with open(self.path, "a") as f:
            f.write(json.dumps(event) + "\n")

    def events(self) -> list[dict]:
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_text().splitlines():
            if line.strip():
                out.append(json.loads(line))
        return out

    def completed_steps(self) -> set[str]:
        return {e["step"] for e in self.events() if e.get("event") == "step_done"}

    def completed_batches(self, step: str) -> set[int]:
        done = set()
        for e in self.events():
            if e.get("step") != step:
                continue
            if e.get("event") == "batch_done":
                done.add(e["batch"])
            elif e.get("event") == "init_done":
                # a re-init invalidates earlier batch completions
                done.clear()
        return done

    def status(self) -> dict[str, Any]:
        steps: dict[str, dict] = {}
        for e in self.events():
            s = e.get("step")
            if not s:
                continue
            entry = steps.setdefault(
                s, {"state": "pending", "batches_done": 0, "n_batches": None,
                    "elapsed": 0.0}
            )
            if e["event"] == "init_done":
                entry.update(state="running", n_batches=e.get("n_batches"),
                             batches_done=0)
            elif e["event"] == "batch_done":
                entry["batches_done"] += 1
                entry["elapsed"] += e.get("elapsed", 0.0)
            elif e["event"] == "step_done":
                entry["state"] = "done"
            elif e["event"] == "step_failed":
                entry["state"] = "failed"
                entry["error"] = e.get("error")
        return steps


class Workflow:
    """Execute a workflow description against an experiment store."""

    def __init__(self, store: ExperimentStore, description: WorkflowDescription):
        description.validate()
        self.store = store
        self.description = description
        self.ledger = RunLedger(store.workflow_dir / "ledger.jsonl")

    def run(self, resume: bool = False) -> dict:
        """Run all active steps in order; with ``resume=True`` skip completed
        steps and completed batches of the interrupted step (reference
        ``resume`` CLI verb backed by DB task state)."""
        if not resume and self.ledger.path.exists():
            self.ledger.path.unlink()
        done_steps = self.ledger.completed_steps() if resume else set()
        summary = {}
        for stage in self.description.stages:
            for sd in stage.steps:
                if not sd.active:
                    continue
                if sd.name in done_steps:
                    logger.info("resume: skipping completed step %s", sd.name)
                    continue
                summary[sd.name] = self._run_step(sd, resume)
        return summary

    def _run_step(self, sd: WorkflowStepDescription, resume: bool) -> dict:
        step_cls = get_step(sd.name)
        step = step_cls(self.store)
        t0 = time.time()
        try:
            existing = step.list_batches() if resume else []
            if existing:
                batches = [step.load_batch(i) for i in existing]
                done = self.ledger.completed_batches(sd.name)
                # if the description's args changed since the batches were
                # planned, the old plan is stale — re-init from scratch
                if batches and step.batch_args.resolve(sd.args) != batches[0]["args"]:
                    logger.info("resume: args changed for %s, re-planning", sd.name)
                    existing = []
            if not existing:
                batches = step.init(sd.args)
                batches = [step.load_batch(i) for i in range(len(batches))]
                done = set()
                self.ledger.append(step=sd.name, event="init_done",
                                   n_batches=len(batches))
            results = []
            pending = [b for b in batches if b["index"] not in done]
            if hasattr(step, "run_batches_pipelined"):
                # device-async pipelining: host IO of adjacent batches runs
                # in the shadow of device compute (see the step's docstring)
                runs = step.run_batches_pipelined(pending)
            else:
                runs = ((b, step.run_batch(b)) for b in pending)
            bt0 = time.time()
            with step.capture_logs("run"):  # per-step log file (§6)
                for batch, result in runs:
                    self.ledger.append(step=sd.name, event="batch_done",
                                       batch=batch["index"],
                                       elapsed=time.time() - bt0, result=result)
                    results.append(result)
                    bt0 = time.time()
                # collect is part of the step execution the log file covers
                collected = step.collect()
            self.ledger.append(step=sd.name, event="step_done",
                               elapsed=time.time() - t0, collected=collected)
            return {"n_batches": len(batches), "collected": collected}
        except Exception as e:
            self.ledger.append(step=sd.name, event="step_failed", error=str(e))
            raise WorkflowError(f"step '{sd.name}' failed: {e}") from e
