"""Unified command-line interface.

Reference parity: ``tmlib/workflow/cli.py`` + per-step console scripts
(``metaconfig``, ``imextract``, ``corilla``, ``align``, ``illuminati``,
``jterator``) and ``tm_workflow`` (``manager.py``) — argparse verbs
``init`` / ``run`` / ``collect`` / ``submit`` / ``resume`` / ``status`` /
``log`` / ``cleanup`` / ``info`` (SURVEY.md §2 row 1).

Here the per-step scripts fold into one ``tmx`` entry point::

    tmx create  --root DIR --name NAME
    tmx <step>  init    --root DIR [step args...]
    tmx <step>  run     --root DIR --job N
    tmx <step>  collect --root DIR
    tmx <step>  info    --root DIR
    tmx workflow submit --root DIR [--description wf.yaml] [--resume]
    tmx workflow status --root DIR
    tmx log     --root DIR [--tail N]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

from tmlibrary_tpu.log import configure_logging
from tmlibrary_tpu.models.experiment import Experiment
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.workflow.engine import (
    RunLedger,
    Workflow,
    WorkflowDescription,
)
from tmlibrary_tpu.workflow.registry import get_step, list_steps


def _add_common(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--root", required=True, help="experiment store directory")
    parser.add_argument("-v", "--verbosity", action="count", default=0)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tmx", description="TPU-native microscopy image analysis"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_create = sub.add_parser("create", help="create an empty experiment store")
    _add_common(p_create)
    p_create.add_argument("--name", required=True)

    p_inspect = sub.add_parser(
        "inspect",
        help="print a microscope file's dimensions/channels (the "
             "Bio-Formats 'showinf' role, on the native parsers)")
    p_inspect.add_argument("files", nargs="+")
    p_inspect.add_argument("--json", action="store_true", dest="as_json",
                           help="one JSON object per file")

    p_log = sub.add_parser("log", help="show the run ledger or captured step logs")
    _add_common(p_log)
    p_log.add_argument("--tail", type=int, default=20)
    p_log.add_argument("--step", default=None,
                       help="print a step's captured log file instead")
    p_log.add_argument("--job", type=int, default=None,
                       help="batch index (with --step); omit for the "
                            "whole-step run log")

    p_export = sub.add_parser(
        "export", help="export feature tables / polygons / illumination stats"
    )
    _add_common(p_export)
    p_export.add_argument("--objects", default=None, help="object type name")
    p_export.add_argument(
        "--illumstats", type=int, default=None, metavar="CHANNEL",
        help="instead of a feature table, write this channel's illumination "
             "statistics as an HDF5 file with the reference IllumstatsFile "
             "layout (mutually exclusive with --objects)",
    )
    p_export.add_argument(
        "--cycle", type=int, default=0,
        help="acquisition cycle for --illumstats/--images (default 0)",
    )
    p_export.add_argument(
        "--images", type=int, default=None, metavar="CHANNEL",
        help="instead of a feature table, write this channel's site images "
             "as uint16 TIFFs into --out (a directory), named with the "
             "canonical <well>_s<site>_... pattern",
    )
    p_export.add_argument(
        "--correct", action="store_true",
        help="--images only: apply illumination correction (corilla stats)",
    )
    p_export.add_argument(
        "--align", action="store_true",
        help="--images only: apply cycle alignment shifts + intersection crop",
    )
    p_export.add_argument(
        "--ome", action="store_true",
        help="--images only: write OME-TIFFs (OME-XML in ImageDescription, "
             "the Bio-Formats convention) instead of bare TIFFs",
    )
    p_export.add_argument(
        "--ngff", action="store_true",
        help="write the WHOLE experiment as an OME-NGFF (OME-Zarr v0.4) "
             "HCS plate into --out (a directory, conventionally *.zarr): "
             "every channel/tpoint/zplane as multiscale tczyx fields; the "
             "exported plate re-ingests via the ngff metaconfig handler",
    )
    p_export.add_argument(
        "--ngff-levels", type=int, default=3, metavar="N",
        help="--ngff only: number of 2x multiscale levels (default 3)",
    )
    p_export.add_argument(
        "--ngff-labels", default=None, metavar="NAME[,NAME...]",
        help="--ngff only: also export these segmentation stacks as NGFF "
             "image-label multiscales under each field's labels/ group",
    )
    p_export.add_argument("--out", required=True, help="output file path")
    p_export.add_argument(
        "--format", choices=("csv", "parquet", "geojson"), default=None,
        help="inferred from --out suffix when omitted; geojson exports the "
             "traced object polygons (run jterator with --as-polygons)",
    )
    p_export.add_argument(
        "--join-features", default=None, metavar="COL[,COL...]",
        help="geojson only: join these measurement columns onto each "
             "polygon's properties by (site, label) — viewer-ready colored "
             "overlays (reference: tmserver joins FeatureValues onto "
             "mapobjects)",
    )
    p_export.add_argument(
        "--simplify", type=float, default=0.0, metavar="TOL",
        help="geojson only: Douglas-Peucker-simplify polygon rings to this "
             "perpendicular-distance tolerance in pixels (reference: PostGIS "
             "geometry simplification for viewer-scale objects)",
    )

    p_metrics = sub.add_parser(
        "metrics",
        help="export run metrics (Prometheus textfile or JSON) from the "
             "live registry snapshot or derived from any run ledger",
    )
    # --root is optional here (unlike _add_common): `tmx metrics --merge`
    # takes the run root positionally and needs no open store
    p_metrics.add_argument("--root", default=None,
                           help="experiment store directory")
    p_metrics.add_argument("-v", "--verbosity", action="count", default=0)
    p_metrics.add_argument(
        "--merge", default=None, metavar="RUN_ROOT",
        help="merge every per-host workflow/metrics.<host>.json under this "
             "run root into one fleet view (adds host labels)",
    )
    p_metrics.add_argument(
        "--format", choices=("prom", "json"), default="prom",
        help="Prometheus textfile exposition format (default) or JSON",
    )
    p_metrics.add_argument(
        "--source", choices=("auto", "snapshot", "ledger"), default="auto",
        help="'snapshot' reads the registry snapshot the last submit wrote "
             "(workflow/metrics.json); 'ledger' derives metrics from the "
             "run ledger (works for runs that predate telemetry); 'auto' "
             "prefers the snapshot and falls back to the ledger",
    )
    p_metrics.add_argument("--out", default=None,
                           help="write to this file instead of stdout")

    p_top = sub.add_parser(
        "top",
        help="live fleet dashboard over a run's heartbeat + metrics "
             "snapshot files (curses-free repaint loop; --once for CI)",
    )
    _add_common(p_top)
    p_top.add_argument("--interval", type=float, default=2.0,
                       help="repaint period in seconds (default 2.0)")
    p_top.add_argument("--once", action="store_true",
                       help="render a single frame and exit (tests/CI)")
    p_top.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the fleet view as JSON (implies --once) "
                            "so CI and tpu_watch can assert on dashboard "
                            "state without screen-scraping")

    p_timeline = sub.add_parser(
        "timeline",
        help="metric history from the durable time-series "
             "(tsdb.<host>.jsonl segments): per-series sparklines with "
             "last/rate summaries; ledger-replay fallback for roots that "
             "predate the tsdb",
    )
    _add_common(p_timeline)
    p_timeline.add_argument("--metric", default=None, metavar="NAME",
                            help="restrict to series whose metric name "
                                 "contains this substring")
    p_timeline.add_argument("--window", type=float, default=None,
                            metavar="SECONDS",
                            help="rate window for counter series "
                                 "(default: full history)")
    p_timeline.add_argument("--width", type=int, default=48,
                            help="sparkline width in columns (default 48)")
    p_timeline.add_argument("--json", action="store_true", dest="as_json",
                            help="emit the merged series as JSON")

    p_trace = sub.add_parser(
        "trace",
        help="dump the run's span tree (run > step > batch > phase) with "
             "critical-path annotation from the run ledger, or export a "
             "Chrome trace; accepts experiment AND serve roots",
    )
    _add_common(p_trace)
    p_trace.add_argument("--json", action="store_true", dest="as_json",
                         help="emit the annotated tree as JSON")
    p_trace.add_argument("--export", choices=("chrome",), default=None,
                         help="export format: 'chrome' writes Trace Event "
                              "Format JSON (chrome://tracing / Perfetto)")
    p_trace.add_argument("out", nargs="?", default=None,
                         help="output path for --export (default "
                              "trace.json)")
    p_trace.add_argument("--trace-id", default=None,
                         help="restrict the export to one job's trace id")

    p_perf = sub.add_parser(
        "perf",
        help="per-program roofline/compile attribution from the last run "
             "(`tmx perf --root DIR`), or the bench history + regression "
             "verdict (`tmx perf history`)",
    )
    # --root is optional here (unlike _add_common): `tmx perf history`
    # reads tuning/BENCH_HISTORY.jsonl, no experiment store involved
    p_perf.add_argument("--root", default=None,
                        help="experiment store directory (roofline table + "
                             "phase breakdown from its last run)")
    p_perf.add_argument("-v", "--verbosity", action="count", default=0)
    p_perf.add_argument("--top", type=int, default=10,
                        help="show the N costliest programs (default 10)")
    p_perf.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the attribution as JSON")
    perf_sub = p_perf.add_subparsers(dest="verb")
    p_phist = perf_sub.add_parser(
        "history",
        help="bench history tail + sentinel verdict (latest vs best "
             "comparable record)",
    )
    p_phist.add_argument("--history", default=None,
                         help="history file (default tuning/"
                              "BENCH_HISTORY.jsonl, BENCH_HISTORY env)")
    p_phist.add_argument("--config", default=None,
                         help="judge this bench config only")
    p_phist.add_argument("--metric", default=None,
                         help="judge this metric only")
    p_phist.add_argument("--threshold", type=float, default=0.05,
                         help="regression/improvement fraction "
                              "(default 0.05)")
    p_phist.add_argument("--stale-hours", type=float, default=None,
                         dest="stale_hours",
                         help="staleness budget (default BENCH_STALE_HOURS "
                              "or 72)")
    p_phist.add_argument("--tail", type=int, default=10,
                         help="history lines to print (default 10)")

    p_cache = sub.add_parser(
        "cache",
        help="the serialized-executable store (aotstore): list entries "
             "or garbage-collect stale/oversize artifacts",
    )
    p_cache.add_argument("-v", "--verbosity", action="count", default=0)
    cache_sub = p_cache.add_subparsers(dest="verb", required=True)
    p_clist = cache_sub.add_parser(
        "list", help="store entries, most recently used first")
    p_clist.add_argument("--dir", default=None, dest="store_dir",
                         help="store directory (default TMX_AOT_STORE_DIR, "
                              "config aot_store_dir, or ~/.cache)")
    p_clist.add_argument("--json", action="store_true", dest="as_json",
                         help="emit entries + stats as JSON (CI manifest)")
    p_cgc = cache_sub.add_parser(
        "gc", help="evict stale-fingerprint, over-age and over-cap entries")
    p_cgc.add_argument("--dir", default=None, dest="store_dir",
                       help="store directory (default TMX_AOT_STORE_DIR, "
                            "config aot_store_dir, or ~/.cache)")
    p_cgc.add_argument("--max-bytes", type=int, default=None,
                       dest="max_bytes",
                       help="LRU size cap to enforce (default the "
                            "configured store cap)")
    p_cgc.add_argument("--max-age-days", type=float, default=None,
                       dest="max_age_days",
                       help="drop entries unused for this many days")
    p_cgc.add_argument("--keep-stale", action="store_true",
                       dest="keep_stale",
                       help="keep entries from other jax/backend "
                            "fingerprints (default: drop them)")
    p_cgc.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the gc summary as JSON")

    p_qc = sub.add_parser(
        "qc",
        help="data-quality report for a run (per-step table, worst-focus "
             "sites, flagged sites) + drift verdict vs a reference "
             "profile; exit codes: 0 ok, 1 drift, 2 stale reference, "
             "3 no reference",
    )
    _add_common(p_qc)
    p_qc.add_argument("--json", action="store_true", dest="as_json",
                      help="emit the QC report + verdict as JSON")
    p_qc.add_argument("--worst", type=int, default=5, metavar="N",
                      help="worst-focus sites to list (default 5)")
    p_qc.add_argument("--reference", default=None, metavar="PATH",
                      help="reference qc.json profile for the drift "
                           "sentinel (default: TMX_QC_BASELINE env, then "
                           "tuning/QC_BASELINE.json if present)")
    p_qc.add_argument("--threshold", type=float, default=0.25,
                      help="drift threshold: allowed median shift as a "
                           "fraction of the reference spread "
                           "(default 0.25)")
    p_qc.add_argument("--stale-hours", type=float, default=None,
                      dest="stale_hours",
                      help="reference staleness budget in hours (default "
                           "TMX_QC_STALE_HOURS, 0 = no staleness check — "
                           "committed baselines age by design)")
    p_qc.add_argument("--profile-kind", choices=("run", "model"),
                      default="run", dest="profile_kind",
                      help="what to compare: 'run' = acquisition + "
                           "feature drift (the default); 'model' = only "
                           "the __model__.* sketches (DL flow-magnitude/"
                           "probability streams) vs the committed "
                           "checkpoint baseline (default reference "
                           "TMX_QC_DL_BASELINE env, then "
                           "tuning/QC_DL_BASELINE.json) — the model "
                           "deploy gate")

    p_weights = sub.add_parser(
        "weights",
        help="DL segmentation checkpoints (tmlibrary_tpu.nn): list the "
             "weights directory or digest a weight spec",
    )
    w_sub = p_weights.add_subparsers(dest="verb", required=True)
    p_wl = w_sub.add_parser(
        "list", help="inventory of the weights directory "
                     "(TMX_WEIGHTS_DIR) with content digests")
    p_wl.add_argument("--dir", default=None,
                      help="weights directory (default TMX_WEIGHTS_DIR)")
    p_wl.add_argument("--json", action="store_true", dest="as_json")
    p_wd = w_sub.add_parser(
        "digest", help="resolve a weight spec (name, path or seed:N) and "
                       "print its content digest — the identity the "
                       "compiled-program cache and the bench sentinel "
                       "key on")
    p_wd.add_argument("spec", help="checkpoint name, .npz path, or "
                                   "seed:N[:base=C][:depth=D]")
    p_wd.add_argument("--json", action="store_true", dest="as_json")

    p_wf = sub.add_parser("workflow", help="full workflow orchestration")
    wf_sub = p_wf.add_subparsers(dest="verb", required=True)
    # submit and resume (the reference's verb) share the same options and
    # code path; resume just defaults resume=True
    shared = argparse.ArgumentParser(add_help=False)
    shared.add_argument(
        "--description",
        help="workflow YAML (default: the store's workflow/workflow.yaml)",
    )
    shared.add_argument("--profile", metavar="DIR", default=None,
                        help="write a jax.profiler device trace to DIR")
    shared.add_argument(
        "--pipeline-depth", type=int, default=None, metavar="N",
        help="in-flight device batches for the pipelined executor "
             "(default: TM_PIPELINE_DEPTH / config, else the tuning "
             "sweep's best_pipeline on device backends, else a safe "
             "per-backend default; 1 = minimal overlap)",
    )
    shared.add_argument(
        "--reduction-strategy", default=None,
        choices=("auto", "onehot", "sort", "scatter", "fused"),
        help="grouped-reduction strategy for the measurement stack "
             "(default: TMX_REDUCTION_STRATEGY / TM_REDUCTION_STRATEGY "
             "config, else the bench sweep's tuned verdict in "
             "tuning/TUNING.json, else scatter on CPU and one-hot "
             "matmuls on accelerators; 'sort' is the exactly "
             "deterministic path, 'fused' the single-pass Pallas "
             "measure megakernels)",
    )
    shared.add_argument(
        "--object-buckets", default=None, metavar="SPEC",
        help="object-capacity bucket ladder for the jterator step "
             "(capacity.py): 'auto' compiles power-of-two capacity "
             "buckets up to max_objects and routes each batch by its "
             "observed object counts (bit-identical results, fewer "
             "padded-slot FLOPs), 'off' pins every batch at "
             "max_objects, or a comma list of capacities like '8,32' "
             "(default: TMX_OBJECT_BUCKETS / TM_OBJECT_BUCKETS config, "
             "else auto)",
    )
    shared.add_argument(
        "--schedule", default=None, metavar="MODE",
        choices=("auto", "pack", "off"),
        help="work-aware site scheduling for the jterator step "
             "(workflow/schedule.py): 'pack' predicts per-site object "
             "counts from prior-run history, packs rung-homogeneous "
             "batches and balances per-device shard work (bit-identical "
             "per-site results, higher slot occupancy, lower straggler "
             "skew), 'off' keeps directory-order batching, 'auto' "
             "follows TMX_SCHEDULE / TM_SCHEDULE config, else the "
             "provenance-gated tuning/TUNING.json verdict, else pack",
    )
    # fault-tolerance knobs (resilience.py; defaults from LibraryConfig /
    # TM_RETRY_ATTEMPTS, TM_MAX_BATCH_FAILURES, ... env)
    shared.add_argument(
        "--max-batch-failures", type=float, default=None, metavar="X",
        help="per-step quarantine budget before the step fails: a value "
             "< 1 is a fraction of the step's batches, >= 1 an absolute "
             "count (default 0.5); 0 disables quarantine (first failure "
             "aborts the step, the pre-resilience behavior)",
    )
    shared.add_argument(
        "--retry-attempts", type=int, default=None, metavar="N",
        help="total tries per batch for transient faults (1 = no retry)",
    )
    shared.add_argument(
        "--retry-delay", type=float, default=None, metavar="SECONDS",
        help="first backoff delay; doubles per retry, with jitter",
    )
    shared.add_argument(
        "--probe-timeout", type=float, default=None, metavar="SECONDS",
        help="device health probe deadline before the circuit breaker "
             "counts a failure (a down TPU relay hangs, not errors)",
    )
    shared.add_argument(
        "--no-telemetry", action="store_true",
        help="disable the metrics registry, span events and resource "
             "sampler for this run (also: TM_TELEMETRY=0)",
    )
    shared.add_argument(
        "--sample-resources", type=float, default=None, metavar="SECONDS",
        help="resource sampler period (RSS/fds/device-memory gauges + "
             "heartbeat file; default from TM_RESOURCE_SAMPLE_PERIOD, "
             "0 disables)",
    )
    shared.add_argument(
        "--qc", action=argparse.BooleanOptionalAction, default=None,
        help="collect data-quality evidence for this run (qc.py): fused "
             "on-device image stats, NaN/outlier guards, feature "
             "sketches -> workflow/qc.json + qc_* ledger events, "
             "inspected with `tmx qc` (default: TMX_QC / TM_QC config, "
             "off; --no-qc forces off)",
    )
    p_submit = wf_sub.add_parser("submit", help="run the workflow",
                                 parents=[shared])
    _add_common(p_submit)
    p_submit.add_argument("--resume", action="store_true",
                          help="skip work completed in a previous run")
    p_resume = wf_sub.add_parser(
        "resume", help="shorthand for submit --resume (reference verb)",
        parents=[shared],
    )
    _add_common(p_resume)
    p_resume.set_defaults(resume=True)
    p_status = wf_sub.add_parser("status", help="per-step progress")
    _add_common(p_status)
    p_clean = wf_sub.add_parser(
        "cleanup", help="remove every step's outputs, batch plans and the "
                        "run ledger (reference cleanup verb, workflow-wide)"
    )
    _add_common(p_clean)
    p_tmpl = wf_sub.add_parser(
        "template", help="write a typed skeleton workflow.yaml"
    )
    _add_common(p_tmpl)
    p_tmpl.add_argument(
        "--type", dest="wf_type", choices=("canonical", "multiplexing"),
        default="canonical", help="workflow type (multiplexing adds align)",
    )

    p_serve = sub.add_parser(
        "serve", help="always-on analysis service (spool-fed job stream "
                      "with admission control)")
    serve_sub = p_serve.add_subparsers(dest="verb", required=True)
    p_srun = serve_sub.add_parser(
        "run", help="run the serve daemon over a spool root")
    _add_common(p_srun)
    p_srun.add_argument("--max-queue", type=int, default=None, metavar="N",
                        help="admission-queue high watermark: at this depth "
                             "new jobs are shed with the pinned queue_full "
                             "retry-after (default TM_SERVE_MAX_QUEUE, 64)")
    p_srun.add_argument("--low-watermark", type=int, default=None,
                        metavar="N",
                        help="shedding stops once the queue drains to this "
                             "depth (hysteresis; default max-queue/2)")
    p_srun.add_argument("--tenant-quota", type=int, default=None,
                        metavar="N",
                        help="max queued jobs per tenant (default "
                             "TM_SERVE_TENANT_QUOTA, 16)")
    p_srun.add_argument("--retry-budget", type=int, default=None,
                        metavar="N",
                        help="per-tenant retry budget: resubmissions spend "
                             "one token, successes refund one (default "
                             "TM_SERVE_RETRY_BUDGET, 8)")
    p_srun.add_argument("--tenant-weights", default=None, metavar="T=W,...",
                        help="weighted deficit-round-robin weights, e.g. "
                             "'prod=3,dev=1' (default: 1 each)")
    p_srun.add_argument("--poll", type=float, default=None,
                        metavar="SECONDS",
                        help="spool poll period (default TM_SERVE_POLL_S, "
                             "0.5)")
    p_srun.add_argument("--max-jobs", type=int, default=0, metavar="N",
                        help="exit 0 after N completed jobs (0 = serve "
                             "forever; CI/smoke harnesses)")
    p_srun.add_argument("--idle-exit", type=float, default=0.0,
                        metavar="SECONDS",
                        help="exit 0 after this long with an empty queue "
                             "(0 = never)")
    p_srun.add_argument("--no-telemetry", action="store_true",
                        help="disable the metrics registry for the daemon")
    p_srun.add_argument("--host", default=None, metavar="ID",
                        help="fleet host identity: claims are leased as "
                             "this id and events/heartbeats land in "
                             "per-host files (default TMX_HOST_ID when a "
                             "fleet is active, else single-host mode)")
    p_srun.add_argument("--lease", type=float, default=None,
                        metavar="SECONDS",
                        help="claim lease duration; an expired lease whose "
                             "owner's heartbeat is stale is reclaimed by "
                             "a peer (default TM_SERVE_LEASE_S, 15)")
    p_srun.add_argument("--canary", type=float, default=None,
                        metavar="SECONDS",
                        help="canary probe period: enqueue one tiny "
                             "self-addressed health probe this often "
                             "(default TM_SERVE_CANARY_PERIOD_S, 0 = off)")
    p_sstatus = serve_sub.add_parser(
        "status", help="queue depth, per-tenant admitted/rejected/"
                       "budget-remaining, oldest-job age")
    _add_common(p_sstatus)
    p_sstatus.add_argument("--json", action="store_true", dest="as_json",
                           help="emit the full status view as JSON")

    p_enq = sub.add_parser(
        "enqueue", help="submit one job spec to a serve spool")
    _add_common(p_enq)
    p_enq.add_argument("--experiment", required=True, metavar="DIR",
                       help="experiment store root the job runs against")
    p_enq.add_argument("--tenant", default="default",
                       help="tenant the job is accounted to")
    p_enq.add_argument("--job-id", default=None,
                       help="unique job id (default: generated)")
    p_enq.add_argument("--description", default=None,
                       help="workflow YAML (default: the experiment's "
                            "workflow/workflow.yaml)")
    p_enq.add_argument("--priority", type=int, default=0,
                       help="within-tenant priority (higher first)")
    p_enq.add_argument("--deadline", type=float, default=None,
                       metavar="SECONDS",
                       help="relative deadline; an expired job is "
                            "cancelled at the next batch boundary")
    p_enq.add_argument("--pipeline-depth", type=int, default=None,
                       metavar="N", help="per-job pipelined-executor depth")
    p_enq.add_argument("--attempt", type=int, default=0, metavar="N",
                       help="resubmission count (attempt > 0 spends one "
                            "retry-budget token)")
    p_enq.add_argument("--trace-id", default=None,
                       help="end-to-end trace correlation id (default: "
                            "generated); every ledger event the job "
                            "produces carries it, and `tmx trace --export "
                            "chrome --trace-id ID` renders the full "
                            "enqueue-to-result timeline")
    p_enq.add_argument("--kind", choices=("workflow", "query"),
                       default="workflow",
                       help="job kind: 'workflow' runs the experiment's "
                            "workflow; 'query' answers one analytics "
                            "query (digest-cached; see `tmx query`)")
    p_enq.add_argument("--tool", default=None,
                       help="query jobs: tool name (clustering, heatmap, "
                            "classification, knn, pca, embedding, "
                            "spatial) — merged into the payload")
    p_enq.add_argument("--objects", default=None, metavar="NAME",
                       help="query jobs: objects_name shorthand — merged "
                            "into the payload")
    p_enq.add_argument("--payload", default=None,
                       help="query jobs: payload as inline JSON")
    p_enq.add_argument("--payload-file", default=None,
                       help="query jobs: payload from a JSON file")
    p_enq.add_argument("--index", default=None,
                       choices=["auto", "ivf", "brute"],
                       help="query jobs: kNN index routing — merged into "
                            "the payload (default: auto via env/config/"
                            "tuned verdict/store size)")
    p_enq.add_argument("--affinity-key", default=None, metavar="KEY",
                       help="compiled-program affinity key for fleet "
                            "routing (default: auto-derived content "
                            "digest of the workflow description + "
                            "jterator pipelines; hosts prefer jobs whose "
                            "key is warm in their compile caches)")

    p_query = sub.add_parser(
        "query", help="one-shot analytics query over an experiment's "
                      "feature store (kNN/PCA/embedding/spatial/"
                      "clustering/heatmap/classification; results are "
                      "cached by feature-store digest — the daemon path "
                      "is `tmx enqueue --kind query`)")
    _add_common(p_query)
    p_query.add_argument("--tool", required=True,
                         help="tool name (see 'tmx tool available')")
    p_query.add_argument("--objects", default=None, metavar="NAME",
                         help="objects_name shorthand (else put "
                              "objects_name in the payload)")
    p_query.add_argument("--payload", default=None,
                         help="tool payload as inline JSON")
    p_query.add_argument("--payload-file", default=None,
                         help="tool payload from a JSON file")
    p_query.add_argument("--index", default=None,
                         choices=["auto", "ivf", "brute"],
                         help="kNN index routing (knn/embedding/"
                              "clustering/classification tools) — merged "
                              "into the payload")
    p_query.add_argument("--no-cache", action="store_true",
                         help="recompute even when a digest-keyed cached "
                              "result exists")

    p_index = sub.add_parser(
        "index", help="IVF kNN index over an experiment's feature store "
                      "(analytics/index.py): build or inspect the "
                      "persisted per-selection index artifacts")
    index_sub = p_index.add_subparsers(dest="verb", required=True)
    p_ibuild = index_sub.add_parser(
        "build", help="build (or reuse) the index for one objects_name; "
                      "prints the manifest JSON")
    _add_common(p_ibuild)
    p_ibuild.add_argument("--objects", required=True, metavar="NAME",
                          help="mapobject type to index")
    p_ibuild.add_argument("--features", default=None,
                          help="comma list of feature columns (default: "
                               "all)")
    p_ibuild.add_argument("--cells", type=int, default=None,
                          help="cell count override (default: 4*sqrt(N))")
    p_ibuild.add_argument("--rebuild", action="store_true",
                          help="force a rebuild even when the persisted "
                               "index matches the live store digest")
    p_ilist = index_sub.add_parser(
        "list", help="list persisted indexes for one objects_name with "
                     "staleness vs the live store digest")
    _add_common(p_ilist)
    p_ilist.add_argument("--objects", required=True, metavar="NAME")

    p_slo = sub.add_parser(
        "slo", help="per-tenant SLO report over a serve root: p50/p95 "
                    "latency, availability, multi-window burn rates "
                    "(exit 0 ok / 1 burn / 3 no data)")
    _add_common(p_slo)
    p_slo.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the report as JSON")

    p_tool = sub.add_parser("tool", help="analysis tools over the feature store")
    tool_sub = p_tool.add_subparsers(dest="verb", required=True)
    p_tsubmit = tool_sub.add_parser("submit", help="run one tool request")
    _add_common(p_tsubmit)
    p_tsubmit.add_argument("--name", required=True,
                           help="tool name (see 'tool available')")
    p_tsubmit.add_argument("--payload", default="{}",
                           help="request payload as inline JSON")
    p_tsubmit.add_argument("--payload-file", default=None,
                           help="request payload from a JSON file")
    p_tsubmit.add_argument("--background", action="store_true",
                           help="run the request as a detached job and "
                                "return its id immediately (reference "
                                "ToolJob fan-out); poll with 'tool status'")
    p_tlist = tool_sub.add_parser(
        "list", help="tool requests with lifecycle state")
    _add_common(p_tlist)
    p_tstatus = tool_sub.add_parser("status", help="one request's state")
    _add_common(p_tstatus)
    p_tstatus.add_argument("--request", required=True)
    p_trun = tool_sub.add_parser(
        "run-request", help="execute a submitted request (internal: the "
                            "--background job body)")
    _add_common(p_trun)
    p_trun.add_argument("--request", required=True)
    tool_sub.add_parser("available", help="registered tool names")

    p_proj = sub.add_parser("project", help="manage a jterator pipeline project")
    proj_sub = p_proj.add_subparsers(dest="verb", required=True)
    p_pcreate = proj_sub.add_parser("create", help="create a skeleton project")
    p_pcreate.add_argument("--dir", required=True, help="project directory")
    p_pcreate.add_argument("--description", default="")
    p_padd = proj_sub.add_parser("add-module", help="append a module instance")
    p_padd.add_argument("--dir", required=True)
    p_padd.add_argument("--module", required=True)
    p_padd.add_argument("--instance", default=None)
    p_premove = proj_sub.add_parser("remove-module", help="remove a module instance")
    p_premove.add_argument("--dir", required=True)
    p_premove.add_argument("--instance", required=True)
    p_pchan = proj_sub.add_parser("add-channel", help="declare an input channel")
    p_pchan.add_argument("--dir", required=True)
    p_pchan.add_argument("--name", required=True)
    p_pchan.add_argument("--no-correct", action="store_true")
    p_pchan.add_argument("--align", action="store_true")
    p_pshow = proj_sub.add_parser("show", help="modules in pipeline order")
    p_pshow.add_argument("--dir", required=True)
    proj_sub.add_parser("modules", help="registered module names")
    p_pcheck = proj_sub.add_parser(
        "check", help="validate a pipeline without running it: dataflow, "
                      "module names, parameter names (reference jterator's "
                      "pipeline check role)")
    p_pcheck.add_argument("--pipe", required=True, help="path to .pipe.yaml")

    for name in list_steps():
        step_cls = get_step(name)
        p_step = sub.add_parser(name, help=f"{name} step")
        verb_sub = p_step.add_subparsers(dest="verb", required=True)
        p_init = verb_sub.add_parser("init", help="plan batches")
        _add_common(p_init)
        step_cls.batch_args.add_to_parser(p_init)
        p_run = verb_sub.add_parser("run", help="run one batch (or all)")
        _add_common(p_run)
        p_run.add_argument("--job", type=int, default=None,
                           help="batch index (default: all)")
        p_collect = verb_sub.add_parser("collect", help="merge phase")
        _add_common(p_collect)
        p_info = verb_sub.add_parser("info", help="planned batches")
        _add_common(p_info)
        p_clean = verb_sub.add_parser(
            "cleanup", help="delete this step's previous outputs"
        )
        _add_common(p_clean)
        verb_sub.add_parser("args", help="argument schema as JSON")
    return parser


def _open_store(args) -> ExperimentStore:
    return ExperimentStore.open(Path(args.root))


def _render_heartbeats(hb_dir: Path, running: bool) -> None:
    """Heartbeat liveness lines, shared by ``tmx workflow status`` and
    ``tmx serve status``: a running process with a stale heartbeat is a
    HUNG one (sampler/daemon thread dead or blocked), not a slow one."""
    from tmlibrary_tpu import telemetry

    for hb_path in sorted(Path(hb_dir).glob("heartbeat*.json")):
        hb = telemetry.read_heartbeat(hb_path)
        if not hb or "ts" not in hb:
            continue
        # fresher-of(embedded ts, file mtime): cross-host clock skew
        # must not flag a live remote host's run as hung
        age = telemetry.heartbeat_age(hb_path)
        period = float(hb.get("period", 0) or 0)
        host = str(hb.get("host") or "host0")
        tag = "" if host == "host0" else f"[{host}]"
        line = (f"heartbeat{tag}: {age:.1f}s ago "
                f"(sampler period {period:g}s)")
        if running and period > 0 and age > 2 * period:
            line += " — STALE: run appears hung"
        print(line)


def _cleanup_step(step) -> None:
    """One step's cleanup recipe (shared by the per-step verb and
    workflow-wide cleanup): outputs + batch plans."""
    step.delete_previous_output()
    for p in step.step_dir.glob("batch_*.json"):
        p.unlink()


#: reader attributes surfaced by ``tmx inspect`` (whichever exist)
_INSPECT_ATTRS = (
    "height", "width", "n_channels", "n_zplanes", "n_tpoints",
    "n_series", "n_scenes", "n_tiles", "n_sequences", "n_components",
    "n_fields",
)


def _inspect_source_dir(src: Path) -> dict:
    """Dry-run ingest preview of a source DIRECTORY: which sidecar
    handler resolves it (metaconfig's auto order) and the layout it
    would produce — without creating a store."""
    from tmlibrary_tpu.errors import VendorConflictError
    from tmlibrary_tpu.workflow.steps.vendors import (
        SIDECAR_HANDLERS,
        resolve_sidecars,
    )

    try:
        # the SAME resolution loop metaconfig's auto mode runs — a
        # separate copy here would drift from real ingest behavior
        resolved = resolve_sidecars(src, list(SIDECAR_HANDLERS), True)
    except VendorConflictError as exc:
        return {"format": "source-dir", "error": str(exc)}
    if resolved is None:
        return {
            "format": "source-dir",
            "handler": None,
            "note": "no sidecar handler resolved this directory; "
                    "metaconfig would fall back to filename patterns",
        }
    handler, entries, skipped = resolved
    wells = {(e["plate"], e["well_row"], e["well_col"]) for e in entries}
    return {
        "format": "source-dir",
        "handler": handler,
        "n_planes": len(entries),
        "n_skipped_files": skipped,
        "n_wells": len(wells),
        "n_sites": len({
            (e["plate"], e["well_row"], e["well_col"], e["site"])
            for e in entries
        }),
        "channels": sorted({e["channel"] for e in entries}),
        "n_zplanes": max(e["zplane"] for e in entries) + 1,
        "n_tpoints": max(e["tpoint"] for e in entries) + 1,
        "n_cycles": max(e["cycle"] for e in entries) + 1,
    }


def cmd_inspect(args) -> int:
    """Bio-Formats ``showinf`` equivalent over the first-party parsers
    (reference users inspect vendor files with showinf before ingest;
    SURVEY.md §3 Readers row).  Prints dims/channels per file — or, for
    a source DIRECTORY, a dry-run ingest preview (resolved handler +
    layout).  Exits non-zero if anything could not be read."""
    from tmlibrary_tpu import readers as _readers

    failed = 0
    for name in args.files:
        path = Path(name)
        info: dict = {"file": str(path)}
        if path.is_dir() and not str(path).lower().endswith(".zarr"):
            preview = _inspect_source_dir(path)
            info.update(preview)
            # an unresolved dir is a legitimate answer (filename-pattern
            # fallback), NOT a failure; a well conflict is
            if "error" in preview:
                failed += 1
            if args.as_json:
                print(json.dumps(info))
            else:
                print(f"{info['file']}: source dir "
                      f"(handler={info.get('handler')})")
                for key, val in info.items():
                    if key not in ("file", "format", "handler"):
                        print(f"  {key:16s} {val}")
            continue
        try:
            # _open_container, not _container_reader: a TIFF-flavored
            # container the dedicated reader declines (RGB .flex/.stk)
            # must fall to the plain-image branch exactly like ingest does
            r = _readers._open_container(path)
            if r is not None:
                try:
                    info["format"] = type(r).__name__.replace("Reader", "")
                    for attr in _INSPECT_ATTRS:
                        val = getattr(r, attr, None)
                        if val is not None:
                            info[attr] = int(val)
                    names = getattr(r, "channel_names", None)
                    if callable(names):
                        names = names()
                    if names:
                        info["channel_names"] = list(names)
                    loops = getattr(r, "loop_shape", None)
                    if callable(loops):
                        loops = loops()
                    if loops:  # ND2 acquisition nesting, outermost first
                        info["loops"] = [[kind, size] for kind, size in loops]
                finally:
                    r.__exit__()
            else:
                plane = _readers.ImageReader(path).read(0)
                info["format"] = "image"
                info["height"], info["width"] = map(int, plane.shape[:2])
                info["dtype"] = str(plane.dtype)
        except Exception as exc:
            info["error"] = str(exc)
            failed += 1
        if args.as_json:
            print(json.dumps(info))
        else:
            head = f"{info['file']}: " + (
                f"ERROR {info['error']}" if "error" in info
                else info.get("format", "?")
            )
            print(head)
            for key, val in info.items():
                if key not in ("file", "format", "error"):
                    print(f"  {key:14s} {val}")
    return 1 if failed else 0


def cmd_create(args) -> int:
    root = Path(args.root)
    if (root / ExperimentStore.MANIFEST).exists():
        print(f"error: store already exists at {root}", file=sys.stderr)
        return 1
    placeholder = Experiment(
        name=args.name, plates=[], channels=[], site_height=1, site_width=1
    )
    ExperimentStore.create(root, placeholder)
    print(f"created experiment '{args.name}' at {root}")
    return 0


def cmd_workflow(args) -> int:
    store = _open_store(args)
    if args.verb == "status":
        status = RunLedger(store.workflow_dir / "ledger.jsonl").status()
        from tmlibrary_tpu.tools.base import ToolRequestManager

        tool_requests = ToolRequestManager(store).list_requests()
        if not status and not tool_requests:
            print("no workflow runs recorded")
            return 0
        for step, entry in status.items():
            done = entry["batches_done"]
            total = entry["n_batches"]
            frac = f"{done}/{total}" if total is not None else str(done)
            line = f"{step:12s} {entry['state']:8s} batches {frac} " \
                   f"({entry['elapsed']:.1f}s)"
            if entry.get("quarantined"):
                line += f" quarantined: {sorted(entry['quarantined'])}"
            if entry.get("error"):
                line += f" error: {entry['error']}"
            print(line)
            ps = entry.get("pipeline_stats")
            if ps:
                phases = " ".join(
                    f"{ph}={v['total_s']:.2f}s"
                    for ph, v in ps.get("phases", {}).items()
                )
                print(f"{'':12s} pipeline depth {ps.get('depth')} "
                      f"({ps.get('source')}) over {ps.get('n_batches')} "
                      f"batches: {phases}")
            for clamp in entry.get("depth_clamps", []):
                print(f"{'':12s} depth clamped {clamp.get('from')} -> "
                      f"{clamp.get('to')} (resource exhausted)")
            if entry.get("watchdog_fires"):
                print(f"{'':12s} watchdog fired {entry['watchdog_fires']} "
                      "time(s) — hung phase(s) classified transient")
            buckets = entry.get("buckets")
            if buckets:
                routed = " ".join(
                    f"cap{c}x{n}" for c, n in sorted(
                        buckets["routed"].items(), key=lambda kv: int(kv[0])
                    )
                )
                line = f"{'':12s} buckets: {routed}"
                if buckets.get("occupancy_n"):
                    occ = buckets["occupancy_sum"] / buckets["occupancy_n"]
                    line += f" slot occupancy {occ:.1%}"
                if buckets.get("escalations"):
                    line += f" escalations {buckets['escalations']}"
                print(line)
            qc_entry = entry.get("qc")
            if qc_entry:
                line = (f"{'':12s} qc: flagged "
                        f"{qc_entry.get('flagged', 0)} site(s)")
                if qc_entry.get("nan_columns"):
                    line += f" nan columns {qc_entry['nan_columns']}"
                if qc_entry.get("worst_focus") is not None:
                    line += f" worst focus {qc_entry['worst_focus']:.4g}"
                if qc_entry.get("budget_exceeded"):
                    line += " ** OVER FLAG BUDGET — inspect with tmx qc **"
                print(line)
        ledger = RunLedger(store.workflow_dir / "ledger.jsonl")
        preempted = ledger.preempted()
        if preempted:
            print(f"PREEMPTED ({preempted.get('reason', 'signal')}) at step "
                  f"'{preempted.get('step')}': drained "
                  f"{preempted.get('drained', 0)}/"
                  f"{preempted.get('in_flight', 0)} in-flight, abandoned "
                  f"{preempted.get('abandoned', 0)} — resume with "
                  "`tmx workflow submit --resume`")
        degraded = ledger.degraded_backend()
        if degraded:
            print(f"backend degraded to {degraded.get('backend')} "
                  f"(at step '{degraded.get('where')}' after "
                  f"{degraded.get('failures')} failed device probes)")
        running = any(e.get("state") == "running" for e in status.values())
        _render_heartbeats(store.workflow_dir, running)
        try:
            # one-line bench-record staleness warning: the certified
            # throughput evidence ages even while runs look healthy
            from tmlibrary_tpu import perf

            stale_rows = [r for r in perf.bench_record_staleness()
                          if r["stale"]]
            if stale_rows:
                worst = max(r["age_hours"] for r in stale_rows)
                configs = ", ".join(r["config"] for r in stale_rows)
                print(f"bench records stale (> {perf.stale_hours():g}h, "
                      f"oldest {worst:g}h): config {configs} — re-capture "
                      "via scripts/bench_regression.py / tpu_watch")
        except Exception:
            pass
        # tool request lifecycle (reference ToolRequestManager submissions
        # surface in the same status view the UI polls)
        for req in tool_requests:
            line = f"tool:{req['request']:30s} {req.get('state', '?'):8s}"
            if req.get("error"):
                line += f" error: {req['error']}"
            print(line)
        return 0
    if args.verb == "cleanup":
        from tmlibrary_tpu.models.mapobject import MapobjectTypeRegistry

        for name in list_steps():
            _cleanup_step(get_step(name)(store))
        # the registry would otherwise advertise object types whose
        # label/feature artifacts were just removed
        registry = MapobjectTypeRegistry(store.root)
        for name in registry.names():
            registry.delete(name)
        ledger_path = store.workflow_dir / "ledger.jsonl"
        ledger_path.unlink(missing_ok=True)
        print("removed all step outputs, batch plans, mapobject "
              "registrations and the run ledger")
        return 0
    if args.verb == "template":
        out = store.workflow_dir / "workflow.yaml"
        if out.exists():
            print(f"error: {out} already exists", file=sys.stderr)
            return 1
        WorkflowDescription.for_type(args.wf_type).save(out)
        print(f"wrote {args.wf_type} workflow template to {out} — fill in "
              "step args and set active: true on the steps to run")
        return 0
    # submit
    if args.description:
        desc = WorkflowDescription.load(Path(args.description))
    else:
        wf_yaml = store.workflow_dir / "workflow.yaml"
        if wf_yaml.exists():
            desc = WorkflowDescription.load(wf_yaml)
        else:
            print("error: no workflow description (pass --description or put "
                  "workflow.yaml in the store's workflow dir)", file=sys.stderr)
            return 1
    from tmlibrary_tpu import telemetry
    from tmlibrary_tpu.profiling import device_trace
    from tmlibrary_tpu.resilience import ResilienceConfig

    if args.no_telemetry:
        telemetry.set_enabled(False)
    if getattr(args, "reduction_strategy", None):
        import os as _os

        # the env (not a plumbed parameter) because compiled programs
        # trace lazily at first call: the request must outlive this
        # function and be visible to every build site (ops/reduction.py
        # resolution order; "auto" clears a stale request)
        if args.reduction_strategy == "auto":
            _os.environ.pop("TMX_REDUCTION_STRATEGY", None)
        else:
            _os.environ["TMX_REDUCTION_STRATEGY"] = args.reduction_strategy
    if getattr(args, "object_buckets", None):
        import os as _os

        # same env pattern as --reduction-strategy: the bucket router
        # resolves the spec at every launch (capacity.py resolution
        # order), so the request must outlive this function; "auto"
        # clears any stale explicit request
        if args.object_buckets == "auto":
            _os.environ.pop("TMX_OBJECT_BUCKETS", None)
        else:
            _os.environ["TMX_OBJECT_BUCKETS"] = args.object_buckets
    if getattr(args, "schedule", None):
        import os as _os

        # same env pattern as --object-buckets: the scheduler resolves
        # its mode at init/create_batches time (workflow/schedule.py
        # precedence: explicit > env > config > tuning > default), so
        # the request must outlive this function; "auto" clears any
        # stale explicit request so the chain falls through
        if args.schedule == "auto":
            _os.environ.pop("TMX_SCHEDULE", None)
        else:
            _os.environ["TMX_SCHEDULE"] = args.schedule
    if getattr(args, "qc", None) is not None:
        import os as _os

        # env (not a plumbed parameter), same pattern as
        # --reduction-strategy: the QC gate is part of the compiled-
        # program cache key (jterator.pipeline.cached_batch_fn) and is
        # re-read at every build site, so the request must outlive this
        # function; an explicit --no-qc writes "0" to beat the config
        _os.environ["TMX_QC"] = "1" if args.qc else "0"
    if args.sample_resources is not None:
        from tmlibrary_tpu.config import cfg as _cfg

        _cfg.resource_sample_period = args.sample_resources
    resilience = ResilienceConfig.from_library_config()
    if args.max_batch_failures is not None:
        resilience.max_batch_failures = args.max_batch_failures
    if args.retry_attempts is not None or args.retry_delay is not None:
        import dataclasses as _dc

        resilience.policy = _dc.replace(
            resilience.policy,
            **{k: v for k, v in (
                ("max_attempts", args.retry_attempts),
                ("base_delay", args.retry_delay),
            ) if v is not None},
        )
    if args.probe_timeout is not None and resilience.guard is not None:
        resilience.guard.timeout = args.probe_timeout
    from tmlibrary_tpu.errors import PreemptedError
    from tmlibrary_tpu.resilience import (
        EXIT_PREEMPTED,
        install_preemption_handlers,
    )

    # SIGTERM/SIGINT ask for a graceful drain instead of killing the
    # process mid-batch: the engine stops admitting work, persists the
    # in-flight window, records run_preempted and we exit with the
    # pinned code so wrappers re-launch `tmx workflow submit --resume`
    restore = install_preemption_handlers()
    try:
        with device_trace(args.profile):
            summary = Workflow(store, desc, resilience=resilience,
                               pipeline_depth=args.pipeline_depth).run(
                resume=args.resume
            )
    except PreemptedError as exc:
        print(f"preempted ({exc.reason}): drained {exc.drained}/"
              f"{exc.in_flight} in-flight batches at step '{exc.step}', "
              f"abandoned {exc.abandoned} — resume with "
              "`tmx workflow submit --resume`", file=sys.stderr)
        return EXIT_PREEMPTED
    finally:
        restore()
    print(json.dumps(summary, default=str, indent=2))
    return 0


def cmd_serve(args) -> int:
    from tmlibrary_tpu import serve as serve_mod

    root = Path(args.root)
    if args.verb == "status":
        view = serve_mod.serve_status_view(root)
        if args.as_json:
            print(json.dumps(view, indent=2, sort_keys=True))
            return 0
        live = "LIVE" if view.get("live") else "not running"
        print(f"serve root: {view['root']}  [{live}]")
        status = view.get("status") or {}
        if status:
            depth = status.get("depth", 0)
            line = (f"queue depth {depth}/{status.get('high_watermark', '?')}"
                    f" (low watermark {status.get('low_watermark', '?')})")
            if status.get("shedding"):
                line += " — SHEDDING"
            print(line)
            age = status.get("oldest_job_age_s")
            if age is not None:
                print(f"oldest queued job: {age:.1f}s ago")
        spool = view.get("spool") or {}
        if spool:
            print("spool: " + "  ".join(
                f"{state} {n}" for state, n in spool.items()))
        # per-tenant table: live queue/budget/breaker state from the
        # daemon's snapshot, lifetime outcomes from the serve ledger
        live_tenants = (status.get("tenants") or {})
        ledger_tenants = view.get("tenants") or {}
        names = sorted(set(live_tenants) | set(ledger_tenants))
        if names:
            print(f"{'tenant':16s} {'queued':>6s} {'admitted':>8s} "
                  f"{'rejected':>8s} {'done':>5s} {'failed':>6s} "
                  f"{'budget':>6s} breaker")
            for name in names:
                lt = live_tenants.get(name, {})
                gt = ledger_tenants.get(name, {})
                print(f"{name:16s} {lt.get('queued', 0):>6d} "
                      f"{gt.get('admitted', lt.get('admitted', 0)):>8d} "
                      f"{gt.get('rejected', lt.get('rejected', 0)):>8d} "
                      f"{gt.get('done', 0):>5d} {gt.get('failed', 0):>6d} "
                      f"{str(lt.get('retry_budget_remaining', '-')):>6s} "
                      f"{lt.get('breaker', '-')}")
        if view.get("preemptions"):
            print(f"preemptions: {view['preemptions']} (drained + "
                  "re-spooled; jobs converge on restart)")
        fleet = view.get("fleet") or {}
        hosts = fleet.get("hosts") or {}
        if hosts:
            aff = fleet.get("affinity") or {}
            rate = aff.get("hit_rate")
            print(f"fleet: {len(hosts)} host(s)  "
                  f"reclaims {fleet.get('reclaims_total', 0)}  "
                  f"stale claims {fleet.get('stale_claims_total', 0)}  "
                  f"affinity "
                  + (f"{rate:.0%}" if rate is not None else "-")
                  + f" ({aff.get('hits', 0)}/{aff.get('known', 0)})")
            for name in sorted(hosts):
                h = hosts[name]
                age = h.get("heartbeat_age_s")
                print(f"  {name:14s} "
                      f"{'LIVE' if h.get('live') else 'dead':4s}  "
                      f"hb " + (f"{age:.1f}s" if age is not None else "-")
                      + f"  leases {h.get('leases', 0)}")
        _render_heartbeats(serve_mod.serve_dir(root),
                           running=bool(view.get("live")))
        return 0
    # run
    from tmlibrary_tpu import telemetry
    from tmlibrary_tpu.resilience import EXIT_PREEMPTED
    from tmlibrary_tpu.workflow.admission import AdmissionConfig

    if args.no_telemetry:
        telemetry.set_enabled(False)
    admission = AdmissionConfig.from_library_config()
    if args.max_queue is not None:
        admission.max_queue = args.max_queue
    if args.low_watermark is not None:
        admission.low_watermark = args.low_watermark
    if args.tenant_quota is not None:
        admission.tenant_quota = args.tenant_quota
    if args.retry_budget is not None:
        admission.retry_budget = args.retry_budget
    if args.tenant_weights:
        weights = {}
        for part in args.tenant_weights.split(","):
            name, _, w = part.partition("=")
            if not name or not w:
                print(f"error: bad --tenant-weights entry '{part}' "
                      "(expected TENANT=WEIGHT)", file=sys.stderr)
                return 1
            weights[name.strip()] = float(w)
        admission.tenant_weights = weights
    rc = serve_mod.run_serve(
        root, admission=admission, poll_s=args.poll,
        max_jobs=args.max_jobs, idle_exit_s=args.idle_exit,
        host=args.host, lease_s=args.lease,
        canary_period_s=args.canary,
    )
    if rc == EXIT_PREEMPTED:
        print("serve preempted: queued jobs re-spooled — restart "
              "`tmx serve run` to resume", file=sys.stderr)
    return rc


def _query_payload(args) -> dict:
    """Assemble one analytics-query payload from --tool/--objects plus
    inline or file JSON (shared by `tmx query` and `tmx enqueue
    --kind query`).  Explicit payload keys win over the shorthands."""
    if args.payload_file and args.payload:
        raise SystemExit("--payload and --payload-file are mutually "
                         "exclusive")
    if args.payload_file:
        payload = json.loads(Path(args.payload_file).read_text())
    elif args.payload:
        payload = json.loads(args.payload)
    else:
        payload = {}
    if not isinstance(payload, dict):
        raise SystemExit("query payload must be a JSON object")
    if getattr(args, "tool", None):
        payload.setdefault("tool", args.tool)
    if getattr(args, "objects", None):
        payload.setdefault("objects_name", args.objects)
    if getattr(args, "index", None):
        payload.setdefault("index", args.index)
    if not payload.get("tool"):
        raise SystemExit("query needs a tool (--tool or payload 'tool')")
    if not payload.get("objects_name"):
        raise SystemExit("query needs an objects_name (--objects or "
                         "payload 'objects_name')")
    return payload


def cmd_query(args) -> int:
    from tmlibrary_tpu.analytics import query as analytics_query

    store = _open_store(args)
    payload = _query_payload(args)
    summary = analytics_query.run_query(
        store, payload, use_cache=not args.no_cache,
    )
    print(json.dumps(summary, default=str))
    return 0


def cmd_index(args) -> int:
    from tmlibrary_tpu.analytics.index import IvfIndex
    from tmlibrary_tpu.analytics.store import FeatureStore

    store = _open_store(args)
    fs = FeatureStore.ensure(store, args.objects)
    if args.verb == "build":
        features = (
            [f.strip() for f in args.features.split(",") if f.strip()]
            if args.features else None
        )
        idx = IvfIndex.ensure(fs, features, n_cells=args.cells,
                              rebuild=args.rebuild)
        print(json.dumps({**idx.meta, "cache": idx.cache_state,
                          "root": str(idx.root)}, default=str))
        return 0
    # list: every persisted selection, with staleness vs the live digest
    rows = []
    for meta_path in sorted((fs.root / "index").glob("*/index_meta.json")):
        try:
            meta = json.loads(meta_path.read_text())
        except Exception:
            continue
        rows.append({
            "selection": meta.get("selection"),
            "n_cells": meta.get("n_cells"),
            "n_objects": meta.get("n_objects"),
            "recall_at_k": meta.get("recall_at_k"),
            "digest": meta.get("digest"),
            "state": ("fresh" if meta.get("store_digest") == fs.digest
                      else "stale"),
            "root": str(meta_path.parent),
        })
    print(json.dumps({"objects_name": args.objects,
                      "store_digest": fs.digest, "indexes": rows},
                     default=str))
    return 0


def cmd_enqueue(args) -> int:
    import uuid

    from tmlibrary_tpu import serve as serve_mod
    from tmlibrary_tpu.workflow.admission import JobSpec

    now = time.time()
    job_id = args.job_id or f"{args.tenant}-{uuid.uuid4().hex[:10]}"
    trace_id = getattr(args, "trace_id", None) or uuid.uuid4().hex
    kind = getattr(args, "kind", "workflow")
    payload = None
    if kind == "query":
        payload = _query_payload(args)
    spec = JobSpec(
        job_id=job_id,
        tenant=args.tenant,
        root=str(Path(args.experiment).resolve()),
        description=args.description,
        priority=args.priority,
        deadline=(now + args.deadline) if args.deadline else None,
        pipeline_depth=args.pipeline_depth,
        attempt=args.attempt,
        submitted_at=now,
        trace_id=trace_id,
        kind=kind,
        payload=payload,
        affinity_key=getattr(args, "affinity_key", None),
    )
    try:
        path = serve_mod.enqueue_job(Path(args.root), spec)
    except Exception as exc:
        print(f"error: enqueue failed for job {job_id}: {exc}",
              file=sys.stderr)
        return 1
    print(f"enqueued {job_id} (tenant {spec.tenant}, trace {trace_id}) "
          f"-> {path}")
    return 0


def cmd_tool(args) -> int:
    from tmlibrary_tpu.tools import base as tools_base

    if args.verb == "available":
        for name in tools_base.list_tools():
            print(name)
        return 0
    store = _open_store(args)
    manager = tools_base.ToolRequestManager(store)
    if args.verb == "submit":
        if args.payload_file and args.payload != "{}":
            raise SystemExit("--payload and --payload-file are mutually exclusive")
        if args.payload_file:
            payload = json.loads(Path(args.payload_file).read_text())
        else:
            payload = json.loads(args.payload)
        if args.background:
            request_id = manager.submit_async(args.name, payload)
            print(json.dumps(manager.status(request_id), default=str))
            return 0
        result = manager.submit(args.name, payload)
        print(json.dumps(
            {
                "tool": result.tool,
                "objects_name": result.objects_name,
                "layer_type": result.layer_type,
                "n_objects": int(len(result.values)),
                "attributes": result.attributes,
            },
            default=str,
        ))
        return 0
    if args.verb == "status":
        print(json.dumps(manager.status(args.request), default=str))
        return 0
    if args.verb == "run-request":
        manager.run_request(args.request)
        print(json.dumps(manager.status(args.request), default=str))
        return 0
    # list
    for entry in manager.list_requests():
        print(json.dumps(entry, default=str))
    return 0


def cmd_project(args) -> int:
    from tmlibrary_tpu.jterator.project import Project

    if args.verb == "modules":
        from tmlibrary_tpu.jterator.modules import list_modules

        for name in list_modules():
            print(name)
        return 0
    if args.verb == "create":
        Project.create(Path(args.dir), description=args.description)
        print(f"created project at {args.dir}")
        return 0
    if args.verb == "check":
        import yaml

        from tmlibrary_tpu.errors import (
            PipelineDescriptionError,
            PipelineError,
            RegistryError,
        )
        from tmlibrary_tpu.jterator.description import PipelineDescription
        from tmlibrary_tpu.jterator.modules import get_module, module_accepts

        try:
            desc = PipelineDescription.load(Path(args.pipe))
        except (PipelineError, OSError, ValueError, KeyError,
                yaml.YAMLError) as e:
            # PipelineError covers the description AND handle-type
            # errors; KeyError = a handle dict missing a required field
            print(f"FAIL: cannot load pipeline: {e}")
            return 1
        problems: list[str] = []
        try:
            desc.validate()
        except PipelineDescriptionError as e:
            problems.append(str(e))
        for mod in desc.modules:
            try:
                get_module(mod.module, mod.backend)
            except RegistryError as e:
                problems.append(str(e))
                continue
            # exactly the names the runner will bind (constants + traced
            # arrays; Plot/Figure handles are display-only and unbound)
            bound = list(mod.constants()) + list(mod.array_inputs())
            for name in bound:
                if not module_accepts(mod.module, mod.backend, name):
                    problems.append(
                        f"module '{mod.module}' has no parameter "
                        f"'{name}'"
                    )
        if problems:
            for p in problems:
                print(f"FAIL: {p}")
            return 1
        print(
            f"OK: {len(desc.modules)} modules, dataflow valid, every "
            "module and parameter resolves"
        )
        return 0
    proj = Project(Path(args.dir))
    if args.verb == "add-module":
        hc = proj.add_module(args.module, instance=args.instance)
        print(f"added '{args.module}' as "
              f"'{args.instance or args.module}' "
              f"({len(hc.input)} inputs, {len(hc.output)} outputs)")
        return 0
    if args.verb == "remove-module":
        proj.remove_module(args.instance)
        print(f"removed '{args.instance}'")
        return 0
    if args.verb == "add-channel":
        proj.add_channel(args.name, correct=not args.no_correct, align=args.align)
        print(f"added channel '{args.name}'")
        return 0
    if args.verb == "show":
        for name in proj.module_names():
            hc = proj.get_handles(name)
            print(f"{name}: module={hc.module} backend={hc.backend}")
        return 0
    return 1


def cmd_step(args) -> int:
    if args.verb == "args":
        # schema introspection needs no experiment store
        print(json.dumps(get_step(args.command).batch_args.to_schema(), indent=2))
        return 0
    store = _open_store(args)
    step = get_step(args.command)(store)
    if args.verb == "init":
        step_args = {
            a.name: getattr(args, a.name)
            for a in step.batch_args
            if getattr(args, a.name, None) is not None
        }
        batches = step.init(step_args)
        print(f"{args.command}: planned {len(batches)} batches")
        return 0
    if args.verb == "run":
        indices = [args.job] if args.job is not None else step.list_batches()
        for i in indices:
            result = step.run(i)
            print(f"{args.command} batch {i}: {json.dumps(result, default=str)}")
        return 0
    if args.verb == "collect":
        print(json.dumps(step.collect(), default=str))
        return 0
    if args.verb == "info":
        for i in step.list_batches():
            batch = step.load_batch(i)
            keys = {k: v for k, v in batch.items() if k not in ("args",)}
            print(f"batch {i}: {json.dumps(keys, default=str)[:200]}")
        return 0
    if args.verb == "cleanup":
        # reference `cleanup` verb: idempotent removal of step outputs
        _cleanup_step(step)
        print(f"{args.command}: outputs removed")
        return 0
    return 1


def cmd_log(args) -> int:
    store = _open_store(args)
    if args.step:
        name = "run" if args.job is None else f"batch_{args.job:03d}"
        path = store.workflow_dir / args.step / "logs" / f"{name}.log"
        if not path.exists():
            print(f"error: no captured log at {path}", file=sys.stderr)
            return 1
        lines = path.read_text().splitlines()
        for line in lines[-args.tail:] if args.tail else lines:
            print(line)
        return 0
    ledger = RunLedger(store.workflow_dir / "ledger.jsonl")
    for event in ledger.events()[-args.tail:]:
        print(json.dumps(event, default=str))
    return 0


def _export_images(store: ExperimentStore, args, out: Path) -> int:
    """Write one channel's (optionally corrected/aligned) site planes as
    uint16 TIFFs — the road OUT of the store (reference parity: tmserver's
    original/corrected image download endpoints).  Every tpoint/zplane is
    exported; names use the default filename handler's grammar
    (``[<plate>_]<well>_s<site>[_t<t>][_z<z>]_<channel>.tif``) so the
    exported tree re-ingests as-is — EXCEPT under ``--align`` when a
    cycle-intersection window is stored: aligned exports are cropped to
    the intersection (smaller than the manifest site shape, matching what
    the analysis actually saw), so that tree re-ingests only as a new
    experiment, not back into this one."""
    import re as _re

    import cv2
    import jax.numpy as jnp

    from tmlibrary_tpu.errors import StoreError
    from tmlibrary_tpu.models.experiment import Well
    from tmlibrary_tpu.models.image import IllumstatsContainer
    from tmlibrary_tpu.ops import image_ops
    from tmlibrary_tpu.writers import OMETiffWriter, minimal_ome_xml

    channel, cycle = args.images, args.cycle
    exp = store.experiment
    # the default ingest pattern accepts [A-Za-z0-9-] channel tokens and
    # [A-Za-z0-9] plate tokens only — sanitize both or the documented
    # re-ingest round-trip breaks on vendor names with '_'/'-'/spaces
    ch_name = _re.sub(r"[^A-Za-z0-9\-]", "-", exp.channels[channel].name)
    plate_token = {p.name: _re.sub(r"[^A-Za-z0-9]", "", p.name) or "plate"
                   for p in exp.plates}
    out.mkdir(parents=True, exist_ok=True)

    stats = None
    if args.correct:
        if not store.has_illumstats(cycle=cycle, channel=channel):
            print("error: --correct requested but corilla stats are missing "
                  f"for cycle {cycle} channel {channel}", file=sys.stderr)
            return 1
        stats = IllumstatsContainer.from_store(
            store.read_illumstats(cycle=cycle, channel=channel)
        )
    shifts = None
    window = (0, 0, 0, 0)
    if args.align:
        if not store.has_shifts(cycle):
            print(f"error: --align requested but no shifts stored for cycle "
                  f"{cycle} (run the align step)", file=sys.stderr)
            return 1
        shifts = store.read_shifts(cycle)
        try:
            w = store.read_intersection()
            window = (w["top"], w["bottom"], w["left"], w["right"])
        except StoreError:
            pass  # align ran but no intersection stored: shift-only

    prep = image_ops.make_batch_prep(
        stats, apply_shift=shifts is not None,
        window=window if any(window) else None,
    )

    # site index within the well (row-major over the well grid) so the
    # exported names round-trip through the default filename handler
    spw_x = max((r.site_x for r in exp.sites()), default=0) + 1
    refs = list(exp.sites())
    multi_plate = len(exp.plates) > 1
    shift_table = (shifts if shifts is not None
                   else np.zeros((len(refs), 2), np.int32))

    from tmlibrary_tpu.utils import create_partitions

    n = 0
    for tpoint in range(exp.n_tpoints):
        for zplane in range(exp.n_zplanes):
            for part in create_partitions(list(range(len(refs))), 32):
                stack = store.read_sites(
                    part, cycle=cycle, channel=channel,
                    tpoint=tpoint, zplane=zplane,
                )
                prepped = np.asarray(
                    prep(jnp.asarray(stack), jnp.asarray(shift_table[part]))
                )
                for b, idx in enumerate(part):
                    ref = refs[idx]
                    arr = np.clip(prepped[b], 0, 65535).astype(np.uint16)
                    well = Well(row=ref.well_row, column=ref.well_column,
                                sites=())
                    name = f"{well.name}_s{ref.site_y * spw_x + ref.site_x:d}"
                    if multi_plate:
                        name = f"{plate_token[ref.plate]}_{name}"
                    if exp.n_tpoints > 1:
                        name += f"_t{tpoint:d}"
                    if exp.n_zplanes > 1:
                        name += f"_z{zplane:d}"
                    name += f"_{ch_name}.tif"
                    if args.ome:
                        OMETiffWriter(out / name).write(
                            arr,
                            minimal_ome_xml(name, *arr.shape),
                        )
                    elif not cv2.imwrite(str(out / name), arr):
                        print(f"error: failed writing {out / name}",
                              file=sys.stderr)
                        return 1
                    n += 1
    print(f"wrote {n} {ch_name} site images to {out}")
    return 0


def cmd_export(args) -> int:
    """Combined per-object feature table → one CSV/Parquet file.

    Reference parity: the reference serves feature values through tmserver's
    data-export endpoints (FeatureValues over the Citus shards); here the
    Parquet shards the jterator step appended are concatenated and written
    as one table with the site/well metadata columns already joined.
    """
    store = _open_store(args)
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    modes = [m for m, v in (("--objects", args.objects),
                            ("--illumstats", args.illumstats),
                            ("--images", args.images),
                            ("--ngff", args.ngff or None)) if v is not None]
    if len(modes) > 1:
        print(f"error: {' and '.join(modes)} are mutually exclusive",
              file=sys.stderr)
        return 1
    if args.ngff:
        from tmlibrary_tpu.ngff import write_ngff_plate

        label_names = (
            [n.strip() for n in args.ngff_labels.split(",") if n.strip()]
            if args.ngff_labels else None
        )
        write_ngff_plate(store, out, n_levels=args.ngff_levels,
                         label_names=label_names)
        extra = (f" + labels {','.join(label_names)}" if label_names else "")
        print(f"wrote OME-NGFF 0.4 HCS plate "
              f"({len(store.experiment.channels)} channels{extra}) to {out}")
        return 0
    if args.images is not None:
        return _export_images(store, args, out)
    if args.illumstats is not None:
        store.export_illumstats_hdf5(
            out, cycle=args.cycle, channel=args.illumstats
        )
        print(f"wrote cycle {args.cycle} channel {args.illumstats} "
              f"illumination statistics (reference IllumstatsFile layout) "
              f"to {out}")
        return 0
    if args.objects is None:
        print("error: pass --objects NAME (feature/polygon export) or "
              "--illumstats CHANNEL", file=sys.stderr)
        return 1
    suffix_fmt = {".csv": "csv", ".geojson": "geojson", ".json": "geojson"}
    fmt = args.format or suffix_fmt.get(out.suffix.lower(), "parquet")
    if fmt == "geojson":
        # reference parity: tmserver serves MapobjectSegmentation polygons
        # as GeoJSON FeatureCollections for the viewer
        import pandas as pd

        shards = sorted(
            (store.root / "segmentations").glob(f"{args.objects}_polygons_*.parquet")
        )
        if not shards:
            print(
                f"error: no polygon shards for '{args.objects}' — run "
                "jterator with --as-polygons", file=sys.stderr,
            )
            return 1
        table = pd.concat([pd.read_parquet(p) for p in shards], ignore_index=True)
        if args.join_features:
            # join selected measurement columns onto the polygons by
            # (site, label) — reference parity: tmserver joins
            # FeatureValues / tool LabelLayers onto mapobjects for the
            # viewer's colored overlays
            wanted = [c.strip() for c in args.join_features.split(",") if c.strip()]
            keys = {"label", "site_index", "site"}
            if keys & set(wanted):
                print(f"error: --join-features cannot include the join keys "
                      f"{sorted(keys & set(wanted))}", file=sys.stderr)
                return 1
            feats = store.read_features(args.objects)
            missing = [c for c in wanted if c not in feats.columns]
            if missing:
                print(f"error: --join-features columns not in the feature "
                      f"table: {missing} (available: "
                      f"{sorted(set(feats.columns) - {'label'})[:20]}...)",
                      file=sys.stderr)
                return 1
            join = feats[["site_index", "label", *wanted]].rename(
                columns={"site_index": "site"}
            )
            table = table.merge(join, on=["site", "label"], how="left")
            # polygons with no feature row would serialize as bare NaN
            # (invalid JSON); emit null instead
            table[wanted] = table[wanted].astype(object).where(
                pd.notna(table[wanted]), None
            )
        from tmlibrary_tpu import native

        features = []
        for _, row in table.iterrows():
            contour = np.stack([row["contour_y"], row["contour_x"]], axis=1)
            if args.simplify > 0:
                contour = native.simplify_polygon_host(contour, args.simplify)
            ring = [[float(x), float(y)] for y, x in contour]
            if ring and ring[0] != ring[-1]:
                ring.append(ring[0])  # GeoJSON rings are closed
            props = {
                k: (row[k].item() if hasattr(row[k], "item") else row[k])
                for k in table.columns
                if k not in ("contour_y", "contour_x")
            }
            features.append(
                {
                    "type": "Feature",
                    "geometry": {"type": "Polygon", "coordinates": [ring]},
                    "properties": props,
                }
            )
        out.write_text(
            json.dumps({"type": "FeatureCollection", "features": features})
        )
        print(f"wrote {len(features)} polygon features to {out}")
        return 0
    table = store.read_features(args.objects)
    if fmt == "csv":
        table.to_csv(out, index=False)
    else:
        table.to_parquet(out, index=False)
    print(f"wrote {len(table)} rows x {len(table.columns)} cols to {out}")
    return 0


def cmd_metrics(args) -> int:
    """Export run metrics as Prometheus textfile format or JSON.

    Sources: the registry snapshot the last ``workflow submit`` wrote
    (``workflow/metrics.json``), or a ledger→metrics derivation that works
    on any ledger — including runs that predate telemetry."""
    from tmlibrary_tpu import telemetry

    if getattr(args, "merge", None):
        pairs = telemetry.load_fleet_snapshots(Path(args.merge))
        if not pairs:
            print(f"error: no workflow/metrics*.json snapshots under "
                  f"{args.merge}", file=sys.stderr)
            return 1
        merged = telemetry.merge_snapshots(pairs)
        if args.format == "json":
            text = telemetry.render_json(merged) + "\n"
        else:
            text = telemetry.render_prometheus(merged)
        if args.out:
            Path(args.out).write_text(text)
            print(f"wrote merged {args.format} metrics for "
                  f"{len(pairs)} host(s) to {args.out}")
        else:
            sys.stdout.write(text)
        return 0
    if not args.root:
        print("error: --root is required (or use --merge RUN_ROOT)",
              file=sys.stderr)
        return 1
    store = _open_store(args)
    snapshot = None
    snap_path = store.workflow_dir / "metrics.json"
    if args.source in ("auto", "snapshot") and snap_path.exists():
        try:
            snapshot = json.loads(snap_path.read_text())
        except ValueError:
            print(f"warning: ignoring corrupt snapshot {snap_path}",
                  file=sys.stderr)
    if snapshot is None:
        if args.source == "snapshot":
            print(f"error: no metrics snapshot at {snap_path} (run "
                  "`tmx workflow submit` first, or use --source ledger)",
                  file=sys.stderr)
            return 1
        ledger = RunLedger(store.workflow_dir / "ledger.jsonl")
        events = ledger.events()
        if not events:
            print("no metrics snapshot and no run ledger — nothing to "
                  "export", file=sys.stderr)
            return 1
        snapshot = telemetry.registry_from_ledger(events).snapshot()
    try:
        # bench-record staleness rides along live (a 3-day-old "certified"
        # number should be visible wherever metrics are scraped, not only
        # when bench.py itself recomputes cache_age_hours)
        from tmlibrary_tpu import perf

        names = {g.get("name") for g in snapshot.get("gauges", [])}
        if "tmx_bench_record_age_hours" not in names:
            for row in perf.bench_record_staleness():
                snapshot.setdefault("gauges", []).append({
                    "name": "tmx_bench_record_age_hours",
                    "labels": {"config": row["config"]},
                    "value": row["age_hours"],
                })
                snapshot.setdefault("gauges", []).append({
                    "name": "tmx_bench_record_stale",
                    "labels": {"config": row["config"]},
                    "value": 1.0 if row["stale"] else 0.0,
                })
    except Exception:
        pass
    if args.format == "json":
        text = telemetry.render_json(snapshot) + "\n"
    else:
        text = telemetry.render_prometheus(snapshot)
    if args.out:
        Path(args.out).write_text(text)
        print(f"wrote {args.format} metrics to {args.out}")
    else:
        sys.stdout.write(text)
    return 0


def cmd_top(args) -> int:
    """Live fleet dashboard (``tmx top``): poll heartbeats + per-host
    metrics snapshots under the run root and repaint a terminal view —
    throughput, pipeline depth, bucket occupancy, per-device utilization,
    straggler skew, QC state, degradation state."""
    from tmlibrary_tpu import top

    return top.run_top(Path(args.root), interval=args.interval,
                       once=args.once,
                       as_json=getattr(args, "as_json", False))


def cmd_timeline(args) -> int:
    """Metric history (``tmx timeline``): merge every per-host
    ``tsdb.<host>.jsonl`` segment under the root and render one sparkline
    per series.  Roots that predate the time-series layer fall back to
    replaying their ledgers into synthetic samples, so the verb answers
    on seed-era runs too."""
    from tmlibrary_tpu import timeseries, traceexport

    root = Path(args.root)
    segments = timeseries.load_tsdb(root)
    source = "tsdb"
    records = timeseries.merge_tsdb(segments)
    if not records:
        source = "ledger"
        try:
            events = traceexport.collect_events(root)
        except Exception:
            events = []
        records = timeseries.synthesize_from_ledger(events)
    series = timeseries.series_index(records)
    if args.metric:
        series = {k: v for k, v in series.items() if args.metric in k[0]}
    if getattr(args, "as_json", False):
        doc = {
            "root": str(root), "source": source,
            "series": [
                {
                    "name": name, "labels": dict(labels),
                    "points": [[ts, v] for ts, v in points],
                    "last": points[-1][1] if points else None,
                    "rate_per_s": timeseries.rate(points, args.window),
                    "p95": timeseries.quantile_over_time(points, 0.95),
                }
                for (name, labels), points in sorted(series.items())
            ],
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    if not series:
        print(f"no time-series data under {root}")
        return 1
    print(f"timeline {root} [{source}] — {len(series)} series")
    for (name, labels), points in sorted(series.items()):
        label_txt = ("{" + ",".join(f"{k}={v}" for k, v in labels) + "}"
                     if labels else "")
        spark = timeseries.sparkline([v for _, v in points],
                                     width=args.width)
        last = points[-1][1]
        r = timeseries.rate(points, args.window)
        rate_txt = "" if r is None else f"  rate {r:.3g}/s"
        print(f"  {name}{label_txt}")
        print(f"    {spark}  last {last:g}{rate_txt}  n={len(points)}")
    return 0


def cmd_trace(args) -> int:
    """Dump the span tree (run > step > batch > phase) with the critical
    path marked ``*`` at every level — the chain the run's wall time
    actually went to.  Accepts serve roots too (the spooled job specs
    point at their experiment ledgers), and ``--export chrome`` writes
    the whole thing as Trace Event Format JSON."""
    from tmlibrary_tpu import serve as serve_mod
    from tmlibrary_tpu import telemetry, traceexport

    root = Path(args.root)
    if getattr(args, "export", None) == "chrome":
        out = Path(args.out or "trace.json")
        try:
            doc = traceexport.export_chrome_trace(
                root, out, trace_id=getattr(args, "trace_id", None))
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        n = len(doc.get("traceEvents", []))
        print(f"wrote {n} trace events -> {out}")
        return 0 if n else 1
    if serve_mod.is_serve_root(root):
        # a serve root has no single span tree — merge every reachable
        # ledger so the text view still answers "where did time go"
        events = traceexport.collect_events(root)
    else:
        store = _open_store(args)
        events = RunLedger(store.workflow_dir / "ledger.jsonl").events()
    if not events:
        print("no run ledger — nothing to trace", file=sys.stderr)
        return 1
    tid = getattr(args, "trace_id", None)
    if tid:
        events = [ev for ev in events if ev.get("trace_id") == tid]
    tree = telemetry.annotate_critical_path(
        telemetry.build_span_tree(events)
    )
    if args.as_json:
        print(json.dumps(tree, indent=2))
        return 0
    print(telemetry.render_span_tree(tree))
    totals = telemetry.phase_totals(events)
    if totals:
        phases = "  ".join(f"{k}={v:.3f}s"
                           for k, v in sorted(totals.items(),
                                              key=lambda kv: -kv[1]))
        print(f"\nphase totals (critical resource): {phases}")
    return 0


def cmd_slo(args) -> int:
    """Per-tenant SLO report over a serve root's ledger: p50/p95 job
    latency vs the latency objective, availability vs the availability
    objective, and multi-window burn rates.

    Exit codes (pinned, same discipline as qc/bench_regression):
    0 ok · 1 some tenant's burn >= 1 · 3 no job-completion data."""
    from tmlibrary_tpu import serve as serve_mod
    from tmlibrary_tpu import slo as slo_mod

    root = Path(args.root)
    if not serve_mod.serve_ledger_paths(root):
        # experiment roots have no job completions — say so with the
        # pinned no-data code rather than a generic error
        print(f"no serve ledger under {root} — `tmx slo` reads a serve "
              "root", file=sys.stderr)
        return slo_mod.EXIT_NO_DATA
    # merged per-host history: fleet burn is one report, not N
    view = slo_mod.report(serve_mod.serve_ledger_events(root))
    if getattr(args, "as_json", False):
        print(json.dumps(view, indent=2))
    else:
        print(slo_mod.render(view), end="")
    return slo_mod.exit_code(view)


def cmd_qc(args) -> int:
    """Data-quality report for a run: per-step table, per-channel image
    stats, numerics guards, worst-focus sites, flagged sites — plus the
    drift-sentinel verdict vs a reference profile.

    Exit codes (pinned, same discipline as scripts/bench_regression.py):
    0 ok · 1 drift · 2 stale reference · 3 no reference."""
    from tmlibrary_tpu import qc as qc_mod

    root = Path(args.root)
    wf = _open_store(args).workflow_dir
    pairs = qc_mod.load_run_profiles(wf)
    if pairs:
        profile = (qc_mod.merge_profiles(pairs) if len(pairs) > 1
                   else pairs[0][1])
        source = (f"qc.json x{len(pairs)} host(s)" if len(pairs) > 1
                  else "qc.json")
    else:
        events = RunLedger(wf / "ledger.jsonl").events()
        profile = qc_mod.qc_from_ledger(events) if events else {}
        source = "ledger"
    if not (profile.get("steps") or profile.get("channels")):
        print("no QC evidence for this run — submit with --qc (or "
              "TMX_QC=1) to collect it", file=sys.stderr)
        return 1

    kind = getattr(args, "profile_kind", "run")
    if kind == "model":
        # the model deploy gate: only the __model__.* sketches count,
        # judged against the committed checkpoint baseline
        ref_path = args.reference or os.environ.get("TMX_QC_DL_BASELINE")
        if not ref_path and Path("tuning/QC_DL_BASELINE.json").exists():
            ref_path = "tuning/QC_DL_BASELINE.json"
        if not qc_mod.filter_profile_kind(profile, "model").get("features"):
            print("no model-output sketches in this run's profile — the "
                  "pipeline has no DL modules or ran without --qc",
                  file=sys.stderr)
            return 1
    else:
        ref_path = args.reference or os.environ.get("TMX_QC_BASELINE")
        if not ref_path and Path("tuning/QC_BASELINE.json").exists():
            ref_path = "tuning/QC_BASELINE.json"
    profile = qc_mod.filter_profile_kind(profile, kind)
    reference = qc_mod.load_profile(Path(ref_path)) if ref_path else None
    reference = qc_mod.filter_profile_kind(reference, kind)
    verdict = qc_mod.compare_profiles(
        profile, reference, threshold=args.threshold,
        stale_hours=args.stale_hours,
    )

    if getattr(args, "as_json", False):
        print(json.dumps({"root": str(root), "source": source,
                          "profile": profile, "reference": ref_path,
                          "verdict": verdict},
                         indent=2, default=float))
        return verdict["exit_code"]

    print(f"tmx qc — {root}  (source: {source})")
    steps = profile.get("steps") or {}
    if steps:
        print(f"  {'step':<16} {'batches':>7} {'sites':>7} {'flagged':>7}")
        for name, e in sorted(steps.items()):
            print(f"  {name:<16} {e.get('batches', 0):>7} "
                  f"{e.get('sites', 0):>7} {e.get('flagged', 0):>7}")
    channels = profile.get("channels") or {}
    if channels:
        print("channels:")
        for ch, metrics in sorted(channels.items()):
            foc = metrics.get("focus_tenengrad") or {}
            sat = metrics.get("saturation_frac") or {}
            bg = metrics.get("background") or {}
            bits = [f"  {ch:<12}"]
            if foc.get("min") is not None:
                bits.append(f"focus min {foc['min']:.4g}")
            if sat.get("max") is not None:
                bits.append(f"saturation max {sat['max']:.2%}")
            if bg.get("mean") is not None:
                bits.append(f"background {bg['mean']:.1f}")
            print("  ".join(bits))
    if kind == "model":
        feats = profile.get("features") or {}
        if feats:
            print("model output sketches:")
            for name, s in sorted(feats.items()):
                print(f"  {name:<28} n {int(s.get('count') or 0):>8}  "
                      f"p50 {float(s.get('p50') or 0.0):.4g}  "
                      f"p95 {float(s.get('p95') or 0.0):.4g}")
    guards = profile.get("guards") or {}
    nan_cols = guards.get("nan_columns") or []
    line = (f"guards: nan columns {len(nan_cols)}  nan/inf values "
            f"{int(guards.get('nan_values') or 0) + int(guards.get('inf_values') or 0)}"
            f"  count z max {float(guards.get('count_z_max') or 0.0):.2f}")
    if guards.get("capacity_saturated_batches"):
        line += (f"  capacity-saturated batches "
                 f"{guards['capacity_saturated_batches']}")
    print(line)
    if nan_cols:
        print(f"  non-finite feature columns: {', '.join(nan_cols[:8])}"
              + (" ..." if len(nan_cols) > 8 else ""))
    worst = (profile.get("worst_sites") or [])[:max(args.worst, 0)]
    if worst:
        print(f"worst {len(worst)} site(s) by focus:")
        for w in worst:
            print(f"  site {w.get('site', '?'):>5}  "
                  f"{str(w.get('channel', '?')):<12} "
                  f"focus {w.get('focus', 0.0):.4g}")
    flagged_total = int(profile.get("flagged_total") or 0)
    if flagged_total:
        print(f"flagged: {flagged_total} site(s)")
        for f in (profile.get("flagged") or [])[:max(args.worst, 0)]:
            bits = [f"  site {f.get('site', '?'):>5}",
                    str(f.get('reason', '?'))]
            if f.get("channel"):
                bits.append(f"[{f['channel']}]")
            if f.get("value") is not None:
                bits.append(f"value {f['value']:.4g}")
            if f.get("z") is not None:
                bits.append(f"z {f['z']:+.1f}")
            print("  ".join(bits))

    line = f"drift verdict: {verdict['status']} (exit {verdict['exit_code']})"
    if reference is not None:
        line += f"  vs {ref_path}  checked {verdict.get('checked', 0)}"
    if verdict.get("age_hours") is not None:
        line += f"  reference age {verdict['age_hours']:.1f}h"
    print(line)
    for d in verdict.get("drifted", [])[:10]:
        if d.get("kind") == "median_shift":
            print(f"  DRIFT {d['feature']}: p50 "
                  f"{d['reference_p50']:.4g} -> {d['current_p50']:.4g} "
                  f"(|Δ| {d['delta']:.4g} > allowed {d['allowed']:.4g})")
        elif d.get("kind") == "new_nan":
            print(f"  DRIFT {d['feature']}: {d['current_nan']} non-finite "
                  "value(s) not present in the reference")
        elif d.get("kind") == "saturation":
            print(f"  DRIFT channel {d['channel']}: saturation max "
                  f"{d['reference_max']:.2%} -> {d['current_max']:.2%}")
    return verdict["exit_code"]


def cmd_weights(args) -> int:
    """DL checkpoint inventory / digests (``tmlibrary_tpu.nn``).

    ``tmx weights list`` — one row per ``.npz`` in the weights
    directory; ``tmx weights digest SPEC`` — resolve any weight spec
    (named checkpoint, path, or ``seed:N``) and print the content
    digest that keys the compiled-program cache and the bench
    sentinel's provenance."""
    from tmlibrary_tpu import nn

    if args.verb == "list":
        rows = nn.list_weights(args.dir)
        if getattr(args, "as_json", False):
            print(json.dumps(rows, indent=2, default=str))
            return 0
        if not rows:
            print(f"no checkpoints in {args.dir or nn.weights_dir()}")
            return 0
        print(f"{'name':<24} {'digest':<14} {'arrays':>7} {'params':>10}")
        for r in rows:
            print(f"{r['name']:<24} {r['digest']:<14} "
                  f"{r['n_arrays']:>7} {r['n_params']:>10}")
        return 0
    # digest
    _params, digest, config = nn.resolve_weights(args.spec)
    if getattr(args, "as_json", False):
        print(json.dumps({"spec": args.spec, "digest": digest,
                          "config": dataclasses.asdict(config)}))
        return 0
    print(f"{args.spec}  digest {digest}  "
          f"(in={config.in_channels}, base={config.base_channels}, "
          f"depth={config.depth})")
    return 0


def _snapshot_gauge(snapshot: dict, name: str) -> "float | None":
    for entry in snapshot.get("gauges", []):
        if entry.get("name") == name:
            return entry.get("value")
    return None


def _snapshot_counter(snapshot: dict, name: str) -> float:
    total = 0.0
    for entry in snapshot.get("counters", []):
        if entry.get("name") == name:
            total += float(entry.get("value") or 0)
    return total


def _perf_schedule_summary(events: list) -> list:
    """Per-step packing readout from the ledger alone: the recorded
    ``schedule_plan`` provenance (digest, predicted occupancy/skew for
    packed vs the directory-order counterfactual) joined with what the
    run actually delivered (mean ``batch_done`` slot occupancy, mean
    actual shard-work spread from ``shard_objects``, plan hit rate from
    escalation-free planned batches)."""
    plans: dict[str, dict] = {}
    actual: dict[str, dict] = {}
    for ev in events:
        step = str(ev.get("step", "")) or "unknown"
        if ev.get("event") == "schedule_plan":
            plans[step] = ev  # last plan wins (resume re-appends the same)
        if ev.get("event") != "batch_done":
            continue
        res = ev.get("result")
        if not isinstance(res, dict):
            continue
        agg = actual.setdefault(step, {
            "occ": [], "spread": [], "pred_skew": [],
            "planned": 0, "hits": 0,
        })
        if isinstance(res.get("slot_occupancy"), (int, float)):
            agg["occ"].append(float(res["slot_occupancy"]))
        shard = res.get("shard_objects")
        if isinstance(shard, list) and len(shard) > 1:
            vals = [float(v) for v in shard]
            agg["spread"].append(max(vals) - min(vals))
        if isinstance(res.get("predicted_skew"), (int, float)):
            agg["pred_skew"].append(float(res["predicted_skew"]))
        if res.get("schedule_rung"):
            agg["planned"] += 1
            if not res.get("bucket_escalations"):
                agg["hits"] += 1
    out = []
    mean = lambda xs: round(sum(xs) / len(xs), 4) if xs else None  # noqa: E731
    for step in sorted(set(plans) | set(actual)):
        plan = plans.get(step, {})
        agg = actual.get(step, {})
        if not plan and not agg.get("planned"):
            continue
        out.append({
            "step": step,
            "mode": plan.get("mode"),
            "source": plan.get("source"),
            "plan_digest": plan.get("plan_digest"),
            "n_batches": plan.get("n_batches"),
            "pred_occupancy_packed": plan.get("pred_occupancy_packed"),
            "pred_occupancy_unpacked": plan.get("pred_occupancy_unpacked"),
            "pred_skew_packed": plan.get("pred_skew_packed"),
            "pred_skew_unpacked": plan.get("pred_skew_unpacked"),
            "mean_slot_occupancy": mean(agg.get("occ", [])),
            "mean_shard_object_spread": mean(agg.get("spread", [])),
            "mean_predicted_skew": mean(agg.get("pred_skew", [])),
            "planned_batches": agg.get("planned", 0),
            "plan_hit_rate": (
                round(agg["hits"] / agg["planned"], 4)
                if agg.get("planned") else None
            ),
        })
    return out


def _perf_strategy_comparison(programs: list) -> list:
    """Group program profiles by (program, step, capacity) and keep the
    groups recorded under two or more reduction strategies — the
    fused-vs-unfused readout: same program identity, strategies side by
    side with FLOPs/bytes/arithmetic-intensity/bound_by, so a kernel win
    (or loss) is readable without re-deriving it from the gauges."""
    groups: dict = {}
    for e in programs:
        if not isinstance(e, dict):
            continue
        key = (str(e.get("program") or "?"), str(e.get("step") or "?"),
               e.get("capacity"))
        groups.setdefault(key, []).append(e)
    out = []
    for (program, step, capacity), entries in groups.items():
        strategies = {e.get("strategy") for e in entries}
        if len(strategies) < 2:
            continue
        variants = sorted(
            entries, key=lambda e: str(e.get("strategy") or "")
        )
        out.append({
            "program": program,
            "step": step,
            "capacity": capacity,
            "variants": [
                {
                    "strategy": v.get("strategy"),
                    "flops": v.get("flops"),
                    "bytes": v.get("bytes"),
                    "arithmetic_intensity": v.get("arithmetic_intensity"),
                    "bound_by": v.get("bound_by"),
                    "compiles": v.get("compiles"),
                }
                for v in variants
            ],
        })
    out.sort(key=lambda g: (g["program"], g["step"], g["capacity"] or 0))
    return out


def cmd_perf(args) -> int:
    """Performance attribution: the per-program roofline table the last
    run recorded (``workflow/perf.json``), the pipelined phase device/host
    breakdown from the ledger, padding-waste gauges — and under the
    ``history`` verb, the bench history + regression-sentinel verdict."""
    from tmlibrary_tpu import perf, tuning

    if getattr(args, "verb", None) == "history":
        return _perf_history(args, perf, tuning)
    if not args.root:
        print("error: --root is required (or use `tmx perf history`)",
              file=sys.stderr)
        return 2
    store = _open_store(args)

    programs: list = []
    perf_path = store.workflow_dir / "perf.json"
    if perf_path.exists():
        try:
            programs = json.loads(perf_path.read_text()).get("programs") or []
        except ValueError:
            print(f"warning: ignoring corrupt perf snapshot {perf_path}",
                  file=sys.stderr)
    if not programs:
        # same-process embedding (tests, notebooks): the live store
        programs = perf.perf_profiles()
    programs = programs[: max(int(args.top), 0) or len(programs)]

    # phase breakdown (device/host split) from the ledger's step events;
    # pre-perf ledgers lack device_s/host_s, so re-derive from the phase
    # resource map when absent
    from tmlibrary_tpu.profiling import PHASE_RESOURCE

    phases_out = []
    events = RunLedger(store.workflow_dir / "ledger.jsonl").events()
    for ev in events:
        if ev.get("event") not in ("step_done", "step_partial"):
            continue
        ps = ev.get("pipeline_stats")
        if not isinstance(ps, dict):
            continue
        phases = ps.get("phases") or {}
        device_s = ps.get("device_s")
        host_s = ps.get("host_s")
        if device_s is None or host_s is None:
            device_s = sum(v.get("total_s", 0.0) for p, v in phases.items()
                           if PHASE_RESOURCE.get(p) == "device")
            host_s = sum(v.get("total_s", 0.0) for p, v in phases.items()
                         if PHASE_RESOURCE.get(p) == "host")
        phases_out.append({
            "step": str(ev.get("step", "")) or "unknown",
            "depth": ps.get("depth"),
            "phases": {p: v.get("total_s", 0.0) for p, v in phases.items()},
            "device_s": round(device_s, 4),
            "host_s": round(host_s, 4),
        })

    # padding-waste gauges from the metrics snapshot (live registry of the
    # last run), falling back to the ledger derivation
    snapshot = {}
    snap_path = store.workflow_dir / "metrics.json"
    if snap_path.exists():
        try:
            snapshot = json.loads(snap_path.read_text())
        except ValueError:
            snapshot = {}
    if not snapshot and events:
        from tmlibrary_tpu import telemetry

        snapshot = telemetry.registry_from_ledger(events).snapshot()
    avoided = _snapshot_gauge(snapshot,
                              "tmx_jterator_padded_flops_avoided_frac")
    occupancy = _snapshot_gauge(snapshot, "tmx_jterator_slot_occupancy")
    schedule_rows = _perf_schedule_summary(events)

    history = tuning.load_bench_history()
    measured = [r for r in history
                if isinstance(r.get("value"), (int, float))
                and r.get("value") and not r.get("error")]
    latest = measured[-1] if measured else None

    strategy_cmp = _perf_strategy_comparison(programs)

    if args.as_json:
        print(json.dumps({
            "programs": programs,
            "strategy_comparison": strategy_cmp,
            "phases": phases_out,
            "padded_flops_avoided_frac": avoided,
            "slot_occupancy": occupancy,
            "schedule": schedule_rows,
            "latest_bench": latest,
        }, indent=2))
        return 0

    if programs:
        print(f"{'program':<24} {'cap':>5} {'strategy':<8} {'backend':<8} "
              f"{'compiles':>8} {'recomp':>6} {'compile_s':>9} "
              f"{'gflops':>9} {'mbytes':>9} {'flops/B':>8} bound-by")
        for e in programs:
            flops = e.get("flops")
            nbytes = e.get("bytes")
            print(
                f"{str(e.get('program', '?')):<24} "
                f"{str(e.get('capacity') or '-'):>5} "
                f"{str(e.get('strategy') or '-'):<8} "
                f"{str(e.get('backend') or '?'):<8} "
                f"{e.get('compiles', 0):>8} "
                f"{e.get('recompiles', 0):>6} "
                f"{round(e.get('compile_seconds_total', 0.0), 2):>9} "
                f"{(round(flops / 1e9, 3) if flops else '-'):>9} "
                f"{(round(nbytes / 1e6, 2) if nbytes else '-'):>9} "
                f"{(e.get('arithmetic_intensity') or '-'):>8} "
                f"{e.get('bound_by') or '-'}"
            )
        print("(roofline verdict vs the v5e reference ridge "
              f"{perf.ridge_point():.0f} FLOPs/byte; MFU/HBM fractions are "
              "runtime numbers — see the bench line below)")
        if strategy_cmp:
            print()
            print("strategy comparison (same program/step/capacity, "
                  "side by side):")
            print(f"{'program':<24} {'step':<10} {'cap':>5} "
                  f"{'strategy':<8} {'gflops':>9} {'mbytes':>9} "
                  f"{'flops/B':>8} bound-by")
            for grp in strategy_cmp:
                for v in grp["variants"]:
                    flops = v.get("flops")
                    nbytes = v.get("bytes")
                    print(
                        f"{str(grp['program']):<24} "
                        f"{str(grp['step']):<10} "
                        f"{str(grp['capacity'] or '-'):>5} "
                        f"{str(v.get('strategy') or '-'):<8} "
                        f"{(round(flops / 1e9, 3) if flops else '-'):>9} "
                        f"{(round(nbytes / 1e6, 2) if nbytes else '-'):>9} "
                        f"{(v.get('arithmetic_intensity') or '-'):>8} "
                        f"{v.get('bound_by') or '-'}"
                    )
    else:
        print("no perf attribution recorded — run `tmx workflow submit` "
              "with telemetry enabled (workflow/perf.json)")
    for row in phases_out:
        parts = "  ".join(f"{p}={s}s" for p, s in row["phases"].items())
        total = row["device_s"] + row["host_s"]
        frac = row["device_s"] / total if total else 0.0
        print(f"phases: {row['step']} depth {row['depth']}: {parts}  "
              f"device={row['device_s']}s host={row['host_s']}s "
              f"({frac:.0%} device)")
    if avoided is not None:
        occ = f" (slot occupancy {occupancy:.2f})" if occupancy else ""
        print(f"padded-FLOPs-avoided: {avoided:.1%}{occ}")
    if schedule_rows:
        print()
        print("schedule packing (workflow/schedule.py — predicted vs "
              "delivered):")
        print(f"{'step':<10} {'mode':<5} {'plan':<16} {'batches':>7} "
              f"{'occ':>6} {'occ-unpacked':>12} {'skew':>8} "
              f"{'skew-unpacked':>13} {'hit-rate':>8}")
        fmt = lambda v, spec=".2f": (  # noqa: E731
            format(float(v), spec) if isinstance(v, (int, float)) else "-"
        )
        for row in schedule_rows:
            occ_actual = (row["mean_slot_occupancy"]
                          if row["mean_slot_occupancy"] is not None
                          else row["pred_occupancy_packed"])
            skew_actual = (row["mean_shard_object_spread"]
                           if row["mean_shard_object_spread"] is not None
                           else row["pred_skew_packed"])
            print(
                f"{row['step']:<10} {str(row['mode'] or '-'):<5} "
                f"{str(row['plan_digest'] or '-'):<16} "
                f"{str(row['n_batches'] or row['planned_batches']):>7} "
                f"{fmt(occ_actual):>6} "
                f"{fmt(row['pred_occupancy_unpacked']):>12} "
                f"{fmt(skew_actual, '.1f'):>8} "
                f"{fmt(row['pred_skew_unpacked'], '.1f'):>13} "
                f"{fmt(row['plan_hit_rate']):>8}"
            )
    if latest:
        print(f"latest bench: {latest.get('metric')} = {latest.get('value')}"
              f" ({latest.get('backend')})"
              f"  mfu_vs_v5e_bf16_peak={latest.get('mfu_vs_v5e_bf16_peak')}"
              f"  hbm_frac={latest.get('hbm_frac_vs_v5e_peak')}")
    return 0


def _perf_history(args, perf, tuning) -> int:
    path = getattr(args, "history", None) or tuning.bench_history_path()
    history = tuning.load_bench_history(path)
    if not history:
        print(f"no bench history at {path} — every bench.py run/sweep "
              "appends one record", file=sys.stderr)
        return 1
    tail = max(int(getattr(args, "tail", 10)), 0)
    print(f"bench history: {len(history)} records at {path}")
    for rec in history[-tail:]:
        bits = [
            str(rec.get("recorded_at", "?")),
            f"config={rec.get('config')}",
            f"backend={rec.get('backend')}",
            f"value={rec.get('value')}",
        ]
        if rec.get("sweep"):
            bits.append("sweep")
        if rec.get("error"):
            bits.append("ERROR")
        qc_rec = rec.get("qc")
        if isinstance(qc_rec, dict):
            if qc_rec.get("worst_focus") is not None:
                bits.append(f"qc_focus={qc_rec['worst_focus']:.4g}")
            if qc_rec.get("nan_columns"):
                bits.append(f"qc_nan_cols={qc_rec['nan_columns']}")
        print("  " + "  ".join(bits) + f"  {rec.get('metric')}")
    stale_hours = getattr(args, "stale_hours", None)
    verdict = perf.compare_history(
        history,
        config=getattr(args, "config", None),
        metric=getattr(args, "metric", None),
        threshold=getattr(args, "threshold", 0.05),
        stale_hours=stale_hours if stale_hours is not None
        else perf.stale_hours(),
    )
    line = f"verdict: {verdict['status']}"
    if verdict.get("delta_frac") is not None:
        line += (f"  delta {verdict['delta_frac']:+.1%} vs best baseline "
                 f"{verdict['baseline'].get('value')}")
    if verdict.get("age_hours") is not None:
        line += f"  age {verdict['age_hours']}h"
    if verdict.get("recapture"):
        line += f"  recapture -> {', '.join(verdict['recapture'])}"
    print(line)
    return 0


def cmd_cache(args) -> int:
    """``tmx cache list|gc`` — inspect and prune the serialized-
    executable store (DESIGN.md §28)."""
    import json as json_mod

    from tmlibrary_tpu import aotstore

    directory = getattr(args, "store_dir", None)
    if args.verb == "list":
        rows = aotstore.list_entries(directory)
        stats = aotstore.store_stats(directory)
        if args.as_json:
            print(json_mod.dumps({"stats": stats, "entries": rows},
                                 indent=2, sort_keys=True))
            return 0
        print(f"store: {stats['dir']}  "
              f"({'enabled' if stats['enabled'] else 'DISABLED'})")
        print(f"fingerprint: {stats['fingerprint']}  entries: "
              f"{stats['entries']}  bytes: {stats['total_bytes']}  "
              f"stale: {stats['stale_entries']}")
        if rows:
            print(f"{'digest':<18} {'program':<24} {'cap':>5} "
                  f"{'strategy':<10} {'size':>9} {'age':>8} fp")
            for m in rows:
                age = m.get("age_s")
                age_txt = "-" if age is None else (
                    f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s")
                fp = str(m.get("fingerprint") or "?")[:8]
                if m.get("stale"):
                    fp += " STALE"
                print(f"{str(m.get('digest'))[:16]:<18} "
                      f"{str(m.get('program'))[:24]:<24} "
                      f"{str(m.get('capacity') if m.get('capacity') is not None else '-'):>5} "
                      f"{str(m.get('strategy') or '-')[:10]:<10} "
                      f"{int(m.get('size_bytes') or 0):>9} "
                      f"{age_txt:>8} {fp}")
        return 0
    if args.verb == "gc":
        max_age_s = (None if args.max_age_days is None
                     else float(args.max_age_days) * 86400.0)
        result = aotstore.prune(
            directory,
            max_bytes=args.max_bytes,
            max_age_s=max_age_s,
            drop_stale_fingerprint=not args.keep_stale,
        )
        if args.as_json:
            print(json_mod.dumps(result, indent=2, sort_keys=True))
            return 0
        print(f"removed {len(result['removed'])} entr"
              f"{'y' if len(result['removed']) == 1 else 'ies'}, "
              f"kept {result['kept']} "
              f"({result['total_bytes']} bytes)")
        for digest in result["removed"]:
            print(f"  - {digest}")
        return 0
    print(f"unknown cache verb: {args.verb}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    # TMX_PLATFORM=cpu forces the backend IN-PROCESS before first use:
    # plain JAX_PLATFORMS is overridden by TPU-relay site configs, and a
    # detached job (tool run-request) inheriting a pinned-but-dead relay
    # would hang in backend init forever
    platform = os.environ.get("TMX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    args = build_parser().parse_args(argv)
    configure_logging(getattr(args, "verbosity", 0))
    from tmlibrary_tpu.config import cfg
    from tmlibrary_tpu.utils import enable_compilation_cache

    # install config (TM_COMPILE_CACHE_DIR / INI) can pin the persistent
    # cache location, e.g. shared scratch on a pod host; unset, the helper
    # falls back to TMX_COMPILE_CACHE_DIR then ~/.cache
    enable_compilation_cache(cfg.compile_cache_dir or None)
    try:
        if args.command == "create":
            return cmd_create(args)
        if args.command == "workflow":
            return cmd_workflow(args)
        if args.command == "serve":
            return cmd_serve(args)
        if args.command == "enqueue":
            return cmd_enqueue(args)
        if args.command == "query":
            return cmd_query(args)
        if args.command == "index":
            return cmd_index(args)
        if args.command == "tool":
            return cmd_tool(args)
        if args.command == "project":
            return cmd_project(args)
        if args.command == "inspect":
            return cmd_inspect(args)
        if args.command == "log":
            return cmd_log(args)
        if args.command == "export":
            return cmd_export(args)
        if args.command == "metrics":
            return cmd_metrics(args)
        if args.command == "top":
            return cmd_top(args)
        if args.command == "timeline":
            return cmd_timeline(args)
        if args.command == "trace":
            return cmd_trace(args)
        if args.command == "slo":
            return cmd_slo(args)
        if args.command == "qc":
            return cmd_qc(args)
        if args.command == "weights":
            return cmd_weights(args)
        if args.command == "perf":
            return cmd_perf(args)
        if args.command == "cache":
            return cmd_cache(args)
        return cmd_step(args)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
