"""Logging configuration.

Reference parity: ``tmlib/log.py`` — ``configure_logging`` plus
``map_logging_verbosity`` translating a ``-v`` count into a logging level.
"""

from __future__ import annotations

import logging
import sys

#: verbosity count (number of ``-v`` flags) → logging level
_VERBOSITY_TO_LEVEL = {
    0: logging.WARNING,
    1: logging.INFO,
    2: logging.DEBUG,
}

FORMAT = "%(asctime)s | %(levelname)-8s | %(name)s | %(message)s"


def map_logging_verbosity(verbosity: int) -> int:
    """Map a ``-v`` flag count to a :mod:`logging` level.

    Mirrors the reference's mapping: 0 → WARNING, 1 → INFO, ≥2 → DEBUG.
    """
    if verbosity < 0:
        raise ValueError("verbosity must be non-negative")
    return _VERBOSITY_TO_LEVEL.get(min(verbosity, 2), logging.DEBUG)


def configure_logging(verbosity: int = 0, stream=None) -> logging.Logger:
    """Configure the root framework logger and return it."""
    logger = logging.getLogger("tmlibrary_tpu")
    logger.setLevel(map_logging_verbosity(verbosity))
    if not logger.handlers:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(logging.Formatter(FORMAT))
        logger.addHandler(handler)
    return logger


#: keys already warned about by :func:`warn_once` this process
_WARNED: set = set()


def warn_once(logger: logging.Logger, key: str, message: str, *args) -> None:
    """Emit ``message`` at WARNING level at most once per process per
    ``key``.  Used for conditions that re-trigger on every poll — e.g. a
    corrupt ledger line re-read by every ``status``/``resume`` call —
    where repeating the warning drowns the signal it carries."""
    if key in _WARNED:
        return
    _WARNED.add(key)
    logger.warning(message, *args)


def reset_warned() -> None:
    """Clear the :func:`warn_once` suppression set.

    The set is process-global, so a warning suppressed in one test would
    otherwise hide the assertion target of another — ``tests/conftest.py``
    calls this between tests."""
    _WARNED.clear()
