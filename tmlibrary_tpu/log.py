"""Logging configuration.

Reference parity: ``tmlib/log.py`` — ``configure_logging`` plus
``map_logging_verbosity`` translating a ``-v`` count into a logging level.
"""

from __future__ import annotations

import logging
import sys

#: verbosity count (number of ``-v`` flags) → logging level
_VERBOSITY_TO_LEVEL = {
    0: logging.WARNING,
    1: logging.INFO,
    2: logging.DEBUG,
}

FORMAT = "%(asctime)s | %(levelname)-8s | %(name)s | %(message)s"


def map_logging_verbosity(verbosity: int) -> int:
    """Map a ``-v`` flag count to a :mod:`logging` level.

    Mirrors the reference's mapping: 0 → WARNING, 1 → INFO, ≥2 → DEBUG.
    """
    if verbosity < 0:
        raise ValueError("verbosity must be non-negative")
    return _VERBOSITY_TO_LEVEL.get(min(verbosity, 2), logging.DEBUG)


def configure_logging(verbosity: int = 0, stream=None) -> logging.Logger:
    """Configure the root framework logger and return it."""
    logger = logging.getLogger("tmlibrary_tpu")
    logger.setLevel(map_logging_verbosity(verbosity))
    if not logger.handlers:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(logging.Formatter(FORMAT))
        logger.addHandler(handler)
    return logger
