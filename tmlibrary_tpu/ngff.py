"""First-party OME-NGFF (OME-Zarr v0.4) plate export and import.

The reference reads/writes vendor formats through Bio-Formats and serves
pyramids from its tile tables (SURVEY.md §3 Readers/Writers/Tile rows).
The modern interchange standard for high-content screens is OME-NGFF: a
Zarr v2 hierarchy with ``plate`` / ``well`` / ``multiscales`` metadata.
Neither ``zarr`` nor ``tensorstore`` ships in this environment, so this
module implements the subset of the Zarr v2 spec the NGFF layout needs
from scratch — C-order chunked arrays with ``.zarray`` JSON headers,
zlib or raw compression, dot-separated chunk keys — plus the NGFF 0.4
HCS metadata, giving the framework a standards-compliant road out
(``tmx export --ngff``) and back in (the ``ngff`` metaconfig handler +
:class:`NGFFReader` container protocol).

Layout written (one plate):

```
plate.zarr/
  .zgroup                      {"zarr_format": 2}
  .zattrs                      {"plate": {rows, columns, wells, ...}}
  A/1/.zgroup  .zattrs         {"well": {"images": [{"path": "0"}, ...]}}
  A/1/0/.zgroup .zattrs        {"multiscales": [...], "omero": {...}}
  A/1/0/0/.zarray  0.0.0.0.0   level-0 (t, c, z, y, x) chunks
  A/1/0/1/...                  2x-downsampled levels
```
"""

from __future__ import annotations

import json
import zlib
from pathlib import Path

import numpy as np

from tmlibrary_tpu.errors import MetadataError

NGFF_VERSION = "0.4"
_AXES = [
    {"name": "t", "type": "time"},
    {"name": "c", "type": "channel"},
    {"name": "z", "type": "space"},
    {"name": "y", "type": "space"},
    {"name": "x", "type": "space"},
]


# ------------------------------------------------------------ zarr v2 arrays
def _dtype_str(dtype: np.dtype) -> str:
    dtype = np.dtype(dtype)
    if dtype.itemsize == 1:
        return "|" + dtype.str[1:]
    return "<" + dtype.str[1:]  # little-endian on disk


def zarr_write_array(
    path: Path,
    arr: np.ndarray,
    chunks: tuple[int, ...],
    compressor: str | None = "zlib",
    level: int = 1,
) -> None:
    """Write ``arr`` as a Zarr v2 array directory (C order, fill 0,
    dot-separated chunk keys).  Edge chunks are stored full-size padded
    with the fill value, exactly as the spec requires."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    chunks = tuple(int(min(c, s)) if s else int(c)
                   for c, s in zip(chunks, arr.shape))
    meta = {
        "zarr_format": 2,
        "shape": list(arr.shape),
        "chunks": list(chunks),
        "dtype": _dtype_str(arr.dtype),
        "compressor": (
            {"id": "zlib", "level": int(level)} if compressor == "zlib"
            else None
        ),
        "fill_value": 0,
        "order": "C",
        "filters": None,
        "dimension_separator": ".",
    }
    (path / ".zarray").write_text(json.dumps(meta, indent=2))
    arr = np.ascontiguousarray(arr, dtype=np.dtype(meta["dtype"]))
    grid = [range(0, s, c) for s, c in zip(arr.shape, chunks)]
    from itertools import product

    for origin in product(*grid):
        sel = tuple(
            slice(o, min(o + c, s))
            for o, c, s in zip(origin, chunks, arr.shape)
        )
        block = arr[sel]
        if block.shape != chunks:  # edge chunk: pad to full chunk shape
            full = np.zeros(chunks, arr.dtype)
            full[tuple(slice(0, e) for e in block.shape)] = block
            block = full
        raw = np.ascontiguousarray(block).tobytes()
        if compressor == "zlib":
            raw = zlib.compress(raw, int(level))
        key = ".".join(str(o // c) for o, c in zip(origin, chunks))
        (path / key).write_bytes(raw)


def _zarray_meta(path: Path) -> dict:
    try:
        meta = json.loads((Path(path) / ".zarray").read_text())
    except (OSError, ValueError) as exc:
        raise MetadataError(f"not a zarr array: {path}: {exc}") from exc
    # validate structure HERE so every consumer can index freely: a
    # corrupted document would otherwise leak KeyError/TypeError past
    # the ingest skip-unreadable contract (fuzz-caught)
    try:
        shape = [int(x) for x in meta["shape"]]
        chunks = [int(x) for x in meta["chunks"]]
        np.dtype(meta["dtype"])
    except (KeyError, TypeError, ValueError) as exc:
        raise MetadataError(f"corrupt zarr metadata at {path}: {exc}") from exc
    total = 1
    for s in shape:
        total *= max(s, 1)
    chunk_elems = 1
    for c in chunks:
        chunk_elems *= max(c, 1)
    # magnitude sanity (generous: 2G elements total, 128M per chunk): a
    # corrupt/malicious document declaring absurd dims would otherwise
    # reach np.zeros(shape) and leak ValueError/MemoryError — or OOM —
    # past the skip-unreadable contract
    if (len(shape) != len(chunks) or not chunks
            or any(c < 1 for c in chunks) or any(s < 0 for s in shape)
            or total > (1 << 31) or chunk_elems > (1 << 27)):
        raise MetadataError(f"nonsensical zarr shape/chunks at {path}")
    comp = meta.get("compressor")
    if comp is not None and not isinstance(comp, dict):
        raise MetadataError(f"corrupt zarr compressor entry at {path}")
    meta["shape"], meta["chunks"] = shape, chunks
    meta["dimension_separator"] = str(meta.get("dimension_separator", "."))
    return meta


def _read_chunk(path: Path, meta: dict, idx: tuple[int, ...]) -> np.ndarray:
    chunks = meta["chunks"]
    dtype = np.dtype(meta["dtype"])
    sep = meta["dimension_separator"]
    key = sep.join(str(i) for i in idx)
    f = Path(path) / key
    if not f.exists():
        try:
            return np.full(chunks, meta.get("fill_value") or 0, dtype)
        except (TypeError, ValueError) as exc:  # corrupt fill_value
            raise MetadataError(
                f"corrupt zarr fill_value at {path}: {exc}"
            ) from exc
    raw = f.read_bytes()
    comp = meta.get("compressor")
    if comp is not None:
        if comp.get("id") != "zlib":
            raise MetadataError(
                f"unsupported zarr compressor {comp.get('id')!r} "
                f"(first-party reader handles zlib/raw)"
            )
        try:
            raw = zlib.decompress(raw)
        except zlib.error as exc:
            raise MetadataError(
                f"corrupt zarr chunk {key} at {path}: {exc}"
            ) from exc
    if meta.get("filters"):
        raise MetadataError("zarr filters are not supported")
    order = meta.get("order", "C")
    try:
        return np.frombuffer(raw, dtype).reshape(chunks, order=order)
    except (ValueError, TypeError) as exc:  # wrong byte count / order
        raise MetadataError(
            f"corrupt zarr chunk {key} at {path}: {exc}"
        ) from exc


def zarr_read_array(path: Path) -> np.ndarray:
    """Read a whole Zarr v2 array directory into memory."""
    meta = _zarray_meta(path)
    shape, chunks = meta["shape"], meta["chunks"]
    out = np.zeros(shape, np.dtype(meta["dtype"]))
    from itertools import product

    grid = [range((s + c - 1) // c) for s, c in zip(shape, chunks)]
    for idx in product(*grid):
        block = _read_chunk(path, meta, idx)
        sel = tuple(
            slice(i * c, min((i + 1) * c, s))
            for i, c, s in zip(idx, chunks, shape)
        )
        out[sel] = block[tuple(slice(0, sl.stop - sl.start) for sl in sel)]
    return out


def zarr_read_plane(path: Path, t: int, c: int, z: int) -> np.ndarray:
    """One (y, x) plane of a 5-D (t, c, z, y, x) Zarr array, touching
    only the chunks that intersect it."""
    meta = _zarray_meta(path)
    shape, chunks = meta["shape"], meta["chunks"]
    if len(shape) != 5:
        raise MetadataError(f"expected a 5-D tczyx array at {path}")
    h, w = shape[3], shape[4]
    out = np.zeros((h, w), np.dtype(meta["dtype"]))
    ci = (t // chunks[0], c // chunks[1], z // chunks[2])
    off = (t % chunks[0], c % chunks[1], z % chunks[2])
    for yi in range((h + chunks[3] - 1) // chunks[3]):
        for xi in range((w + chunks[4] - 1) // chunks[4]):
            block = _read_chunk(path, meta, (*ci, yi, xi))
            y0, x0 = yi * chunks[3], xi * chunks[4]
            ye, xe = min(y0 + chunks[3], h), min(x0 + chunks[4], w)
            out[y0:ye, x0:xe] = block[off][: ye - y0, : xe - x0]
    return out


# ----------------------------------------------------------- plate metadata
def _well_name(row: int, col: int) -> tuple[str, str]:
    return chr(ord("A") + row), str(col + 1)


def _downsample_2x(plane: np.ndarray) -> np.ndarray:
    """2x2 mean pool (display levels); odd edges are cropped, matching
    the zoomify convention of ops/pyramid."""
    h, w = plane.shape
    he, we = h - h % 2, w - w % 2
    pooled = plane[:he, :we].reshape(he // 2, 2, we // 2, 2).mean((1, 3))
    if np.issubdtype(plane.dtype, np.integer):
        pooled = np.round(pooled)
    return pooled.astype(plane.dtype)


def _write_label_image(
    field_dir: Path,
    name: str,
    stack: np.ndarray,
    n_levels: int,
    chunk_yx: int,
    compressor: str | None,
) -> None:
    """One NGFF 0.4 ``image-label`` under ``<field>/labels/<name>``:
    a 5-D (t, 1, z, y, x) int32 multiscale whose display levels use
    nearest subsampling (mean-pooling label ids would invent objects).
    The ``labels/`` group listing is written by the caller — one listing
    per export run, so names from a previous export into the same
    directory are never advertised."""
    img_dir = field_dir / "labels" / name
    img_dir.mkdir(parents=True, exist_ok=True)
    (img_dir / ".zgroup").write_text(json.dumps({"zarr_format": 2}))
    datasets = []
    level = stack
    for lvl in range(n_levels):
        if lvl:
            # crop odd edges BEFORE subsampling — the exact level shapes
            # of the image pyramid's _downsample_2x, so viewers that pair
            # multiscale levels by index see aligned overlays
            h, w = level.shape[3], level.shape[4]
            level = level[:, :, :, : h - h % 2 : 2, : w - w % 2 : 2]
            if level.shape[3] < 1 or level.shape[4] < 1:
                break
        zarr_write_array(
            img_dir / str(lvl), level, (1, 1, 1, chunk_yx, chunk_yx),
            compressor,
        )
        datasets.append({
            "path": str(lvl),
            "coordinateTransformations": [{
                "type": "scale",
                "scale": [1.0, 1.0, 1.0, float(2 ** lvl), float(2 ** lvl)],
            }],
        })
    (img_dir / ".zattrs").write_text(json.dumps({
        "multiscales": [{
            "version": NGFF_VERSION,
            "name": name,
            "axes": _AXES,
            "datasets": datasets,
        }],
        "image-label": {
            "version": NGFF_VERSION,
            "source": {"image": "../../"},
        },
    }, indent=2))


def write_ngff_plate(
    store,
    out: Path,
    n_levels: int = 3,
    chunk_yx: int = 256,
    compressor: str | None = "zlib",
    label_names: list[str] | None = None,
) -> Path:
    """Export the experiment store as one OME-NGFF 0.4 HCS plate.

    Every (well, site, tpoint, zplane, channel) plane is read from the
    store (raw, as ingested) and written as 5-D tczyx multiscale fields
    grouped ``<row>/<col>/<field>``; ``n_levels`` 2x display levels per
    field.  ``label_names`` additionally exports those segmentation
    stacks as NGFF ``image-label`` multiscales under each field's
    ``labels/`` group (the standard road for masks, reference parity:
    MapobjectSegmentation rows served to the viewer).  Returns the plate
    root (``<out>``, conventionally ``*.zarr``)."""
    out = Path(out)
    exp = store.experiment
    # fail fast on a mistyped/partial label name BEFORE any plate I/O —
    # aborting mid-export would leave a partial .zarr the user has to
    # clean up.  Every (tpoint, zplane) the field loop will read must
    # exist, not just t0/z0 (a jterator run on one tpoint of a
    # multi-tpoint experiment is exactly the partial case)
    for lname in label_names or []:
        for t in range(exp.n_tpoints):
            for z in range(exp.n_zplanes):
                if not store.has_labels(lname, tpoint=t, zplane=z):
                    raise MetadataError(
                        f"no segmentation stack named {lname!r} for "
                        f"tpoint {t} zplane {z} (run jterator first, or "
                        f"check --ngff-labels spelling)"
                    )
    refs = list(exp.sites())
    n_t, n_z = exp.n_tpoints, exp.n_zplanes
    n_c = len(exp.channels)

    by_well: dict[tuple[int, int], list] = {}
    for i, r in enumerate(refs):
        by_well.setdefault((r.well_row, r.well_column), []).append((i, r))

    rows = sorted({wr for wr, _ in by_well})
    cols = sorted({wc for _, wc in by_well})
    plate_attrs = {
        "plate": {
            "version": NGFF_VERSION,
            "name": exp.name,
            "rows": [{"name": _well_name(r, 0)[0]} for r in rows],
            "columns": [{"name": _well_name(0, c)[1]} for c in cols],
            "wells": [
                {
                    "path": "/".join(_well_name(wr, wc)),
                    "rowIndex": rows.index(wr),
                    "columnIndex": cols.index(wc),
                }
                for wr, wc in sorted(by_well)
            ],
            "field_count": max(len(v) for v in by_well.values()),
        }
    }
    out.mkdir(parents=True, exist_ok=True)
    (out / ".zgroup").write_text(json.dumps({"zarr_format": 2}))
    (out / ".zattrs").write_text(json.dumps(plate_attrs, indent=2))

    omero = {
        "channels": [
            {"label": ch.name, "active": True}
            for ch in exp.channels
        ],
        "version": NGFF_VERSION,
    }
    for (wr, wc), sites in sorted(by_well.items()):
        rname, cname = _well_name(wr, wc)
        well_dir = out / rname / cname
        well_dir.mkdir(parents=True, exist_ok=True)
        (well_dir / ".zgroup").write_text(json.dumps({"zarr_format": 2}))
        (well_dir / ".zattrs").write_text(json.dumps({
            "well": {
                "images": [{"path": str(f)} for f in range(len(sites))],
                "version": NGFF_VERSION,
            }
        }, indent=2))
        for field, (site_idx, _ref) in enumerate(sites):
            field_dir = well_dir / str(field)
            field_dir.mkdir(parents=True, exist_ok=True)
            (field_dir / ".zgroup").write_text(
                json.dumps({"zarr_format": 2})
            )
            # level 0: (t, c, z, y, x)
            planes = np.stack([
                np.stack([
                    np.stack([
                        store.read_sites(
                            [site_idx], channel=c, tpoint=t, zplane=z
                        )[0]
                        for z in range(n_z)
                    ])
                    for c in range(n_c)
                ])
                for t in range(n_t)
            ])
            datasets = []
            level = planes
            for lvl in range(n_levels):
                if lvl:
                    level = np.stack([
                        np.stack([
                            np.stack([
                                _downsample_2x(level[t, c, z])
                                for z in range(n_z)
                            ])
                            for c in range(n_c)
                        ])
                        for t in range(n_t)
                    ])
                    if level.shape[3] < 1 or level.shape[4] < 1:
                        break
                zarr_write_array(
                    field_dir / str(lvl), level,
                    (1, 1, 1, chunk_yx, chunk_yx), compressor,
                )
                datasets.append({
                    "path": str(lvl),
                    "coordinateTransformations": [{
                        "type": "scale",
                        "scale": [1.0, 1.0, 1.0, float(2 ** lvl),
                                  float(2 ** lvl)],
                    }],
                })
            (field_dir / ".zattrs").write_text(json.dumps({
                "multiscales": [{
                    "version": NGFF_VERSION,
                    "name": f"{rname}{cname}/{field}",
                    "axes": _AXES,
                    "datasets": datasets,
                }],
                "omero": omero,
            }, indent=2))
            if label_names:
                labels_dir = field_dir / "labels"
                labels_dir.mkdir(parents=True, exist_ok=True)
                (labels_dir / ".zgroup").write_text(
                    json.dumps({"zarr_format": 2})
                )
                # the listing is THIS run's names only — never merged
                # with a previous export's leftovers in the same dir
                (labels_dir / ".zattrs").write_text(
                    json.dumps({"labels": list(label_names)}, indent=2)
                )
            for lname in label_names or []:
                stack = np.stack([
                    np.stack([
                        np.stack([
                            store.read_labels(
                                [site_idx], lname, tpoint=t, zplane=z
                            )[0]
                            for z in range(n_z)
                        ])
                    ])  # single label "channel"
                    for t in range(n_t)
                ])
                _write_label_image(
                    field_dir, lname, stack, n_levels, chunk_yx,
                    compressor,
                )
    return out


# ------------------------------------------------------- container protocol
def _level0_name(attrs: dict) -> str:
    """The first multiscale dataset's path — the level-0 array directory.
    Our writer uses ``"0"``, but the spec only promises SOME path, so
    wild images (``scale0``, ``s0``…) must be followed, not assumed."""
    try:
        return str(attrs["multiscales"][0]["datasets"][0]["path"])
    except (KeyError, IndexError, TypeError):
        return "0"


class NGFFReader:
    """Container-protocol reader over an OME-NGFF directory — an HCS
    plate, or a bare multiscale image (the most common OME-Zarr form in
    the wild), which reads as a one-well one-field plate.

    Matches the :mod:`tmlibrary_tpu.readers` container conventions
    (context manager, ``height``/``width``, a linear page decode) so a
    ``*.zarr`` directory ingests exactly like an ND2/CZI/LIF file.  The
    linear page convention (shared with the ``ngff`` metaconfig handler,
    which writes it into the file mappings) is::

        page = (((well * F + field) * T + t) * C + c) * Z + z

    with wells in plate-attrs order and F/T/C/Z the uniform per-field
    dimensions (non-uniform plates raise).  ``is_plate`` tells the two
    forms apart — for a bare image the handler assigns the well from the
    filename instead of plate metadata.
    """

    def __init__(self, path):
        self.path = Path(path)

    def _enter_bare_image(self, attrs: dict):
        """A root-level ``multiscales`` image: one well at (0, 0), one
        field whose directory IS the container root."""
        self.is_plate = False
        self.well_paths = [""]
        self.well_indices = [(0, 0)]
        self.fields_per_well = [1]
        self.field_paths = [[""]]
        self.level0_names = [[_level0_name(attrs)]]
        meta = _zarray_meta(self.path / self.level0_names[0][0])
        if len(meta["shape"]) != 5:
            raise MetadataError(
                f"NGFF image {self.path} is not 5-D tczyx"
            )
        dims = tuple(meta["shape"])
        self.channel_names = None
        omero = attrs.get("omero") or {}
        if isinstance(omero.get("channels"), list):
            self.channel_names = [
                ch.get("label", f"C{i:02d}")
                for i, ch in enumerate(omero["channels"])
            ]
        self.n_fields = 1
        self.n_tpoints, self.n_channels, self.n_zplanes = dims[:3]
        self.height, self.width = dims[3], dims[4]
        return self

    def __enter__(self):
        # one broad guard over BOTH the plate and bare-image parsing:
        # valid-JSON type corruption ("rowIndex": null, "omero": "x",
        # string channel entries) raises TypeError/AttributeError at
        # scattered consumers — all of it must surface as the
        # MetadataError the ingest skip-unreadable contract expects
        try:
            return self._enter_impl()
        except MetadataError:
            raise
        except (KeyError, TypeError, ValueError, AttributeError,
                IndexError) as exc:
            raise MetadataError(
                f"malformed NGFF metadata in {self.path}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc

    def _enter_impl(self):
        attrs_file = self.path / ".zattrs"
        try:
            attrs = json.loads(attrs_file.read_text())
        except (OSError, ValueError) as exc:
            raise MetadataError(
                f"not an NGFF plate: {self.path}: {exc}"
            ) from exc
        plate = attrs.get("plate")
        if not plate or "wells" not in plate:
            if attrs.get("multiscales"):
                return self._enter_bare_image(attrs)
            raise MetadataError(
                f"no HCS 'plate' or 'multiscales' metadata in {attrs_file}"
            )
        self.is_plate = True
        try:
            self.well_paths = [w["path"] for w in plate["wells"]]
        except (KeyError, TypeError) as exc:
            raise MetadataError(
                f"malformed plate wells entry in {attrs_file}: {exc}"
            ) from exc
        self.well_indices = [
            (int(w.get("rowIndex", 0)), int(w.get("columnIndex", 0)))
            for w in plate["wells"]
        ]
        self.fields_per_well: list[int] = []
        #: per-well field directory names from the well metadata — the
        #: spec does not promise 0-based numeric image paths, so the
        #: linear page decode must index THESE, not str(field)
        self.field_paths: list[list[str]] = []
        #: per-(well, field) level-0 dataset directory names (the spec
        #: only promises some multiscales datasets[0].path, not "0")
        self.level0_names: list[list[str]] = []
        dims = None
        self.channel_names: list[str] | None = None
        for wp in self.well_paths:
            well_dir = self.path / wp
            try:
                wattrs = json.loads((well_dir / ".zattrs").read_text())
                images = wattrs["well"]["images"]
                paths = [img["path"] for img in images]
            except (OSError, ValueError, KeyError, TypeError) as exc:
                raise MetadataError(
                    f"bad NGFF well at {well_dir}: {exc}"
                ) from exc
            self.fields_per_well.append(len(images))
            self.field_paths.append(paths)
            well_levels: list[str] = []
            for img in images:
                field_dir = well_dir / img["path"]
                try:
                    fattrs = json.loads(
                        (field_dir / ".zattrs").read_text()
                    )
                except (OSError, ValueError):
                    fattrs = {}
                lvl0 = _level0_name(fattrs)
                well_levels.append(lvl0)
                meta = _zarray_meta(field_dir / lvl0)
                if len(meta["shape"]) != 5:
                    raise MetadataError(
                        f"NGFF field {field_dir} is not 5-D tczyx"
                    )
                if dims is None:
                    dims = tuple(meta["shape"])
                elif tuple(meta["shape"]) != dims:
                    raise MetadataError(
                        f"non-uniform NGFF fields: {field_dir} has "
                        f"{meta['shape']}, expected {list(dims)}"
                    )
                if self.channel_names is None:
                    try:
                        self.channel_names = [
                            ch.get("label", f"C{i:02d}")
                            for i, ch in enumerate(
                                fattrs["omero"]["channels"]
                            )
                        ]
                    except (KeyError, TypeError):
                        pass
            self.level0_names.append(well_levels)
        if dims is None:
            raise MetadataError(f"NGFF plate {self.path} has no fields")
        if len(set(self.fields_per_well)) != 1:
            raise MetadataError(
                f"non-uniform field counts per well in {self.path}: "
                f"{self.fields_per_well}"
            )
        self.n_fields = self.fields_per_well[0]
        self.n_tpoints, self.n_channels, self.n_zplanes = dims[:3]
        self.height, self.width = dims[3], dims[4]
        return self

    def __exit__(self, *exc) -> None:
        pass

    @property
    def n_wells(self) -> int:
        return len(self.well_paths)

    def read_plane_linear(self, page: int) -> np.ndarray:
        t_sz, c_sz, z_sz = self.n_tpoints, self.n_channels, self.n_zplanes
        per_field = t_sz * c_sz * z_sz
        field_lin, rem = divmod(page, per_field)
        well, field = divmod(field_lin, self.n_fields)
        t, rem = divmod(rem, c_sz * z_sz)
        c, z = divmod(rem, z_sz)
        if well >= len(self.well_paths):
            raise MetadataError(
                f"page {page} out of range for {self.path}"
            )
        field_dir = (
            self.path / self.well_paths[well]
            / self.field_paths[well][field]
            / self.level0_names[well][field]
        )
        return zarr_read_plane(field_dir, t, c, z)
