"""Crash-consistent file writes: the tmp+rename discipline in one place.

Several subsystems persist small JSON artifacts next to the run ledger
(metrics snapshots, heartbeats, QC profiles, perf attribution, tuning
verdicts).  A reader racing a writer — ``tmx top`` polling a live run,
or a resumed process inspecting the artifacts a killed one left behind —
must never observe a half-written file, and a hard kill mid-write must
never corrupt the previous good version.  POSIX ``rename(2)`` within a
directory is atomic, so every writer here follows the same protocol:
write the full payload to a sibling temp file, then rename over the
target.  Readers either see the old complete file or the new complete
file, nothing in between.

The temp name embeds the writer's PID so two processes targeting the
same path (a sampler thread and an engine ``finally`` block, or two
fleet hosts mis-configured onto one file) cannot interleave partial
writes into one temp file; the last rename wins, which is the same
last-write-wins semantics whole-file writes always had.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any


def atomic_write_text(path: Path | str, text: str,
                      fsync: bool = False) -> None:
    """Write ``text`` to ``path`` atomically (tmp + rename).

    With ``fsync=True`` the payload is flushed to stable storage before
    the rename, making the write crash-*durable* as well as
    crash-consistent — the ledger-adjacent artifacts default to
    consistency only, matching the ledger's own ``ledger_fsync`` knob.
    """
    path = Path(path)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        with open(tmp, "w") as f:
            f.write(text)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        # a failure between open and replace must not litter temp files
        if tmp.exists():
            tmp.unlink(missing_ok=True)


def atomic_write_json(path: Path | str, obj: Any,
                      fsync: bool = False, **dumps_kwargs: Any) -> None:
    """``atomic_write_text`` for a JSON payload (serialized first, so a
    serialization error can never leave a partial file either)."""
    atomic_write_text(path, json.dumps(obj, **dumps_kwargs), fsync=fsync)


def claim_rename(src: Path | str, dst: Path | str) -> bool:
    """Atomically move ``src`` to ``dst``; returns whether *this caller*
    won the move.

    This is the fleet spool protocol's claim arbiter (DESIGN.md §25):
    several hosts polling one spool directory race to ``rename(2)`` the
    same source file, POSIX guarantees exactly one rename observes the
    source, and every loser gets ``ENOENT`` — converted here to a plain
    ``False`` so "someone else claimed it" is a decision, not an error.
    The destination may already exist (a stale copy left by a crashed
    reaper); rename atomically replaces it, which is exactly the
    last-write-wins recovery those torn sweeps need.
    """
    try:
        os.replace(src, dst)
        return True
    except FileNotFoundError:
        return False
