"""First-party OLE2 Compound File Binary (CFB) parser.

The container of Olympus ``.oib`` acquisitions (and several other legacy
microscopy formats: Zeiss ``.zvi``, older ``.ipw``) is Microsoft's
structured-storage format — a FAT filesystem in a file.  The reference
reads these through Bio-Formats' OLE support on the JVM (SURVEY.md §3
Readers row); this is the no-JVM equivalent: header → DIFAT → FAT →
directory tree → per-stream payloads, with the mini-FAT handling streams
below the 4096-byte cutoff.

Scope: read-only, version 3 (512-byte sectors) and version 4 (4096-byte
sectors), little-endian per spec.  Corruption (cycles, out-of-range
sectors, truncation) raises :class:`~tmlibrary_tpu.errors.MetadataError`
so ingest skips the file instead of crashing.
"""

from __future__ import annotations

import struct

from tmlibrary_tpu.errors import MetadataError

_MAGIC = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
_ENDOFCHAIN = 0xFFFFFFFE
_FREESECT = 0xFFFFFFFF
_NOSTREAM = 0xFFFFFFFF
_SPECIAL = 0xFFFFFFFA  # any id >= this is a sentinel, not a sector

#: hard caps so a corrupt FAT cannot balloon memory: no real OIB in a
#: microscopy source tree has more than a few thousand plane streams
_MAX_SECTORS = 1 << 22          # 2 GiB of 512-byte sectors
_MAX_DIR_ENTRIES = 1 << 16


class CompoundFile:
    """Parse a CFB container from ``buf`` (bytes, memoryview or mmap).

    Stream payloads are extracted LAZILY: the constructor walks only the
    FAT and the directory tree; ``stream_paths`` lists the slash-joined
    storage paths (root storage omitted, e.g.
    ``"Storage00001/Stream00000"``) and :meth:`read_stream` materializes
    one payload on demand — an open reader over a multi-GB container
    holds the directory tables, not the pixel data (the reader cache
    keeps up to 64 containers open during ingest).  ``streams``
    materializes everything at once for small containers and tests.
    """

    def __init__(self, buf, filename="<buf>"):
        self._buf = memoryview(buf)
        self._name = str(filename)
        if len(self._buf) < 512 or bytes(self._buf[:8]) != _MAGIC:
            raise MetadataError(f"not a compound file: {self._name}")
        (major,) = struct.unpack_from("<H", self._buf, 26)
        (sector_shift,) = struct.unpack_from("<H", self._buf, 30)
        (mini_shift,) = struct.unpack_from("<H", self._buf, 32)
        if (major, sector_shift) not in ((3, 9), (4, 12)) or mini_shift != 6:
            raise MetadataError(
                f"unsupported compound file layout (version {major}, "
                f"sector shift {sector_shift}) in {self._name}"
            )
        self._sec = 1 << sector_shift
        self._mini = 1 << mini_shift
        (self._n_fat,) = struct.unpack_from("<I", self._buf, 44)
        (self._dir_start,) = struct.unpack_from("<I", self._buf, 48)
        (self._cutoff,) = struct.unpack_from("<I", self._buf, 56)
        (self._minifat_start,) = struct.unpack_from("<I", self._buf, 60)
        (difat_start,) = struct.unpack_from("<I", self._buf, 68)
        (n_difat,) = struct.unpack_from("<I", self._buf, 72)
        self._fat = self._parse_fat(difat_start, n_difat)
        self._minifat = self._read_fat_table(self._minifat_start)
        entries = self._parse_directory()
        self._root = entries[0]
        self._ministream: "bytes | None" = None
        self._paths = self._walk(entries)
        self.stream_paths = tuple(self._paths)

    # ------------------------------------------------------------- sectors
    def _sector(self, sid: int) -> memoryview:
        # the header occupies the space of one 512-byte sector; in v4
        # files sector 0 still starts at byte 4096 (one full sector)
        off = self._sec + sid * self._sec
        if sid >= _SPECIAL or off + self._sec > len(self._buf):
            raise MetadataError(f"sector {sid} out of range in {self._name}")
        return self._buf[off:off + self._sec]

    def _parse_fat(self, difat_start: int, n_difat: int) -> list:
        ids = list(struct.unpack_from("<109I", self._buf, 76))
        sid, seen = difat_start, set()
        while sid < _SPECIAL:
            if sid in seen or len(seen) > n_difat + 16:
                raise MetadataError(f"DIFAT cycle in {self._name}")
            seen.add(sid)
            sec = self._sector(sid)
            per = self._sec // 4 - 1
            ids.extend(struct.unpack_from(f"<{per}I", sec, 0))
            (sid,) = struct.unpack_from("<I", sec, self._sec - 4)
        fat: list = []
        per = self._sec // 4
        for fid in ids:
            if fid >= _SPECIAL:
                continue
            fat.extend(struct.unpack_from(f"<{per}I", self._sector(fid), 0))
        return fat

    def _chain(self, start: int, table: list) -> list:
        out: list = []
        seen: set = set()
        sid = start
        while sid < _SPECIAL:
            if sid >= len(table) or len(out) > _MAX_SECTORS:
                raise MetadataError(
                    f"broken sector chain (sid {sid}) in {self._name}"
                )
            if sid in seen:
                raise MetadataError(f"sector chain cycle in {self._name}")
            seen.add(sid)
            out.append(sid)
            sid = table[sid]
        return out

    def _read_chain(self, start: int) -> bytes:
        return b"".join(bytes(self._sector(s)) for s in self._chain(start, self._fat))

    def _read_fat_table(self, start: int) -> list:
        if start >= _SPECIAL:
            return []
        raw = self._read_chain(start)
        return list(struct.unpack_from(f"<{len(raw) // 4}I", raw, 0))

    # ----------------------------------------------------------- directory
    def _parse_directory(self) -> list[dict]:
        raw = self._read_chain(self._dir_start)
        entries = []
        for off in range(0, min(len(raw), _MAX_DIR_ENTRIES * 128), 128):
            chunk = raw[off:off + 128]
            if len(chunk) < 128:
                break
            (name_len,) = struct.unpack_from("<H", chunk, 64)
            obj_type = chunk[66]
            if obj_type == 0 or not 2 <= name_len <= 64:
                entries.append(None)
                continue
            name = chunk[: name_len - 2].decode("utf-16-le", "replace")
            left, right, child = struct.unpack_from("<3I", chunk, 68)
            (start,) = struct.unpack_from("<I", chunk, 116)
            (size,) = struct.unpack_from("<Q", chunk, 120)
            if self._sec == 512:
                size &= 0xFFFFFFFF  # v3: only the low 4 bytes are valid
            entries.append({
                "name": name, "type": obj_type, "left": left,
                "right": right, "child": child, "start": start,
                "size": size,
            })
        if not entries or entries[0] is None or entries[0]["type"] != 5:
            raise MetadataError(f"compound file without root entry: {self._name}")
        return entries

    def _walk(self, entries: list) -> dict[str, dict]:
        paths: dict[str, dict] = {}
        visited: set = set()
        # explicit stack: each storage's children form a binary tree of
        # siblings, and real OIBs hold one stream per plane — a
        # right-leaning chain thousands deep would blow Python's
        # recursion limit
        stack = [(entries[0]["child"], "")]
        while stack:
            eid, prefix = stack.pop()
            if eid == _NOSTREAM or eid >= len(entries):
                continue
            if eid in visited:  # cycles in a corrupt tree
                raise MetadataError(f"directory tree cycle in {self._name}")
            visited.add(eid)
            e = entries[eid]
            if e is None:
                continue
            stack.append((e["left"], prefix))
            stack.append((e["right"], prefix))
            path = prefix + e["name"]
            if e["type"] == 1:  # storage
                stack.append((e["child"], path + "/"))
            elif e["type"] == 2:  # stream
                paths.setdefault(path, e)
        return paths

    def read_stream(self, path: str) -> bytes:
        """Materialize one stream payload."""
        e = self._paths.get(path)
        if e is None:
            raise MetadataError(f"no stream {path!r} in {self._name}")
        size = e["size"]
        if size == 0:
            return b""
        if size < self._cutoff:  # mini stream (64-byte sectors)
            if self._ministream is None:
                root = self._root
                self._ministream = (
                    self._read_chain(root["start"])[: root["size"]]
                    if root["start"] < _SPECIAL and root["size"] else b""
                )
            out = bytearray()
            for sid in self._chain(e["start"], self._minifat):
                lo = sid * self._mini
                if lo + self._mini > len(self._ministream):
                    raise MetadataError(
                        f"mini sector {sid} beyond mini stream in {self._name}"
                    )
                out += self._ministream[lo:lo + self._mini]
            return bytes(out[:size])
        return self._read_chain(e["start"])[:size]

    @property
    def streams(self) -> dict[str, bytes]:
        """All payloads at once (small containers, tests)."""
        return {p: self.read_stream(p) for p in self._paths}
