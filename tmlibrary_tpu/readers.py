"""Context-manager readers.

Reference parity: ``tmlib/readers.py`` — ``ImageReader`` (cv2),
``BFImageReader`` (Bio-Formats via javabridge upstream; here a working
facade over the first-party container parsers — no JVM),
``DatasetReader`` (HDF5/h5py), ``JsonReader``, ``XmlReader``,
``TablesReader`` (pandas/HDF) — all usable as context managers.

These exist for workflow-script parity: framework-internal IO goes through
:mod:`tmlibrary_tpu.models.store`, but user analysis scripts written
against the reference's reader API translate 1:1.
"""

from __future__ import annotations

import json
import threading as _threading
from abc import ABC
from pathlib import Path
from xml.etree import ElementTree

import numpy as np

from tmlibrary_tpu.errors import NotSupportedError


class Reader(ABC):
    """Base context-manager reader (reference ``tmlib.readers.Reader``)."""

    def __init__(self, filename):
        self.filename = Path(filename)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _container_reader(path):
    """The container Reader class for ``path``, or None for plain images."""
    name = str(path).lower()
    if name.endswith(".nd2"):
        return ND2Reader
    if name.endswith(".czi"):
        return CZIReader
    if name.endswith(".lif"):
        return LIFReader
    if name.endswith((".dv", ".r3d")):
        return DVReader
    if name.endswith(".ims"):
        return IMSReader
    if name.endswith(".stk"):
        return STKReader
    if name.endswith(".lsm"):
        return LSMReader
    if name.endswith(".oib"):
        return OIBReader
    if name.endswith(".oif"):
        return OIFReader
    if name.endswith(".flex"):
        return FlexReader
    if name.endswith(".zarr"):  # OME-NGFF plate directory (covers .ome.zarr)
        from tmlibrary_tpu.ngff import NGFFReader

        return NGFFReader
    return None


def _container_plane(reader, page: int) -> np.ndarray:
    """One plane from an OPEN container reader by the linear page index
    its metaconfig handler writes (the single home of that convention:
    ND2 ``seq * n_components + comp``, CZI ``(((s*M+m)*C+c)*Z+z)*T+t``,
    LIF ``series * C*Z*T + (c*Z+z)*T + t``)."""
    if isinstance(reader, ND2Reader):
        seq, comp = divmod(page, reader.n_components)
        return reader.read_plane(seq, comp)
    if isinstance(reader, LIFReader):
        return reader.read_plane_global(page)
    # CZI/NGFF/DV/IMS/STK/LSM and Olympus OIF/OIB all expose the shared
    # linear-page decode
    return reader.read_plane_linear(page)


#: (path, mtime_ns, size) -> open container reader.  imextract's decode
#: loop calls read_container_plane once PER PLANE; re-parsing a per-well
#: container's whole chunk map / subblock directory / XML header for
#: every plane would be O(planes^2) parse work per file.  Readers are
#: read-only after __enter__, so sharing one across the decode thread
#: pool is safe; eviction only DROPS the reference (the mmap closes when
#: the last user's reference is garbage-collected), so a concurrent
#: reader can never see a closed mapping.
_OPEN_READERS: dict = {}
_OPEN_READERS_CAP = 64
_open_readers_lock = _threading.Lock()


#: TIFF-flavored containers: when the dedicated reader rejects one (RGB,
#: 32-bit, exotic compression), the file is still a TIFF that the plain
#: native-TIFF/cv2 path may decode — fall back instead of failing ingest.
_TIFF_FLAVORED = (".stk", ".lsm", ".flex")


def _open_container(path):
    """``cls(path).__enter__()`` for container paths, or None when the
    path is a plain image OR a TIFF-flavored container whose dedicated
    reader declines it (the caller then uses the TIFF/cv2 decode path,
    which handles RGB and 32-bit single-IFD stacks the STK/LSM readers
    reject)."""
    cls = _container_reader(path)
    if cls is None:
        return None
    try:
        return cls(path).__enter__()
    except NotSupportedError:
        if str(path).lower().endswith(_TIFF_FLAVORED):
            return None
        raise


#: negative-cache sentinel: a TIFF-flavored container the dedicated
#: reader declined.  Without it, imextract's per-plane loop would
#: re-open and re-parse the declined header on EVERY plane read — the
#: exact O(planes^2) work the reader cache exists to prevent.
_DECLINED = object()


def _cached_container_reader(path):
    import os

    if _container_reader(path) is None:
        return None
    st = os.stat(path)
    key = (str(path), st.st_mtime_ns, st.st_size)
    with _open_readers_lock:
        reader = _OPEN_READERS.get(key)
    if reader is _DECLINED:
        return None
    if reader is not None:
        return reader
    reader = _open_container(path)
    if reader is None:
        with _open_readers_lock:
            while len(_OPEN_READERS) >= _OPEN_READERS_CAP:
                _OPEN_READERS.pop(next(iter(_OPEN_READERS)))
            _OPEN_READERS.setdefault(key, _DECLINED)
        return None
    with _open_readers_lock:
        while len(_OPEN_READERS) >= _OPEN_READERS_CAP:
            _OPEN_READERS.pop(next(iter(_OPEN_READERS)))
        winner = _OPEN_READERS.setdefault(key, reader)
    if winner is not reader:  # lost an open race: release our fds now
        reader.__exit__()
    return winner


def read_container_plane(path, page: int) -> np.ndarray | None:
    """One container plane by linear page index; None for non-container
    paths (imextract's thread-pooled per-plane loader uses this).  The
    parsed container stays cached across calls — see ``_OPEN_READERS``."""
    reader = _cached_container_reader(path)
    if reader is None:
        return None
    return _container_plane(reader, page)


def container_dimensions(path) -> tuple[int, int] | None:
    """(height, width) of a container's planes, or None for non-container
    paths (metaconfig's site-shape probe uses this)."""
    r = _open_container(path)
    if r is None:
        return None
    try:
        return r.height, r.width
    finally:
        r.__exit__()


#: path -> (validation_key, (byteorder, ifds)) — the value offsets in
#: the parsed entries are plain ints, independent of any open buffer, so
#: the parse survives across per-plane re-opens.  Bounded per-path LRU
#: (capacity >= imextract's default batch grouping, which cycles page 0
#: of every file before page 1): without it, the per-plane loop re-walks
#: every IFD of a multi-page stack for every plane — O(planes^2).
#: Accessed from imextract's decode thread pool, so all dict mutation
#: sits under the lock.
import collections as _collections
import threading as _threading

_TIFF_PY_PARSE_CACHE: "_collections.OrderedDict[str, tuple]" = (
    _collections.OrderedDict()
)
_TIFF_PY_PARSE_CACHE_MAX = 64
_TIFF_PY_PARSE_LOCK = _threading.Lock()


def _tiff_parse_spans_key(m, spans) -> tuple:
    """Freshness key for a cached parse: a crc per parse-relevant byte
    range — the header plus every IFD table span recorded by
    ``_tiff_parse``.  mtime alone misses same-size in-place rewrites
    inside one filesystem timestamp tick, and a fixed head/tail probe
    misses mid-file IFDs (multi-page BigTIFFs interleave IFDs with pixel
    data; round-4 advisor finding).  Value arrays the IFD entries point
    at are NOT covered — they are dereferenced against the live mmap at
    decode time, so the parse can never serve stale bytes from them."""
    import zlib

    return tuple(
        (s, e, zlib.crc32(m[s:e])) for s, e in [(0, min(len(m), 16))] + spans
    )


def read_tiff_page_py(path, page: int) -> "np.ndarray | None":
    """First-party Python fallback for TIFF pages the native C++ page
    reader declines — BigTIFF (magic 43) and deflate-compressed strips —
    limited to 8/16-bit grayscale strip layouts.  Returns None when the
    file is not such a TIFF (caller falls through to cv2), so a failure
    here can never mask a format cv2 could still decode."""
    import mmap
    import os
    import struct

    from tmlibrary_tpu.errors import MetadataError, NotSupportedError

    try:
        with open(path, "rb") as f, mmap.mmap(
            f.fileno(), 0, access=mmap.ACCESS_READ
        ) as m:
            st = os.fstat(f.fileno())
            stat_key = (st.st_mtime_ns, st.st_size, st.st_ino)
            spath = str(path)
            with _TIFF_PY_PARSE_LOCK:
                entry = _TIFF_PY_PARSE_CACHE.get(spath)
            hit = None
            if entry is not None and entry[0] == stat_key:
                # re-crc the exact ranges the cached parse read (outside
                # the lock: mmap reads of an unchanged file are pure)
                import zlib

                if all(
                    e <= len(m) and zlib.crc32(m[s:e]) == c
                    for s, e, c in entry[1]
                ):
                    hit = entry[2]
                    with _TIFF_PY_PARSE_LOCK:
                        if spath in _TIFF_PY_PARSE_CACHE:
                            _TIFF_PY_PARSE_CACHE.move_to_end(spath)
            if hit is None:
                spans: list = []
                hit = _tiff_parse(m, spans)  # outside the lock: pure
                key = _tiff_parse_spans_key(m, spans)
                with _TIFF_PY_PARSE_LOCK:
                    _TIFF_PY_PARSE_CACHE[spath] = (stat_key, key, hit)
                    _TIFF_PY_PARSE_CACHE.move_to_end(spath)
                    while (len(_TIFF_PY_PARSE_CACHE)
                           > _TIFF_PY_PARSE_CACHE_MAX):
                        _TIFF_PY_PARSE_CACHE.popitem(last=False)
            bo, ifds = hit
            if not 0 <= page < len(ifds):
                return None
            return _gray_ifd_plane(bo, m, ifds[page], path,
                                   "plain TIFF pages")
    except (OSError, ValueError, MetadataError, NotSupportedError,
            struct.error):
        return None


class ImageReader(Reader):
    """Read 2-D image files; grayscale TIFFs decode through the
    first-party native reader (``native.tiff_read``) with the Python
    paged fallback (:func:`read_tiff_page_py`: BigTIFF, deflate), Nikon
    ND2 / Zeiss CZI containers through the first-party chunk parsers
    (``page`` is the linear plane index their metaconfig handlers write;
    the parsed chunk map is cached for the context's lifetime),
    everything else (PNG, RGB, tiled TIFF) through cv2.  uint8/uint16
    preserved."""

    def __enter__(self):
        self._container = _open_container(self.filename)
        return self

    def __exit__(self, *exc):
        if getattr(self, "_container", None) is not None:
            self._container.__exit__()
            self._container = None
        return False

    def read(self, page: int = 0) -> np.ndarray:
        container = getattr(self, "_container", None)
        if container is not None:
            return _container_plane(container, page)
        out = read_container_plane(self.filename, page)  # non-context use
        if out is not None:
            return out
        if str(self.filename).lower().endswith((".tif", ".tiff")):
            from tmlibrary_tpu.native import tiff_read_page

            img = tiff_read_page(self.filename, page)  # ONE file load
            if img is not None:
                return img
            img = read_tiff_page_py(self.filename, page)
            if img is not None:
                return img

        import cv2

        img = cv2.imread(str(self.filename), cv2.IMREAD_UNCHANGED)
        if img is None:
            raise FileNotFoundError(f"cannot read image: {self.filename}")
        if img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2GRAY)
        return img


class BFImageReader(Reader):
    """Bio-Formats-compatible facade over the first-party container
    readers.

    The reference reads vendor microscope formats through the Java
    Bio-Formats library (``python-bioformats``/``javabridge``,
    ``tmlib/readers.py`` ``BFImageReader.read(filename)``).  This image
    has no JVM; instead the call delegates to the native parsers —
    Nikon ND2, Zeiss CZI/LSM, Leica LIF, DeltaVision DV/R3D, Imaris IMS,
    MetaMorph STK, Olympus OIF/OIB, Opera FLEX, OME-NGFF — and to the plain
    TIFF/PNG path for everything else, so reference analysis scripts
    using this class keep working for every format the rebuild models.
    A genuinely unsupported container still raises a clear
    :class:`~tmlibrary_tpu.errors.NotSupportedError` up front instead of
    failing deep inside a job.
    """

    def read(self, page: int = 0) -> np.ndarray:
        # MetadataError (corrupt/truncated container) propagates as-is —
        # it names the structural problem; only "nothing can read this
        # EXISTING file" becomes the NotSupportedError of the reference's
        # API contract.  A missing path is a path problem, not a format
        # problem — advising format conversion for a typo would mislead.
        try:
            return ImageReader(self.filename).read(page)
        except (OSError, ValueError, NotSupportedError) as exc:
            if not self.filename.exists():
                raise FileNotFoundError(
                    f"no such image file: {self.filename}"
                ) from exc
            raise NotSupportedError(
                f"no native reader for {self.filename} (Bio-Formats/JVM "
                "is not available; supported containers: nd2, czi, lif, "
                "dv/r3d, ims, stk, lsm, oif/oib, flex, zarr, plus "
                "TIFF/PNG) — convert other vendor containers to one of "
                "these"
            ) from exc


class ND2Reader(Reader):
    """First-party reader for Nikon NIS-Elements ``.nd2`` containers
    (modern chunk-map layout, "v3").

    Narrows the Bio-Formats gap (reference reads ND2 through the Java
    Bio-Formats library, SURVEY.md §3 Readers row) with a native parser
    for the common high-content layout: XY-position sequences x
    interleaved channel components, uint16.

    Container structure parsed here:

    - every chunk starts with a 16-byte header ``<u32 magic=0x0ABECEDA>
      <u32 name_len> <u64 data_len>`` followed by the ASCII chunk name
      (ending ``!``) and ``data_len`` bytes of payload;
    - the last 8 bytes of the file hold the offset of the chunk-map
      chunk, whose payload lists ``name + <u64 offset> <u64 size>``
      entries terminated by the map's own signature name;
    - ``ImageAttributesLV!`` holds dimensions in the "lite variants"
      key-value encoding (``uiWidth``/``uiHeight``/``uiComp``/
      ``uiBpcInMemory``/``uiSequenceCount`` under ``SLxImageAttributes``);
    - ``ImageDataSeq|<n>!`` holds one sequence's pixels: an 8-byte
      acquisition timestamp (f64) followed by row-major uint16 samples
      interleaved across components.

    Acquisition loops (time / XY-position / Z-stack nesting) decode from
    the ``ImageMetadataLV!`` SLxExperiment tree (:meth:`loop_shape` /
    :meth:`seq_coords`), with an unmodeled or inconsistent experiment
    falling back to flat sequences-as-sites; compressed payloads or
    non-uint16 samples raise
    :class:`~tmlibrary_tpu.errors.MetadataError` with a clear message
    rather than mis-decoding.
    """

    MAGIC = 0x0ABECEDA
    SIG_FILE = b"ND2 FILE SIGNATURE CHUNK NAME01!"
    SIG_MAP = b"ND2 CHUNK MAP SIGNATURE 0000001!"

    def __enter__(self):
        import mmap

        from tmlibrary_tpu.errors import MetadataError

        # mmap, not read_bytes(): imextract's thread pool opens one reader
        # per plane, and holding whole multi-GB containers per thread would
        # OOM the host — the chunk map lets every access touch only its
        # own chunk's pages
        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError as exc:  # empty file
            self._file.close()
            raise MetadataError(f"not an ND2 v3 container: {self.filename}") from exc
        if len(self._data) < 56 or self._data[16:48] != self.SIG_FILE:
            self.__exit__()
            raise MetadataError(f"not an ND2 v3 container: {self.filename}")
        import struct

        try:
            self._chunks = self._parse_chunk_map()
            attrs = self._attributes()
        except MetadataError:
            self.__exit__()
            raise
        except (struct.error, OverflowError, IndexError, ValueError,
                UnicodeDecodeError) as exc:
            # a truncated file keeps a valid signature but its trailing
            # bytes parse as garbage offsets — callers (the nd2 metaconfig
            # handler) skip on MetadataError, not on raw struct errors
            self.__exit__()
            raise MetadataError(
                f"corrupt ND2 container {self.filename}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        try:
            # .get + coercion guard: a corrupt LV tree can drop uiHeight
            # or retype any value to a string/bytes (fuzz-caught) — both
            # must land in the nonsensical-attributes MetadataError below
            self.width = int(attrs.get("uiWidth", 0))
            self.height = int(attrs.get("uiHeight", 0))
            self.n_components = int(attrs.get("uiComp", 1))
            self.bits = int(attrs.get("uiBpcInMemory", 16))
        except (TypeError, ValueError):
            self.width = self.height = self.n_components = -1
            self.bits = 16
        if self.width <= 0 or self.height <= 0 or self.n_components < 1:
            # uiComp=0 would reach divmod(page, 0) at decode time
            self.__exit__()
            raise MetadataError(
                f"{self.filename}: nonsensical attributes (width="
                f"{self.width}, height={self.height}, "
                f"components={self.n_components})"
            )
        if self.bits != 16:
            self.__exit__()
            raise MetadataError(
                f"{self.filename}: only uint16 ND2 payloads are supported "
                f"(uiBpcInMemory={self.bits})"
            )
        # eCompression per the public nd2 attribute convention:
        # 0 = lossless (zlib stream after the 8-byte timestamp),
        # 1 = lossy (JPEG2000 — no first-party decoder), else/absent = raw
        comp = attrs.get("eCompression")
        self._lossless = comp == 0
        if comp == 1:
            self.__exit__()
            from tmlibrary_tpu.errors import NotSupportedError

            raise NotSupportedError(
                f"{self.filename}: lossy-compressed ND2 (eCompression=1) "
                "is not supported (lossless zlib and uncompressed are)"
            )
        n_chunks = sum(1 for n in self._chunks if n.startswith(b"ImageDataSeq|"))
        try:
            declared = int(attrs.get("uiSequenceCount", n_chunks))
        except (TypeError, ValueError):
            # same corrupt-retyped-LV-value class as the block above:
            # fall back to counting what was actually written
            declared = n_chunks
        # an aborted acquisition can declare more sequences than were
        # written; trusting the attribute would emit phantom planes
        self.n_sequences = min(declared, n_chunks)
        return self

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            try:
                self._data.close()
            except (ValueError, AttributeError):
                pass
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    # ------------------------------------------------------------ container
    def _chunk_payload(self, offset: int) -> bytes:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        magic, name_len, data_len = struct.unpack_from("<IIQ", self._data, offset)
        if magic != self.MAGIC:
            raise MetadataError(
                f"{self.filename}: bad chunk magic at offset {offset}"
            )
        start = offset + 16 + name_len
        return bytes(self._data[start:start + data_len])

    def _parse_chunk_map(self) -> dict[bytes, int]:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        (map_offset,) = struct.unpack_from("<Q", self._data, len(self._data) - 8)
        payload = self._chunk_payload(map_offset)
        chunks: dict[bytes, int] = {}
        pos = 0
        while pos < len(payload):
            end = payload.find(b"!", pos)
            if end < 0:
                raise MetadataError(f"{self.filename}: corrupt chunk map")
            name = payload[pos:end + 1]
            if name == self.SIG_MAP:
                break
            offset, _size = struct.unpack_from("<QQ", payload, end + 1)
            chunks[name] = offset
            pos = end + 1 + 16
        if not chunks:
            raise MetadataError(f"{self.filename}: empty chunk map")
        return chunks

    # ------------------------------------------------------- LV metadata
    @classmethod
    def _parse_lv(cls, buf: bytes, pos: int = 0, end: int | None = None) -> dict:
        """Parse "lite variants" key-value metadata: ``<u8 type><u8 name
        chars>`` + UTF-16LE name, value by type (1 u8, 2 i32, 3 u32,
        4 u64, 5 f64, 6 UTF-16 string, 8 length-prefixed bytes,
        11 nested compound with ``<u32 count><u64 byte length>``)."""
        import struct

        out: dict = {}
        next_suffix: dict = {}

        def store(name, value):
            # list compounds (e.g. XYPosLoop Points) repeat one name per
            # element; index-suffix later occurrences so every element
            # survives into the dict in document order instead of each
            # overwriting the last
            if name in out:
                i = next_suffix.get(name, 1)
                while f"{name}~{i}" in out:
                    i += 1
                next_suffix[name] = i + 1
                name = f"{name}~{i}"
            out[name] = value

        end = len(buf) if end is None else end
        while pos < end - 1:
            vtype, name_chars = struct.unpack_from("<BB", buf, pos)
            pos += 2
            name = buf[pos:pos + 2 * name_chars].decode("utf-16-le").rstrip("\x00")
            pos += 2 * name_chars
            if vtype == 1:
                store(name, buf[pos])
                pos += 1
            elif vtype == 2:
                store(name, struct.unpack_from("<i", buf, pos)[0])
                pos += 4
            elif vtype == 3:
                store(name, struct.unpack_from("<I", buf, pos)[0])
                pos += 4
            elif vtype == 4:
                store(name, struct.unpack_from("<Q", buf, pos)[0])
                pos += 8
            elif vtype == 5:
                store(name, struct.unpack_from("<d", buf, pos)[0])
                pos += 8
            elif vtype == 6:
                stop = pos
                while stop < end and buf[stop:stop + 2] != b"\x00\x00":
                    stop += 2
                store(name, buf[pos:stop].decode("utf-16-le"))
                pos = stop + 2
            elif vtype == 8:
                (blen,) = struct.unpack_from("<Q", buf, pos)
                store(name, buf[pos + 8:pos + 8 + blen])
                pos += 8 + blen
            elif vtype == 11:
                _count, blen = struct.unpack_from("<IQ", buf, pos)
                pos += 12
                store(name, cls._parse_lv(buf, pos, pos + blen))
                pos += blen
            else:
                from tmlibrary_tpu.errors import MetadataError

                raise MetadataError(
                    f"unsupported LV value type {vtype} for key '{name}'"
                )
        return out

    def _attributes(self) -> dict:
        from tmlibrary_tpu.errors import MetadataError

        off = self._chunks.get(b"ImageAttributesLV!")
        if off is None:
            raise MetadataError(f"{self.filename}: no ImageAttributesLV chunk")
        tree = self._parse_lv(self._chunk_payload(off))
        # attributes live under an SLxImageAttributes compound
        for v in tree.values():
            if isinstance(v, dict) and "uiWidth" in v:
                return v
        if "uiWidth" in tree:
            return tree
        raise MetadataError(f"{self.filename}: uiWidth missing from attributes")

    # -------------------------------------------------------- loop shape
    #: SLxExperiment eType -> axis kind (values per the public nd2
    #: loop-type enum: TimeLoop=1, XYPosLoop=2, ZStackLoop=4,
    #: NETimeLoop=8); anything else is unmodeled
    _LOOP_KINDS = {1: "T", 2: "XY", 4: "Z", 8: "T"}

    def loop_shape(self) -> "list[tuple[str, int]] | None":
        """Ordered acquisition loops (outermost first, innermost varies
        fastest in the sequence index): ``[("T"|"XY"|"Z", size), ...]``
        from the ``ImageMetadataLV!`` SLxExperiment tree — or None when
        the chunk is absent, a loop type is unmodeled, a kind repeats,
        or the loop product does not equal the written sequence count
        (callers then fall back to sequences = flat sites, the
        pre-loop-support behavior).  Parsed once per open reader."""
        if not hasattr(self, "_loops"):
            self._loops = self._compute_loop_shape()
        return self._loops

    def _compute_loop_shape(self) -> "list[tuple[str, int]] | None":
        import struct

        from tmlibrary_tpu.errors import MetadataError

        off = self._chunks.get(b"ImageMetadataLV!")
        if off is None:
            return None
        try:
            tree = self._parse_lv(self._chunk_payload(off))
        except (MetadataError, struct.error, OverflowError, IndexError,
                UnicodeDecodeError):
            return None

        def find_level(node):
            if isinstance(node, dict):
                if "eType" in node:
                    return node
                for v in node.values():
                    found = find_level(v)
                    if found is not None:
                        return found
            return None

        def find_experiment(node):
            # anchor on the SLxExperiment compound: other metadata
            # blocks carry their own 'eType' fields, and the first one
            # in tree order would silently defeat loop decode
            if isinstance(node, dict):
                exp = node.get("SLxExperiment")
                if isinstance(exp, dict):
                    return exp
                for v in node.values():
                    found = find_experiment(v)
                    if found is not None:
                        return found
            return None

        loops: list = []
        experiment = find_experiment(tree)
        level = find_level(experiment if experiment is not None else tree)
        while level is not None:
            kind = self._LOOP_KINDS.get(level.get("eType"))
            size = level.get("uiLoopSize") or (
                level.get("uLoopPars") or {}
            ).get("uiCount")
            if kind is None or not isinstance(size, int) or size < 1:
                return None
            if any(k == kind for k, _ in loops):
                return None  # nested loops of one kind are unmodeled
            if kind == "XY":
                self._xy_level = level  # stage positions live here
            loops.append((kind, size))
            level = find_level(level.get("ppNextLevelEx"))
        product = 1
        for _, size in loops:
            product *= size
        if not loops or product != self.n_sequences:
            return None
        return loops

    def xy_positions(self) -> "list[tuple[float, float]] | None":
        """(stage_y, stage_x) per XY position, from the XYPosLoop's
        ``uLoopPars`` point list — or None when the loop structure is
        unmodeled or the point count disagrees with the loop size.  The
        nd2 handler turns these into within-well grid coordinates."""
        loops = self.loop_shape()  # also binds self._xy_level
        level = getattr(self, "_xy_level", None)
        if not loops or level is None:
            return None
        n_xy = dict(loops).get("XY")

        def collect(node, out):
            if isinstance(node, dict):
                x, y = node.get("dPosX"), node.get("dPosY")
                if isinstance(x, (int, float)) and isinstance(y, (int, float)):
                    out.append((float(y), float(x)))
                    return  # a point's children are calibration noise
                # document order, NOT sorted(): point keys are not
                # guaranteed zero-padded, and 'a10' sorts before 'a2' —
                # same convention as channel_names' plane iteration
                for v in node.values():
                    collect(v, out)

        points: list = []
        collect(level.get("uLoopPars"), points)
        return points if n_xy and len(points) == n_xy else None

    def channel_names(self) -> "list[str] | None":
        """Component names from ``ImageMetadataSeqLV|0!``'s
        ``SLxPictureMetadata.sPicturePlanes`` plane descriptions
        (``sDescription`` per plane compound, key order = component
        order) — or None when absent or disagreeing with the component
        count.  Names are a courtesy: any parse problem degrades to the
        ``C00``… fallback."""
        import struct

        from tmlibrary_tpu.errors import MetadataError

        off = self._chunks.get(b"ImageMetadataSeqLV|0!")
        if off is None:
            return None
        try:
            tree = self._parse_lv(self._chunk_payload(off))
        except (MetadataError, struct.error, OverflowError, IndexError,
                UnicodeDecodeError):
            return None

        def find(node, key):
            if isinstance(node, dict):
                if key in node and isinstance(node[key], dict):
                    return node[key]
                for v in node.values():
                    found = find(v, key)
                    if found is not None:
                        return found
            return None

        planes = find(tree, "sPicturePlanes")
        if planes is None:
            return None
        # insertion order IS component order (_parse_lv preserves the
        # document order); sorting keys would put "a10" before "a2" and
        # silently mislabel every channel past the ninth
        names = [
            str(v["sDescription"])
            for v in planes.values()
            if isinstance(v, dict) and isinstance(v.get("sDescription"), str)
        ]
        if len(names) != self.n_components or not any(names):
            return None
        return names

    def seq_coords(self, sequence: int) -> tuple[int, int, int]:
        """(xy_position, zplane, tpoint) of a sequence index under
        :meth:`loop_shape`; flat ``(sequence, 0, 0)`` without loops."""
        loops = self.loop_shape()
        if not loops:
            return sequence, 0, 0
        coords = {"XY": 0, "Z": 0, "T": 0}
        rem = sequence
        for kind, size in reversed(loops):  # innermost varies fastest
            rem, coords[kind] = divmod(rem, size)
        return coords["XY"], coords["Z"], coords["T"]

    # ------------------------------------------------------------- pixels
    def read_plane(self, sequence: int, component: int = 0) -> np.ndarray:
        """One ``(height, width)`` uint16 plane: ``sequence`` selects the
        ``ImageDataSeq`` chunk (XY position), ``component`` the interleaved
        channel."""
        from tmlibrary_tpu.errors import MetadataError

        if not 0 <= component < self.n_components:
            raise MetadataError(
                f"component {component} out of range 0..{self.n_components - 1}"
            )
        name = b"ImageDataSeq|%d!" % sequence
        off = self._chunks.get(name)
        if off is None:
            raise MetadataError(
                f"{self.filename}: no sequence {sequence} "
                f"(have {self.n_sequences})"
            )
        import struct

        try:
            payload = self._chunk_payload(off)
        except (struct.error, OverflowError) as exc:
            # a chunk-map offset near EOF surfaces here at READ time; the
            # skip-on-MetadataError contract must hold on this path too
            raise MetadataError(
                f"{self.filename}: corrupt sequence chunk {sequence}: {exc}"
            ) from exc
        n_px = self.height * self.width * self.n_components
        if getattr(self, "_lossless", False):
            import zlib

            try:
                # max_length bounds the expansion: a crafted chunk must
                # fail the size check below, not OOM the ingest job.
                # Requested one byte PAST the expectation so an oversized
                # stream is detectable — it means mis-modeled geometry or
                # component count, and truncating it would hand back
                # plausible-looking wrong pixels (DESIGN.md 9e: overflow
                # and shortfall are both MetadataError)
                decoded = zlib.decompressobj().decompress(
                    payload[8:], 2 * n_px + 1)
            except zlib.error as exc:
                raise MetadataError(
                    f"{self.filename}: corrupt lossless sequence "
                    f"{sequence}: {exc}"
                ) from exc
            if len(decoded) != 2 * n_px:
                raise MetadataError(
                    f"{self.filename}: lossless sequence {sequence} "
                    f"decodes to {'>' if len(decoded) > 2 * n_px else ''}"
                    f"{len(decoded)} bytes, expected {2 * n_px}"
                )
            samples = np.frombuffer(decoded, np.uint16, count=n_px)
            plane = samples.reshape(self.height, self.width,
                                    self.n_components)
            return np.ascontiguousarray(plane[:, :, component])
        expect = 8 + 2 * n_px  # f64 timestamp + uint16 samples
        if len(payload) < expect:
            raise MetadataError(
                f"{self.filename}: sequence {sequence} holds "
                f"{len(payload)} bytes, expected {expect}"
            )
        samples = np.frombuffer(payload, np.uint16, count=n_px, offset=8)
        plane = samples.reshape(self.height, self.width, self.n_components)
        return np.ascontiguousarray(plane[:, :, component])

    def timestamp(self, sequence: int) -> float:
        """Acquisition timestamp (ms since experiment start) of a sequence."""
        import struct

        from tmlibrary_tpu.errors import MetadataError

        off = self._chunks.get(b"ImageDataSeq|%d!" % sequence)
        if off is None:
            raise MetadataError(
                f"{self.filename}: no sequence {sequence} "
                f"(have {self.n_sequences})"
            )
        try:
            return struct.unpack_from("<d", self._chunk_payload(off), 0)[0]
        except (struct.error, OverflowError) as exc:
            raise MetadataError(
                f"{self.filename}: corrupt sequence chunk {sequence}: {exc}"
            ) from exc


def _czi_zstd_plane(raw: bytes, h: int, w: int, zstd1: bool,
                    filename, itemsize: int = 2) -> np.ndarray:
    """Decode a zstd-compressed Gray8/Gray16 CZI subblock payload.

    ``zstd0`` (compression id 5) is a bare zstd frame.  ``zstd1``
    (id 6, the modern ZEN default) prefixes a small header — byte 0 is
    the header size including itself, followed by (field-id, value)
    byte pairs — whose field 1 is the hi-lo-byte-packing flag: when
    set, the UNCOMPRESSED stream stores all low bytes then all high
    bytes (libCZI's hiLoByteUnpackPreprocessing) and must be
    re-interleaved.  Layout per the public libCZI zstd conventions.
    """
    from tmlibrary_tpu.errors import MetadataError

    try:
        import zstandard
    except ImportError as exc:  # keep the skip-on-MetadataError contract
        raise MetadataError(
            f"zstd-compressed subblock in {filename} but the zstandard "
            "codec is not installed"
        ) from exc

    expect = itemsize * h * w
    hilo = False
    if zstd1:
        if not raw or raw[0] < 1 or raw[0] > len(raw):
            raise MetadataError(f"corrupt zstd1 subblock header in {filename}")
        fields = raw[1:raw[0]]
        for i in range(0, len(fields) - 1, 2):
            if fields[i] == 1:
                hilo = bool(fields[i + 1])
        raw = raw[raw[0]:]
    try:
        # max_output_size only caps frames WITHOUT an embedded content
        # size — a few-KB frame declaring multi-GB would be allocated in
        # full before the length check, OOM-killing the ingest worker.
        # Reject a wrong declared size up front (-1 = not declared).
        declared = zstandard.frame_content_size(raw)
        if declared not in (-1, expect):
            raise MetadataError(
                f"zstd subblock in {filename} declares {declared} bytes, "
                f"expected {expect}"
            )
        out = zstandard.ZstdDecompressor().decompress(
            raw, max_output_size=expect
        )
    except zstandard.ZstdError as exc:
        raise MetadataError(
            f"corrupt zstd subblock in {filename}: {exc}"
        ) from exc
    if len(out) != expect:
        raise MetadataError(
            f"zstd subblock in {filename} decodes to {len(out)} bytes, "
            f"expected {expect}"
        )
    if hilo:
        if itemsize != 2:
            raise MetadataError(
                f"zstd1 hi-lo packing on a non-16-bit subblock in "
                f"{filename}"
            )
        half = expect // 2
        lo = np.frombuffer(out, np.uint8, count=half)
        hi = np.frombuffer(out, np.uint8, count=half, offset=half)
        return (
            lo.astype(np.uint16) | (hi.astype(np.uint16) << 8)
        ).reshape(h, w)
    dtype = np.uint8 if itemsize == 1 else np.dtype("<u2")
    return np.frombuffer(out, dtype).reshape(h, w).copy()


class CZIReader(Reader):
    """First-party reader for Zeiss ``.czi`` containers (ZISRAW layout).

    Second entry in the Bio-Formats-gap program (after
    :class:`ND2Reader`): covers the common high-content layout — scene
    (S) × channel (C) × z (Z) × time (T) Gray16 subblocks
    (uncompressed or zstd).

    Container structure parsed here:

    - the file is a sequence of segments, each with a 32-byte header:
      16-byte ASCII id (null-padded), ``<i64 allocated_size>``
      ``<i64 used_size>``, then the payload;
    - ``ZISRAWFILE`` (at offset 0) holds the directory position at payload
      offset 36 (``major, minor, reserved×2, guid×2, file_part`` precede);
    - ``ZISRAWDIRECTORY`` lists ``DirectoryEntryDV`` records: pixel type,
      file position, compression, and per-dimension
      ``(name, start, size, …)`` entries (X/Y/C/Z/T/S/M);
    - ``ZISRAWSUBBLOCK`` holds ``metadata_size, attachment_size,
      data_size`` + its own directory entry; pixel data starts at payload
      offset ``max(256, 16 + entry_size) + metadata_size``.

    Gray8/Gray16 planes decode uncompressed, zstd-compressed
    (zstd0/zstd1 with hi-lo byte packing — the modern ZEN default, see
    :func:`_czi_zstd_plane`), or JPEG-compressed (the legacy lossy
    option, via cv2); mosaic tiles (M dimension, slide scans) read per
    tile with pyramid copies skipped; JPEG-XR-compressed or float files
    raise :class:`~tmlibrary_tpu.errors.MetadataError` with a clear
    message (see docs/FORMATS.md for the JPEG-XR rationale).
    """

    #: DirectoryEntryDV pixel types handled -> numpy dtype
    #: (0 = Gray8, 1 = Gray16 per the public ZISRAW enum)
    _PIXEL_DTYPES = {0: np.dtype(np.uint8), 1: np.dtype("<u2")}

    def __enter__(self):
        import mmap
        import struct

        from tmlibrary_tpu.errors import MetadataError

        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            raise MetadataError(f"not a CZI container: {self.filename}") from exc
        if len(self._data) < 64 or self._data[0:10] != b"ZISRAWFILE":
            self.__exit__()
            raise MetadataError(f"not a CZI container: {self.filename}")
        try:
            payload = self._segment_payload(0, b"ZISRAWFILE")
            # FileHeaderSegment: major(4) minor(4) reserved(4+4)
            # primary_guid(16) file_guid(16) file_part(4) = 52 bytes,
            # then DirectoryPosition(i64)
            (dir_pos,) = struct.unpack_from("<q", payload, 52)
            # MetadataPosition follows DirectoryPosition; 0/absent = none
            (meta_pos,) = (
                struct.unpack_from("<q", payload, 60)
                if len(payload) >= 68 else (0,)
            )
            self.channel_names = self._channel_names_from_xml(meta_pos)
            all_planes = self._parse_directory(dir_pos)
            # pyramidal files interleave subsampled copies with the
            # acquisition planes; only pyramid-0 subblocks are data
            self._planes = [p for p in all_planes if not p["pyramid"]]
            if not self._planes:
                raise MetadataError(
                    f"{self.filename}: only pyramid subblocks present"
                )
            # every plane needs X/Y dims NOW: a corrupt entry without
            # them would KeyError at read time, past the skip-unreadable
            # guard (fuzz-caught)
            for p in self._planes:
                if "w" not in p or "h" not in p or p["w"] <= 0 or p["h"] <= 0:
                    raise MetadataError(
                        f"{self.filename}: subblock entry without valid "
                        "X/Y dimensions"
                    )
            # raw dimension starts need not be 0-based (substack
            # acquisitions): normalize EVERY axis through sorted id lists
            self._scene_ids = sorted({p["S"] for p in self._planes})
            self._channel_ids = sorted({p["C"] for p in self._planes})
            self._z_ids = sorted({p["Z"] for p in self._planes})
            self._t_ids = sorted({p["T"] for p in self._planes})
            # mosaic tiles rank PER SCENE: ZEN commonly numbers M
            # globally across scenes (scene 0: 0..5, scene 1: 6..11), so
            # a global id list would leave most (scene, tile) pairs empty
            tiles_by_scene: dict = {}
            for p in self._planes:
                tiles_by_scene.setdefault(p["S"], set()).add(p["M"])
            tile_counts = {len(v) for v in tiles_by_scene.values()}
            if len(tile_counts) != 1:
                raise MetadataError(
                    f"{self.filename}: scenes carry differing mosaic "
                    f"tile counts {sorted(len(v) for v in tiles_by_scene.values())}"
                )
            self.n_tiles = tile_counts.pop()
            tile_rank = {
                (s, m): i
                for s, ms in tiles_by_scene.items()
                for i, m in enumerate(sorted(ms))
            }
            # O(1) lookups: a linear scan per plane would be O(planes^2)
            # over a production-scale subblock directory
            self._plane_index = {
                (p["S"], tile_rank[(p["S"], p["M"])],
                 p["C"], p["Z"], p["T"]): p
                for p in self._planes
            }
            # per-(scene, tile) mosaic pixel origin (first plane wins;
            # c/z/t share the tile's frame) — adjacency for slide scans
            self._tile_origins: dict = {}
            for p in self._planes:
                key = (p["S"], tile_rank[(p["S"], p["M"])])
                self._tile_origins.setdefault(
                    key, (p.get("y0", 0), p.get("x0", 0))
                )
            # a sparse or duplicated (scene, tile, c, z, t) grid would
            # fail mid-extract with half the sites written; fail the OPEN
            # instead so the handler skips the file with a logged reason
            expected = (
                len(self._scene_ids) * self.n_tiles
                * len(self._channel_ids) * len(self._z_ids)
                * len(self._t_ids)
            )
            if len(self._plane_index) != len(self._planes):
                raise MetadataError(
                    f"{self.filename}: duplicate subblocks for one "
                    "(scene, tile, channel, z, t) coordinate"
                )
            if len(self._planes) != expected:
                raise MetadataError(
                    f"{self.filename}: sparse subblock grid "
                    f"({len(self._planes)} planes for {expected} "
                    "coordinates)"
                )
            self.width = self._planes[0]["w"]
            self.height = self._planes[0]["h"]
        except MetadataError:
            self.__exit__()
            raise
        except (struct.error, OverflowError, IndexError, KeyError,
                ValueError) as exc:
            self.__exit__()
            raise MetadataError(
                f"corrupt CZI container {self.filename}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        self.n_scenes = len(self._scene_ids)
        self.n_channels = len(self._channel_ids)
        self.n_zplanes = len(self._z_ids)
        self.n_tpoints = len(self._t_ids)
        if self.channel_names is not None and len(self.channel_names) != (
            self.n_channels
        ):
            # a substack/split export keeps the full acquisition's XML
            # channel list: labeling rank c with names[c] would silently
            # mislabel scientific data — degrade to C00… instead
            self.channel_names = None
        return self

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            try:
                self._data.close()
            except (ValueError, AttributeError):
                pass
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    # ------------------------------------------------------------ container
    def _segment_payload(self, offset: int, expect: bytes) -> bytes:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        sid = bytes(self._data[offset:offset + 16]).rstrip(b"\x00")
        if sid != expect:
            raise MetadataError(
                f"{self.filename}: expected {expect.decode()} segment at "
                f"{offset}, found {sid!r}"
            )
        _alloc, used = struct.unpack_from("<qq", self._data, offset + 16)
        return bytes(self._data[offset + 32:offset + 32 + used])

    @staticmethod
    def _parse_entry(buf: bytes, pos: int) -> tuple[dict, int]:
        """One DirectoryEntryDV at ``pos`` → (plane dict, end pos)."""
        import struct

        from tmlibrary_tpu.errors import MetadataError

        if buf[pos:pos + 2] != b"DV":
            raise MetadataError("directory entry is not DV-typed")
        pixel_type, file_pos, _file_part, compression = struct.unpack_from(
            "<iqii", buf, pos + 2
        )
        (dim_count,) = struct.unpack_from("<i", buf, pos + 28)
        plane = {
            "pixel_type": pixel_type,
            "compression": compression,
            "file_pos": file_pos,
            # pyramid byte follows compression: non-zero marks a
            # subsampled copy of tiles, not an acquisition plane
            "pyramid": buf[pos + 22] != 0,
            "C": 0, "Z": 0, "T": 0, "S": 0, "M": 0,
        }
        p = pos + 32
        for _ in range(dim_count):
            name = buf[p:p + 4].rstrip(b"\x00").decode("ascii", "replace")
            start, size = struct.unpack_from("<ii", buf, p + 4)
            if name == "X":
                # start = the tile's pixel origin in the mosaic frame —
                # the adjacency information the spatial layout needs
                plane["w"] = size
                plane["x0"] = start
            elif name == "Y":
                plane["h"] = size
                plane["y0"] = start
            elif name in ("C", "Z", "T", "S", "M"):
                # M = mosaic tile index (slide scans / large areas): each
                # tile is exposed as its own plane, tiles -> sites
                plane[name] = start
            p += 20
        return plane, p

    def _channel_names_from_xml(self, meta_pos: int) -> "list[str] | None":
        """Channel names from the ZISRAWMETADATA document
        (``Information/Image/Dimensions/Channels/Channel`` ``Name``
        attributes, in element order = C index order), or None — names
        are a courtesy, so ANY parse problem degrades to the ``C00``
        fallback rather than failing the open."""
        import struct

        if meta_pos <= 0:
            return None
        try:
            payload = self._segment_payload(meta_pos, b"ZISRAWMETADATA")
            # MetadataSegment data: xml_size(i32) attachment_size(i32)
            # + 248 spare bytes, then the XML document
            (xml_size,) = struct.unpack_from("<i", payload, 0)
            # bytes, not a decoded str: an XML encoding declaration makes
            # fromstring(str) raise and would silently drop valid names
            root = ElementTree.fromstring(bytes(payload[256:256 + xml_size]))
        except Exception:
            return None

        def child(node, local):
            for el in node:
                if el.tag.rsplit("}", 1)[-1] == local:
                    return el
            return None

        # the EXPLICIT Information/Image/Dimensions/Channels path: ZEN
        # documents carry other Channels lists (DisplaySetting,
        # acquisition blocks) that can precede it in document order
        node = root
        if node.tag.rsplit("}", 1)[-1] != "Metadata":
            meta = child(node, "Metadata")
            node = node if meta is None else meta  # Element truthiness trap
        for local in ("Information", "Image", "Dimensions", "Channels"):
            node = child(node, local)
            if node is None:
                return None
        names = [
            ch.get("Name") or ""
            for ch in node
            if ch.tag.rsplit("}", 1)[-1] == "Channel"
        ]
        return names if any(names) else None

    def _parse_directory(self, dir_pos: int) -> list[dict]:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        payload = self._segment_payload(dir_pos, b"ZISRAWDIRECTORY")
        (count,) = struct.unpack_from("<i", payload, 0)
        pos = 128  # 4-byte count + 124 reserved
        planes = []
        for _ in range(count):
            plane, pos = self._parse_entry(payload, pos)
            planes.append(plane)
        if not planes:
            raise MetadataError(f"{self.filename}: empty subblock directory")
        return planes

    # ------------------------------------------------------------- pixels
    def read_plane(
        self, scene: int = 0, channel: int = 0, zplane: int = 0,
        tpoint: int = 0, tile: int = 0
    ) -> np.ndarray:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        for name, idx, n in (
            ("scene", scene, self.n_scenes),
            ("tile", tile, self.n_tiles),
            ("channel", channel, self.n_channels),
            ("zplane", zplane, self.n_zplanes),
            ("tpoint", tpoint, self.n_tpoints),
        ):
            if not 0 <= idx < n:
                # a negative index would silently WRAP through the sorted
                # id lists; match the sibling readers' MetadataError contract
                raise MetadataError(
                    f"{self.filename}: {name} {idx} out of range 0..{n - 1}"
                )
        plane = self._plane_index.get((
            self._scene_ids[scene],
            tile,  # already a per-scene rank (see __enter__)
            self._channel_ids[channel],
            self._z_ids[zplane],
            self._t_ids[tpoint],
        ))
        if plane is None:
            raise MetadataError(
                f"{self.filename}: no subblock for "
                f"scene={scene} tile={tile} channel={channel} "
                f"z={zplane} t={tpoint}"
            )
        compression = plane["compression"]
        if compression not in (0, 1, 5, 6):
            # 4 = JPEG-XR: no conformant decoder buildable here (see
            # docs/FORMATS.md); 1 = JPEG decoded via cv2 below;
            # 5/6 = zstd0/zstd1, the modern ZEN default
            raise MetadataError(
                f"{self.filename}: compressed CZI subblocks "
                f"(compression={compression}) are not supported "
                "(zstd0/zstd1 and JPEG are; JPEG-XR is not)"
            )
        dtype = self._PIXEL_DTYPES.get(plane["pixel_type"])
        if dtype is None:
            raise MetadataError(
                f"{self.filename}: only Gray8/Gray16 subblocks are "
                f"supported (pixel_type={plane['pixel_type']})"
            )
        payload_off = plane["file_pos"] + 32
        sid = bytes(self._data[plane["file_pos"]:plane["file_pos"] + 16])
        if sid.rstrip(b"\x00") != b"ZISRAWSUBBLOCK":
            raise MetadataError(
                f"{self.filename}: directory points at a non-subblock segment"
            )
        try:
            meta_size, _att_size, data_size = struct.unpack_from(
                "<iiq", self._data, payload_off
            )
            # the DV entry embedded in the subblock mirrors the directory's;
            # data starts after max(256, 16 + entry bytes) + metadata
            entry_buf = bytes(
                self._data[payload_off + 16:payload_off + 16 + 32 + 20 * 16]
            )
            _, entry_end = self._parse_entry(entry_buf, 0)
        except (struct.error, OverflowError, IndexError) as exc:
            # truncation inside the subblock header surfaces at READ
            # time; the skip-on-MetadataError contract must hold here too
            raise MetadataError(
                f"{self.filename}: corrupt subblock at "
                f"{plane['file_pos']}: {exc}"
            ) from exc
        data_off = payload_off + max(256, 16 + entry_end) + meta_size
        h, w = plane["h"], plane["w"]
        expect = dtype.itemsize * h * w
        if compression != 0:
            if data_size <= 0 or data_off + data_size > len(self._data):
                raise MetadataError(
                    f"{self.filename}: compressed subblock claims "
                    f"{data_size} bytes, {len(self._data) - data_off} in file"
                )
            raw = bytes(self._data[data_off:data_off + data_size])
            if compression == 1:
                return self._jpeg_plane(raw, h, w, dtype)
            return _czi_zstd_plane(
                raw, h, w, compression == 6, self.filename,
                itemsize=dtype.itemsize,
            )
        if data_size < expect or data_off + expect > len(self._data):
            # data_size is the writer's CLAIM; a truncated file can keep an
            # intact directory while the pixels run past EOF
            raise MetadataError(
                f"{self.filename}: subblock holds {data_size} bytes "
                f"({len(self._data) - data_off} in file), expected {expect}"
            )
        samples = np.frombuffer(
            self._data, dtype, count=h * w, offset=data_off
        )
        return samples.reshape(h, w).copy()

    def _jpeg_plane(self, raw: bytes, h: int, w: int, dtype) -> np.ndarray:
        """JPEG (compression=1) subblock via cv2 — the legacy ZEN lossy
        option.  Grayscale only; a decode failure or geometry mismatch
        keeps the skip-on-MetadataError contract."""
        import cv2

        from tmlibrary_tpu.errors import MetadataError

        try:
            # cv2 returns None for most bad input but RAISES for e.g. a
            # SOF declaring CV_IO_MAX_IMAGE_PIXELS-busting dimensions —
            # both must land in the skip-on-MetadataError contract
            img = cv2.imdecode(
                np.frombuffer(raw, np.uint8), cv2.IMREAD_UNCHANGED
            )
        except cv2.error as exc:
            raise MetadataError(
                f"{self.filename}: corrupt JPEG subblock: {exc}"
            ) from exc
        if img is None:
            raise MetadataError(
                f"{self.filename}: corrupt JPEG subblock"
            )
        if img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2GRAY)
        if img.shape != (h, w):
            raise MetadataError(
                f"{self.filename}: JPEG subblock decodes to {img.shape}, "
                f"directory says {(h, w)}"
            )
        return np.asarray(img, dtype)

    def tile_origin(self, scene: int, tile: int) -> tuple[int, int]:
        """(y0, x0) mosaic pixel origin of a tile (0-based per-scene
        rank), for grid derivation; (0, 0) when the directory carried no
        origins."""
        from tmlibrary_tpu.errors import MetadataError

        if not (0 <= scene < self.n_scenes and 0 <= tile < self.n_tiles):
            # same contract as read_plane: a negative index must not
            # silently wrap through the sorted id lists
            raise MetadataError(
                f"{self.filename}: tile origin ({scene}, {tile}) out of "
                f"range ({self.n_scenes} scenes, {self.n_tiles} tiles)"
            )
        return self._tile_origins.get(
            (self._scene_ids[scene], tile), (0, 0)
        )

    def read_plane_linear(self, page: int) -> np.ndarray:
        """Decode by linear page index, the encoding the czi metaconfig
        handler writes: ``(((s * M + m) * C + c) * Z + z) * T + t``
        (sites = scenes × mosaic tiles; M = 1 reduces to the pre-mosaic
        convention)."""
        per_site = self.n_channels * self.n_zplanes * self.n_tpoints
        sm, rem = divmod(page, per_site)
        s, m = divmod(sm, self.n_tiles)
        c, rem = divmod(rem, self.n_zplanes * self.n_tpoints)
        z, t = divmod(rem, self.n_tpoints)
        return self.read_plane(s, c, z, t, tile=m)


class LIFReader(Reader):
    """First-party reader for Leica Image Files (``.lif``).

    Third entry in the Bio-Formats-gap program (ND2, CZI, LIF): covers
    uint16/uint8 grayscale image series — the high-content layout where
    each series is one field/site with C/Z/T planes.

    Container structure parsed here:

    - the file is a sequence of blocks, each ``<u32 0x70> <u32 len>``
      followed by a test byte ``0x2A``;
    - the FIRST block holds the XML header: ``<u8 0x2A> <u32 n_chars>``
      + UTF-16LE document (``LMSDataContainerHeader``, whose ``Version``
      selects 4- vs 8-byte memory sizes);
    - every following block is a memory block: ``<u8 0x2A> <u32|u64
      mem_size> <u8 0x2A> <u32 id_chars>`` + UTF-16LE block id + the raw
      pixel bytes;
    - the XML's ``Element/Data/Image/ImageDescription`` carries
      ``ChannelDescription`` (``Resolution`` bits, ``BytesInc``) and
      ``DimensionDescription`` (``DimID`` 1=X 2=Y 3=Z 4=T,
      ``NumberOfElements``, ``BytesInc``) entries, and the sibling
      ``Memory`` element names the block holding the series' pixels.

    Plane addressing is pure ``BytesInc`` arithmetic, so interleaved and
    planar channel layouts both decode.  Non-8/16-bit resolutions raise
    :class:`~tmlibrary_tpu.errors.MetadataError`.
    """

    MAGIC = 0x70

    def __enter__(self):
        import mmap
        import struct

        from tmlibrary_tpu.errors import MetadataError

        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            raise MetadataError(f"not a LIF container: {self.filename}") from exc
        try:
            if len(self._data) < 13 or struct.unpack_from("<I", self._data, 0)[0] != self.MAGIC:
                raise MetadataError(f"not a LIF container: {self.filename}")
            xml, pos = self._read_header()
            from xml.etree import ElementTree as ET

            root = ET.fromstring(xml)
            version = int(root.get("Version") or 1)
            self._blocks = self._scan_memory_blocks(pos, version)
            self.series = self._parse_xml(root)
        except MetadataError:
            self.__exit__()
            raise
        except (struct.error, OverflowError, IndexError, KeyError,
                ValueError, UnicodeDecodeError, SyntaxError) as exc:
            # SyntaxError: a truncated UTF-16 header decodes to malformed
            # XML and ElementTree.ParseError subclasses SyntaxError
            self.__exit__()
            raise MetadataError(
                f"corrupt LIF container {self.filename}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        if not self.series:
            self.__exit__()
            raise MetadataError(
                f"{self.filename}: no decodable image series "
                "(only 8/16-bit grayscale series are supported)"
            )
        self.n_series = len(self.series)
        self.height = self.series[0]["height"]
        self.width = self.series[0]["width"]
        return self

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            try:
                self._data.close()
            except (ValueError, AttributeError):
                pass
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    # ------------------------------------------------------------ container
    def _read_header(self) -> tuple[str, int]:
        import struct

        from tmlibrary_tpu.errors import MetadataError

        _magic, _blen = struct.unpack_from("<II", self._data, 0)
        if self._data[8] != 0x2A:
            raise MetadataError(f"{self.filename}: bad header test byte")
        (n_chars,) = struct.unpack_from("<I", self._data, 9)
        xml = bytes(self._data[13:13 + 2 * n_chars]).decode("utf-16-le")
        return xml, 13 + 2 * n_chars

    def _scan_memory_blocks(
        self, pos: int, version: int
    ) -> dict[str, tuple[int, int]]:
        """block id -> (data offset, size).  ``version`` comes from the
        parsed header root (it selects 4- vs 8-byte memory sizes; a
        substring sniff would misread files whose Version attribute sits
        past the first decode window)."""
        import struct

        from tmlibrary_tpu.errors import MetadataError

        blocks: dict[str, tuple[int, int]] = {}
        n = len(self._data)
        while pos + 8 <= n:
            magic, _blen = struct.unpack_from("<II", self._data, pos)
            if magic != self.MAGIC:
                raise MetadataError(
                    f"{self.filename}: bad block magic at offset {pos}"
                )
            p = pos + 8
            if self._data[p] != 0x2A:
                raise MetadataError(f"{self.filename}: bad block test byte")
            if version >= 2:
                (mem_size,) = struct.unpack_from("<Q", self._data, p + 1)
                p += 9
            else:
                (mem_size,) = struct.unpack_from("<I", self._data, p + 1)
                p += 5
            if self._data[p] != 0x2A:
                raise MetadataError(f"{self.filename}: bad id test byte")
            (id_chars,) = struct.unpack_from("<I", self._data, p + 1)
            p += 5
            block_id = bytes(self._data[p:p + 2 * id_chars]).decode("utf-16-le")
            p += 2 * id_chars
            if p + mem_size > n:
                raise MetadataError(
                    f"{self.filename}: memory block '{block_id}' runs past "
                    f"EOF (truncated file?)"
                )
            if mem_size:
                blocks[block_id] = (p, mem_size)
            pos = p + mem_size
        return blocks

    def _parse_xml(self, root) -> list[dict]:
        series: list[dict] = []
        for el in root.iter("Element"):
            image = el.find("./Data/Image")
            memory = el.find("./Memory")
            if image is None or memory is None:
                continue
            desc = image.find("ImageDescription")
            if desc is None:
                continue
            channels = [
                {
                    "bits": int(c.get("Resolution", "16")),
                    "bytes_inc": int(c.get("BytesInc", "0")),
                    # LUTName is how Leica labels acquisition channels
                    # (Bio-Formats surfaces the same attribute)
                    "name": c.get("LUTName") or "",
                }
                for c in desc.iter("ChannelDescription")
            ]
            dims = {1: None, 2: None, 3: None, 4: None}
            for d in desc.iter("DimensionDescription"):
                dim_id = int(d.get("DimID", "0"))
                if dim_id in dims:
                    dims[dim_id] = {
                        "n": int(d.get("NumberOfElements", "1")),
                        "bytes_inc": int(d.get("BytesInc", "0")),
                    }
            if not channels or dims[1] is None or dims[2] is None:
                continue
            if any(c["bits"] not in (8, 16) for c in channels):
                continue  # counted as undecodable; __enter__ errors if none
            if dims[1]["bytes_inc"] <= 0 or dims[2]["bytes_inc"] <= 0:
                # a zero X/Y stride would reach as_strided and replicate
                # one pixel silently instead of erroring
                continue
            block_id = memory.get("MemoryBlockID", "")
            if block_id not in self._blocks:
                continue
            series.append({
                "name": el.get("Name", f"Series{len(series)}"),
                "channels": channels,
                "width": dims[1]["n"],
                "x_inc": dims[1]["bytes_inc"],
                "height": dims[2]["n"],
                "y_inc": dims[2]["bytes_inc"],
                "n_zplanes": dims[3]["n"] if dims[3] else 1,
                "z_inc": dims[3]["bytes_inc"] if dims[3] else 0,
                "n_tpoints": dims[4]["n"] if dims[4] else 1,
                "t_inc": dims[4]["bytes_inc"] if dims[4] else 0,
                "block": block_id,
            })
        return series

    # ------------------------------------------------------------- pixels
    def read_plane(
        self, series: int = 0, channel: int = 0, zplane: int = 0, tpoint: int = 0
    ) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        if not 0 <= series < len(self.series):
            raise MetadataError(
                f"{self.filename}: no series {series} (have {len(self.series)})"
            )
        s = self.series[series]
        if not 0 <= channel < len(s["channels"]):
            raise MetadataError(
                f"{self.filename}: series {series} has "
                f"{len(s['channels'])} channels, asked for {channel}"
            )
        if not 0 <= zplane < s["n_zplanes"] or not 0 <= tpoint < s["n_tpoints"]:
            raise MetadataError(
                f"{self.filename}: plane z={zplane} t={tpoint} out of range "
                f"Z={s['n_zplanes']} T={s['n_tpoints']}"
            )
        ch = s["channels"][channel]
        itemsize = ch["bits"] // 8
        base, size = self._blocks[s["block"]]
        start = ch["bytes_inc"] + zplane * s["z_inc"] + tpoint * s["t_inc"]
        h, w = s["height"], s["width"]
        last = start + (h - 1) * s["y_inc"] + (w - 1) * s["x_inc"] + itemsize
        if last > size:
            raise MetadataError(
                f"{self.filename}: series {series} plane runs past its "
                f"memory block ({last} > {size} bytes)"
            )
        dtype = np.uint8 if itemsize == 1 else np.dtype("<u2")
        # copy the plane's byte span out of the mmap FIRST: a frombuffer
        # view would pin the mapping open past __exit__ (BufferError)
        span = bytes(self._data[base + start:base + last])
        plane = np.lib.stride_tricks.as_strided(
            np.frombuffer(span, np.uint8),
            shape=(h, w, itemsize),
            strides=(s["y_inc"], s["x_inc"], 1),
        )
        out = np.ascontiguousarray(plane).view(dtype)[:, :, 0]
        return out.astype(np.uint16) if itemsize == 1 else out

    def read_plane_linear(self, series: int, page: int) -> np.ndarray:
        """Decode by per-series linear page index, the encoding the lif
        metaconfig handler writes: ``(c * Z + z) * T + t``."""
        s = self.series[series]
        c, rem = divmod(page, s["n_zplanes"] * s["n_tpoints"])
        z, t = divmod(rem, s["n_tpoints"])
        return self.read_plane(series, c, z, t)

    def channel_names(self) -> "list[str] | None":
        """Per-channel ``LUTName`` labels when every series agrees — or
        None (names are a courtesy; the ``C00``… fallback applies)."""
        if not self.series:
            return None
        first = [c.get("name", "") for c in self.series[0]["channels"]]
        for s in self.series[1:]:
            if [c.get("name", "") for c in s["channels"]] != first:
                return None
        return first if any(first) else None

    def uniform_dims(self) -> tuple[int, int, int]:
        """(C, Z, T), required identical across series — as is the plane
        shape (the HCS layout the lif handler maps: series = sites of one
        well; a mixed-size file, e.g. an overview scan plus field series,
        must not silently set the experiment's site shape)."""
        from tmlibrary_tpu.errors import MetadataError

        dims = {
            (len(s["channels"]), s["n_zplanes"], s["n_tpoints"])
            for s in self.series
        }
        if len(dims) != 1:
            raise MetadataError(
                f"{self.filename}: series disagree on (C, Z, T) {sorted(dims)} "
                "— not a uniform HCS acquisition"
            )
        shapes = {(s["height"], s["width"]) for s in self.series}
        if len(shapes) != 1:
            raise MetadataError(
                f"{self.filename}: series disagree on plane shape "
                f"{sorted(shapes)} — not a uniform HCS acquisition"
            )
        return next(iter(dims))

    def read_plane_global(self, page: int) -> np.ndarray:
        """Decode by whole-file linear page index
        ``series * C*Z*T + (c*Z + z)*T + t`` (uniform series required)."""
        c, z, t = self.uniform_dims()
        series, rem = divmod(page, c * z * t)
        return self.read_plane_linear(series, rem)


class DVReader(Reader):
    """First-party reader for DeltaVision ``.dv`` / ``.r3d`` stacks
    (the MRC-variant format of GE/Applied Precision widefield scopes).

    Fourth entry in the Bio-Formats-gap program (after ND2/CZI/LIF):
    a 1024-byte fixed header (image dims, pixel mode, extended-header
    size) followed by the extended header and row-major section planes.
    Byte order is detected from the DVID magic (``0xC0A0`` little- or
    big-endian at byte 96); sections interleave Z/wavelength/time in one
    of three documented orders (byte 182): 0 = ZTW, 1 = WZT, 2 = ZWT.

    Linear page convention (shared with the ``dv`` metaconfig handler):
    ``page = (c * Z + z) * T + t``.
    """

    #: pixel mode -> numpy dtype character (endianness applied at parse)
    _MODES = {0: "u1", 1: "i2", 2: "f4", 6: "u2"}

    def __enter__(self):
        import struct

        from tmlibrary_tpu.errors import MetadataError

        try:
            # header only — never the whole file: imextract's thread pool
            # opens one reader per plane, and multi-GB stacks would be
            # read N times over (see the ND2Reader mmap note)
            with open(self.filename, "rb") as f:
                header = f.read(1024)
        except OSError as exc:
            raise MetadataError(f"unreadable DV file: {self.filename}") from exc
        if len(header) < 1024:
            raise MetadataError(f"not a DV stack (short header): {self.filename}")
        (dvid_le,) = struct.unpack_from("<h", header, 96)
        (dvid_be,) = struct.unpack_from(">h", header, 96)
        if dvid_le == -16224:
            self._bo = "<"
        elif dvid_be == -16224:
            self._bo = ">"
        else:
            raise MetadataError(
                f"not a DV stack (no DVID magic at byte 96): {self.filename}"
            )
        bo = self._bo
        nx, ny, nsec, mode = struct.unpack_from(f"{bo}4i", header, 0)
        (ext_size,) = struct.unpack_from(f"{bo}i", header, 92)
        (n_times,) = struct.unpack_from(f"{bo}h", header, 180)
        (sequence,) = struct.unpack_from(f"{bo}h", header, 182)
        (n_waves,) = struct.unpack_from(f"{bo}h", header, 196)
        if mode not in self._MODES:
            raise MetadataError(
                f"unsupported DV pixel mode {mode} in {self.filename} "
                f"(supported: {sorted(self._MODES)})"
            )
        if sequence not in (0, 1, 2):
            raise MetadataError(
                f"unknown DV image sequence {sequence} in {self.filename}"
            )
        n_waves = max(1, n_waves)
        n_times = max(1, n_times)
        if nx <= 0 or ny <= 0 or nsec <= 0 or ext_size < 0:
            raise MetadataError(f"corrupt DV header in {self.filename}")
        if nsec % (n_waves * n_times) != 0:
            raise MetadataError(
                f"DV section count {nsec} does not factor into "
                f"{n_waves} waves x {n_times} times in {self.filename}"
            )
        self.width, self.height = nx, ny
        self.n_channels = n_waves
        self.n_tpoints = n_times
        self.n_zplanes = nsec // (n_waves * n_times)
        self._sequence = sequence
        self._dtype = np.dtype(bo + self._MODES[mode])
        self._data_start = 1024 + ext_size
        self._plane_bytes = nx * ny * self._dtype.itemsize
        expected = self._data_start + nsec * self._plane_bytes
        actual = self.filename.stat().st_size
        if actual < expected:
            raise MetadataError(
                f"truncated DV stack {self.filename}: "
                f"{actual} bytes < {expected} expected"
            )
        return self

    def _section(self, z: int, c: int, t: int) -> int:
        zn, wn = self.n_zplanes, self.n_channels
        if self._sequence == 0:  # ZTW: Z fastest, then time, then wave
            return (c * self.n_tpoints + t) * zn + z
        if self._sequence == 1:  # WZT: wave fastest, then Z, then time
            return (t * zn + z) * wn + c
        return (t * wn + c) * zn + z  # ZWT: Z fastest, then wave, then time

    def read_plane(self, z: int, c: int, t: int) -> np.ndarray:
        sec = self._section(z, c, t)
        off = self._data_start + sec * self._plane_bytes
        with open(self.filename, "rb") as f:
            f.seek(off)
            raw = f.read(self._plane_bytes)
        plane = np.frombuffer(raw, self._dtype).reshape(self.height, self.width)
        # store planes are uint16.  Signed int16 (mode 1, the most common
        # DV mode) can carry negative intensities after deconvolution —
        # clip at 0 rather than letting the cast wrap them to ~65535
        if plane.dtype.kind == "i":
            return np.clip(plane, 0, None).astype(np.uint16)
        if plane.dtype.kind == "u":
            return plane.astype(np.uint16)
        return plane.astype(np.float32)

    def read_plane_linear(self, page: int) -> np.ndarray:
        ct, rem_t = divmod(page, self.n_tpoints)
        c, z = divmod(ct, self.n_zplanes)
        return self.read_plane(z, c, rem_t)


class IMSReader(Reader):
    """First-party reader for Bitplane Imaris ``.ims`` files (HDF5-based;
    h5py is already a dependency, so "first-party" here means the Imaris
    layout conventions, not the container encoding).

    Fifth entry in the Bio-Formats-gap program: resolution level 0 lives
    at ``/DataSet/ResolutionLevel 0/TimePoint <t>/Channel <c>/Data`` as a
    (Z, Y, X) dataset, padded up to chunk multiples — the TRUE image size
    comes from ``/DataSetInfo/Image`` attributes ``X``/``Y``/``Z``, which
    Imaris stores as byte-character arrays (``[b'5', b'1', b'2']``).

    Linear page convention (shared with the ``ims`` metaconfig handler):
    ``page = (c * Z + z) * T + t``.
    """

    def __enter__(self):
        import h5py

        from tmlibrary_tpu.errors import MetadataError

        try:
            self._f = h5py.File(self.filename, "r")
        except OSError as exc:
            raise MetadataError(
                f"not an HDF5/Imaris file: {self.filename}: {exc}"
            ) from exc
        try:
            try:
                level0 = self._f["DataSet/ResolutionLevel 0"]
                info = self._f["DataSetInfo/Image"]
            except KeyError as exc:
                raise MetadataError(
                    f"no Imaris DataSet layout in {self.filename}: {exc}"
                ) from exc
            try:
                self.width = int(self._decode_attr(info.attrs["X"]))
                self.height = int(self._decode_attr(info.attrs["Y"]))
                self.n_zplanes = int(self._decode_attr(info.attrs["Z"]))
            except (KeyError, ValueError) as exc:
                raise MetadataError(
                    f"bad Imaris image-size attributes in "
                    f"{self.filename}: {exc}"
                ) from exc
            if self.width < 1 or self.height < 1 or self.n_zplanes < 1:
                # Z=0 would reach divmod(page, 0) in read_plane_linear;
                # non-positive X/Y would silently truncate every plane
                raise MetadataError(
                    f"nonsensical Imaris image size in {self.filename}: "
                    f"X={self.width} Y={self.height} Z={self.n_zplanes}"
                )
            tps = sorted(
                k for k in level0 if k.startswith("TimePoint ")
            )
            if not tps:
                raise MetadataError(f"no TimePoints in {self.filename}")
            chans = sorted(
                k for k in level0[tps[0]] if k.startswith("Channel ")
            )
            if not chans:
                raise MetadataError(f"no Channels in {self.filename}")
            self.n_tpoints = len(tps)
            self.n_channels = len(chans)
        except MetadataError:
            self.__exit__()
            raise
        except (RuntimeError, OSError, KeyError, ValueError, IndexError,
                TypeError) as exc:
            # h5py surfaces HDF5-library corruption as RuntimeError/OSError
            # mid-iteration (fuzz-caught); the skip-unreadable contract
            # requires MetadataError
            self.__exit__()
            raise MetadataError(
                f"corrupt Imaris file {self.filename}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        return self

    def __exit__(self, *exc):
        try:
            self._f.close()
        except Exception:
            pass
        return False

    @staticmethod
    def _decode_attr(val) -> str:
        """The ONE decoder for Imaris attribute values — stored as
        byte-character arrays (``[b'5', b'1', b'2']``), bytes, or plain
        scalars depending on the writer."""
        if isinstance(val, np.ndarray):
            return b"".join(val.astype("S1")).decode()
        if isinstance(val, bytes):
            return val.decode()
        return str(val)

    def channel_names(self) -> list[str] | None:
        """Names from ``/DataSetInfo/Channel <c>`` ``Name`` attributes,
        or None when absent."""
        names = []
        for c in range(self.n_channels):
            try:
                names.append(self._decode_attr(
                    self._f[f"DataSetInfo/Channel {c}"].attrs["Name"]
                ))
            except KeyError:
                return None
        return names

    def read_plane(self, z: int, c: int, t: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        path = f"DataSet/ResolutionLevel 0/TimePoint {t}/Channel {c}/Data"
        try:
            data = self._f[path]
            # crop chunk padding down to the true image size.  Imaris
            # Data may be uint32 (routine, unlike DV's 8/16-bit modes) —
            # clip to the store's uint16 range instead of silently
            # wrapping 70000 to 4464
            plane = np.asarray(data[z, : self.height, : self.width])
        except KeyError as exc:
            raise MetadataError(
                f"missing {path} in {self.filename}"
            ) from exc
        except (RuntimeError, OSError, ValueError, IndexError,
                TypeError) as exc:
            # HDF5-library corruption at dataset-read time (fuzz-caught)
            raise MetadataError(
                f"corrupt Imaris data in {self.filename}: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        if plane.dtype.kind in "iu":
            return np.clip(plane, 0, 65535).astype(np.uint16)
        return plane.astype(np.float32)

    def read_plane_linear(self, page: int) -> np.ndarray:
        ct, t = divmod(page, self.n_tpoints)
        c, z = divmod(ct, self.n_zplanes)
        return self.read_plane(z, c, t)


# --------------------------------------------------- TIFF-variant containers
#: TIFF value-type sizes (BYTE, ASCII, SHORT, LONG, RATIONAL, signed/float,
#: IFD, and the BigTIFF 8-byte types LONG8/SLONG8/IFD8)
_TIFF_TYPE_SIZE = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 6: 1, 7: 1, 8: 2, 9: 4,
                   10: 8, 11: 4, 12: 8, 13: 4, 16: 8, 17: 8, 18: 8}


def _tiff_parse(buf, spans: "list | None" = None) -> tuple[str, list[dict]]:
    """Minimal TIFF IFD walk over an in-memory buffer — classic (magic
    42) and BigTIFF (magic 43, 8-byte offsets/counts, 20-byte entries).

    Returns ``(byteorder, ifds)`` where each IFD is ``{tag: (type, count,
    value_data_offset)}``.  The value offset is RESOLVED at parse time
    (inline position when the value fits in the entry's 4/8-byte value
    field, else the dereferenced pointer), so downstream helpers are
    format-agnostic.  Shared by the STK/LSM/FLEX/Olympus container
    readers — their plane layouts don't fit the native page reader's
    model, so they need the raw tag table, not decoded pages.

    When ``spans`` is a list, the byte range of every IFD table walked
    (count field through next-IFD pointer) is appended to it — the
    parse-cache freshness key crcs exactly these ranges.
    """
    import struct

    from tmlibrary_tpu.errors import MetadataError

    bo = {b"II": "<", b"MM": ">"}.get(bytes(buf[0:2]))
    if bo is None or len(buf) < 8:
        raise MetadataError("not a TIFF (bad byte-order mark)")
    (magic,) = struct.unpack_from(bo + "H", buf, 2)
    if magic == 42:
        big = False
        (off,) = struct.unpack_from(bo + "I", buf, 4)
    elif magic == 43:
        if len(buf) < 16:
            raise MetadataError("truncated BigTIFF header")
        osize, zero = struct.unpack_from(bo + "HH", buf, 4)
        if osize != 8 or zero != 0:
            raise MetadataError(
                f"BigTIFF with unsupported offset size {osize}"
            )
        big = True
        (off,) = struct.unpack_from(bo + "Q", buf, 8)
    else:
        raise MetadataError(f"not a TIFF (magic {magic})")
    # per-format geometry: (IFD-count fmt, entry-count fmt, entry size,
    # value-field offset within an entry, inline capacity, offset fmt)
    nfmt, cfmt, esize, vfield, inline, off_fmt = (
        ("Q", "Q", 20, 12, 8, "Q") if big else ("H", "I", 12, 8, 4, "I")
    )
    csize = struct.calcsize(nfmt)
    ifds: list[dict] = []
    seen: set = set()
    while off and off not in seen and len(ifds) < 65535:
        seen.add(off)
        if off + csize > len(buf):
            break
        (n,) = struct.unpack_from(bo + nfmt, buf, off)
        p = off + csize
        nextsize = struct.calcsize(off_fmt)
        if n > (len(buf) - p) // esize or p + esize * n + nextsize > len(buf):
            break
        if spans is not None:
            spans.append((off, p + esize * n + nextsize))
        entries: dict = {}
        for _ in range(n):
            tag, typ = struct.unpack_from(bo + "HH", buf, p)
            (cnt,) = struct.unpack_from(bo + cfmt, buf, p + 4)
            total = _TIFF_TYPE_SIZE.get(typ, 1) * cnt
            if total <= inline:
                voff = p + vfield
            else:
                (voff,) = struct.unpack_from(bo + off_fmt, buf, p + vfield)
            entries[tag] = (typ, cnt, voff)
            p += esize
        ifds.append(entries)
        (off,) = struct.unpack_from(bo + off_fmt, buf, p)
    if not ifds:
        raise MetadataError("TIFF contains no parseable IFD")
    return bo, ifds


def _tiff_value_offset(bo: str, buf, entry) -> int:
    """Offset of an entry's value data (already resolved at parse time:
    inline when it fit the entry's value field, dereferenced otherwise)."""
    return entry[2]


def _tiff_ints(bo: str, buf, entry, limit: "int | None" = None) -> list[int]:
    """Integer values of a BYTE/SHORT/LONG/LONG8 entry."""
    import struct

    typ, cnt, _ = entry
    fmt = {1: "B", 3: "H", 4: "I", 16: "Q"}.get(typ)
    if fmt is None:
        return []
    if limit is not None:
        cnt = min(cnt, limit)
    base = _tiff_value_offset(bo, buf, entry)
    return list(struct.unpack_from(f"{bo}{cnt}{fmt}", buf, base))


def _tiff_int(bo: str, buf, ifd: dict, tag: int, default: int) -> int:
    entry = ifd.get(tag)
    if entry is None:
        return default
    vals = _tiff_ints(bo, buf, entry, limit=1)
    return vals[0] if vals else default


def _tiff_strips(bo: str, buf, ifd: dict, filename) -> tuple[list, list]:
    """StripOffsets/StripByteCounts of an IFD, as MetadataError on any
    structural problem (tiled TIFFs have neither tag; corrupt offsets make
    struct.unpack_from throw) — ingest must skip such files, not crash."""
    import struct

    from tmlibrary_tpu.errors import MetadataError

    try:
        offs = _tiff_ints(bo, buf, ifd[273])
        counts = _tiff_ints(bo, buf, ifd[279])
    except KeyError as exc:
        raise MetadataError(
            f"TIFF IFD without strip tags (tiled or corrupt): {filename}"
        ) from exc
    except struct.error as exc:
        raise MetadataError(f"corrupt TIFF tag data in {filename}") from exc
    if not offs or len(offs) != len(counts):
        raise MetadataError(f"corrupt TIFF strip layout in {filename}")
    return offs, counts


def _decode_strip(chunk: bytes, compression: int, expect: int,
                  filename) -> bytes:
    """One TIFF strip -> exactly ``expect`` decoded bytes."""
    from tmlibrary_tpu.errors import MetadataError, NotSupportedError

    if compression == 1:
        if len(chunk) < expect:
            raise MetadataError(f"truncated strip in {filename}")
        return chunk[:expect]
    if compression == 5:
        from tmlibrary_tpu.native import lzw_decode

        out = lzw_decode(chunk, expect)
    elif compression in (8, 32946):
        # Adobe deflate (8) and the old deflate id (32946): one zlib
        # stream per strip.  max_length bounds the expansion — a crafted
        # strip must fail the size check, not OOM the ingest job; one
        # byte PAST the expectation is requested so an oversized stream
        # (mis-modeled strip geometry) is rejected rather than silently
        # truncated into plausible pixels (DESIGN.md 9e)
        import zlib

        try:
            raw = zlib.decompressobj().decompress(chunk, expect + 1)
        except zlib.error:
            raw = None
        out = raw if raw is not None and len(raw) == expect else None
    elif compression == 32773:
        from tmlibrary_tpu.native import packbits_decode

        out = packbits_decode(chunk, expect)
    else:
        raise NotSupportedError(
            f"unsupported TIFF compression {compression} in {filename}"
        )
    if out is None:
        raise MetadataError(f"corrupt compressed strip in {filename}")
    return out


def _apply_predictor(plane: np.ndarray, predictor: int) -> np.ndarray:
    """TIFF predictor 2 (horizontal differencing): cumulative sum along
    rows with the sample width's natural wraparound."""
    if predictor == 2:
        return np.cumsum(plane.astype(np.uint32), axis=1).astype(plane.dtype)
    return plane


class STKReader(Reader):
    """First-party reader for MetaMorph ``.stk`` stacks.

    Sixth entry in the Bio-Formats-gap program (SURVEY.md §3 Readers
    row).  An STK file is a classic TIFF whose FIRST IFD describes plane
    0 while the remaining planes of the Z-series follow contiguously in
    the pixel data — the plane count lives in the UIC2 private tag's
    ``count`` field (tag 33629), NOT in the IFD chain, so a plain paged
    TIFF reader sees one page and silently drops the rest of the stack
    (exactly what the cv2 fallback used to do for the metamorph
    handler's ``page`` indices).  Some writers emit per-plane IFDs
    instead; both layouts are handled.

    Linear page convention (shared with the metamorph handler and the
    ``stk`` container handler): ``page = z``.
    """

    _UIC2 = 33629

    def __enter__(self):
        import mmap
        import struct

        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        # mmap, not read_bytes(): imextract's thread pool opens one reader
        # per plane, and multi-GB stacks would be read N times over
        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0,
                                   access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            raise MetadataError(f"empty STK file: {self.filename}") from exc
        try:
            bo, ifds = _tiff_parse(self._data)
            self._parse_stk(bo, ifds)
        except (MetadataError, NotSupportedError):
            self.__exit__()
            raise
        except (KeyError, IndexError, struct.error) as exc:
            self.__exit__()
            raise MetadataError(
                f"corrupt STK structure in {self.filename}: {exc}"
            ) from exc
        return self

    def _parse_stk(self, bo: str, ifds: list) -> None:
        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        self._bo = bo
        buf = self._data
        first = ifds[0]
        self.width = _tiff_int(bo, buf, first, 256, 0)
        self.height = _tiff_int(bo, buf, first, 257, 0)
        bits = _tiff_int(bo, buf, first, 258, 8)
        self._compression = _tiff_int(bo, buf, first, 259, 1)
        self._predictor = _tiff_int(bo, buf, first, 317, 1)
        samples = _tiff_int(bo, buf, first, 277, 1)
        if self.width <= 0 or self.height <= 0:
            raise MetadataError(f"corrupt STK dimensions in {self.filename}")
        if bits not in (8, 16) or samples != 1:
            raise NotSupportedError(
                f"STK reader handles 8/16-bit grayscale, got {bits}-bit "
                f"x{samples} in {self.filename}"
            )
        self._dtype = np.dtype(bo + ("u1" if bits == 8 else "u2"))
        uic2 = first.get(self._UIC2)
        n_uic = uic2[1] if uic2 else 0
        if len(ifds) == 1 and n_uic >= 1:
            # canonical STK: one IFD, planes appended after plane 0's data
            if self._compression != 1:
                raise NotSupportedError(
                    f"compressed single-IFD STK is not supported "
                    f"({self.filename}): plane offsets are only defined "
                    "for contiguous uncompressed planes"
                )
            self.n_zplanes = n_uic
            self._layout = "contiguous"
            offs, counts = _tiff_strips(bo, buf, first, self.filename)
            self._strip_offsets = offs
            self._strip_counts = counts
            self._plane_bytes = self.width * self.height * self._dtype.itemsize
            if sum(counts) < self._plane_bytes:
                raise MetadataError(f"truncated STK plane 0 in {self.filename}")
            end = offs[-1] + counts[-1] + (self.n_zplanes - 1) * self._plane_bytes
            size = len(buf)
            if end > size:
                raise MetadataError(
                    f"truncated STK stack {self.filename}: {size} bytes "
                    f"< {end} expected for {self.n_zplanes} planes"
                )
        else:
            # per-plane IFDs (paged variant some writers emit)
            self.n_zplanes = len(ifds)
            self._layout = "paged"
            self._ifds = ifds
        self.n_channels = 1
        self.n_tpoints = 1

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            self._data.close()
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    def _read_ifd_plane(self, ifd: dict) -> np.ndarray:
        return _decode_ifd_plane(self._bo, self._data, ifd, self.width,
                                 self.height, self._dtype, self.filename)

    def read_plane(self, z: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        if not 0 <= z < self.n_zplanes:
            raise MetadataError(
                f"plane {z} out of range for {self.filename}: "
                f"Z={self.n_zplanes}"
            )
        if self._layout == "paged":
            return self._read_ifd_plane(self._ifds[z])
        shift = z * self._plane_bytes
        raw = bytearray()
        need = self._plane_bytes
        for off, cnt in zip(self._strip_offsets, self._strip_counts):
            take = min(cnt, need - len(raw))
            base = off + shift
            raw += self._data[base:base + take]
            if len(raw) >= need:
                break
        plane = np.frombuffer(bytes(raw), self._dtype).reshape(
            self.height, self.width
        )
        return _apply_predictor(plane, self._predictor)

    def read_plane_linear(self, page: int) -> np.ndarray:
        return self.read_plane(page)


class LSMReader(Reader):
    """First-party reader for Zeiss LSM 510/710 confocal stacks.

    Seventh entry in the Bio-Formats-gap program.  An ``.lsm`` file is a
    classic TIFF in which every full-resolution plane IFD is followed by
    a thumbnail IFD (``NewSubfileType`` = 1, skipped here), channels are
    stored planar (``PlanarConfiguration`` = 2) as one strip per channel
    inside each plane IFD, and the acquisition dimensions live in the
    private CZ_LSMINFO tag (34412: DimensionZ / Channels / Time at byte
    offsets 16/20/24 of the struct).  Full-resolution IFDs are ordered Z
    fastest, then T — cross-checked against ``Z * T`` at open.

    Linear page convention (shared with the ``lsm`` metaconfig handler,
    same as DV/IMS): ``page = (c * Z + z) * T + t``.
    """

    _CZ_LSMINFO = 34412
    #: CZ_LSMINFO magic numbers (LSM 5 / LSM 7 series)
    _MAGIC = (0x00300494, 0x00400494)

    def __enter__(self):
        import mmap
        import struct

        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0,
                                   access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            raise MetadataError(f"empty LSM file: {self.filename}") from exc
        try:
            bo, ifds = _tiff_parse(self._data)
            self._parse_lsm(bo, ifds)
        except (MetadataError, NotSupportedError):
            self.__exit__()
            raise
        except (KeyError, IndexError, struct.error) as exc:
            self.__exit__()
            raise MetadataError(
                f"corrupt LSM structure in {self.filename}: {exc}"
            ) from exc
        return self

    def _parse_lsm(self, bo: str, ifds: list) -> None:
        import struct

        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        buf = self._data
        self._bo = bo
        full = [
            ifd for ifd in ifds if _tiff_int(bo, buf, ifd, 254, 0) == 0
        ]
        if not full:
            raise MetadataError(f"no full-resolution IFDs in {self.filename}")
        info = ifds[0].get(self._CZ_LSMINFO)
        if info is None:
            raise MetadataError(
                f"not an LSM file (no CZ_LSMINFO tag): {self.filename}"
            )
        info_off = _tiff_value_offset(bo, buf, info)
        # the CZ_LSMINFO struct is always little-endian (as is every real
        # LSM file; the tag layout predates any big-endian writer)
        magic, _size, _x, _y, dim_z, dim_c, dim_t = struct.unpack_from(
            "<IiiiiiI", buf, info_off
        )
        if magic not in self._MAGIC:
            raise MetadataError(
                f"bad CZ_LSMINFO magic 0x{magic:08x} in {self.filename}"
            )
        first = full[0]
        self.width = _tiff_int(bo, buf, first, 256, 0)
        self.height = _tiff_int(bo, buf, first, 257, 0)
        bits = _tiff_int(bo, buf, first, 258, 8)
        samples = _tiff_int(bo, buf, first, 277, 1)
        planar = _tiff_int(bo, buf, first, 284, 1)
        if self.width <= 0 or self.height <= 0:
            raise MetadataError(f"corrupt LSM dimensions in {self.filename}")
        if bits not in (8, 16):
            raise NotSupportedError(
                f"LSM reader handles 8/16-bit data, got {bits}-bit "
                f"in {self.filename}"
            )
        if samples > 1 and planar != 2:
            raise NotSupportedError(
                f"interleaved (chunky) multi-channel LSM is not supported "
                f"in {self.filename}"
            )
        self.n_channels = max(dim_c, 1)
        if samples != self.n_channels:
            raise MetadataError(
                f"LSM channel mismatch in {self.filename}: CZ_LSMINFO says "
                f"{self.n_channels}, IFD SamplesPerPixel says {samples}"
            )
        self.n_zplanes = max(dim_z, 1)
        self.n_tpoints = max(dim_t, 1)
        if len(full) != self.n_zplanes * self.n_tpoints:
            raise MetadataError(
                f"LSM plane-count mismatch in {self.filename}: "
                f"{len(full)} full-resolution IFDs != Z {self.n_zplanes} "
                f"x T {self.n_tpoints}"
            )
        self._dtype = np.dtype(bo + ("u1" if bits == 8 else "u2"))
        self._full = full

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            self._data.close()
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    def read_plane(self, z: int, c: int, t: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        for name, val, n in (("zplane", z, self.n_zplanes),
                             ("channel", c, self.n_channels),
                             ("tpoint", t, self.n_tpoints)):
            if not 0 <= val < n:
                raise MetadataError(
                    f"{name} {val} out of range for {self.filename} "
                    f"(Z={self.n_zplanes} C={self.n_channels} "
                    f"T={self.n_tpoints})"
                )
        bo, buf = self._bo, self._data
        ifd = self._full[t * self.n_zplanes + z]
        offs, counts = _tiff_strips(bo, buf, ifd, self.filename)
        if len(offs) != self.n_channels:
            raise MetadataError(
                f"LSM strip layout in {self.filename}: {len(offs)} strips "
                f"for {self.n_channels} channels (expected one per channel)"
            )
        compression = _tiff_int(bo, buf, ifd, 259, 1)
        predictor = _tiff_int(bo, buf, ifd, 317, 1)
        expect = self.width * self.height * self._dtype.itemsize
        raw = _decode_strip(bytes(buf[offs[c]:offs[c] + counts[c]]),
                            compression, expect, self.filename)
        plane = np.frombuffer(raw, self._dtype).reshape(
            self.height, self.width
        )
        return _apply_predictor(plane, predictor)

    def read_plane_linear(self, page: int) -> np.ndarray:
        ct, t = divmod(page, self.n_tpoints)
        c, z = divmod(ct, self.n_zplanes)
        return self.read_plane(z, c, t)


def _decode_oif_text(raw: bytes) -> str:
    """Olympus INI text is UTF-16-LE with BOM on real scopes; tolerate
    BOM-less UTF-16 and plain 8-bit too (fixtures, resaved files)."""
    if raw[:2] in (b"\xff\xfe", b"\xfe\xff"):
        # "replace", not strict: a corrupt odd-length tail must degrade
        # to unparseable text (-> MetadataError downstream), not leak
        # UnicodeDecodeError past the skip-unreadable guard (fuzz-caught)
        return raw.decode("utf-16", "replace")
    if b"\x00" in raw[:64]:
        return raw.decode("utf-16-le", "replace")
    return raw.decode("utf-8", "replace")


def _parse_oif_dims(text: str) -> dict[str, int]:
    """Axis sizes from an OIF main file: ``[Axis N Parameters Common]``
    sections carry ``AxisCode`` (X/Y/Z/T/C/…) and ``MaxSize``.  Returns
    ``{axis_code: size}`` for POSITIVE sizes only — FV1000 files declare
    every axis slot and unused ones carry ``MaxSize=0``, which must not
    shadow the decode-from-first-plane fallback (X/Y) or the observed
    plane grid (C/Z/T)."""
    import re as _re

    dims: dict[str, int] = {}
    code = size = None
    section_ok = False

    def flush():
        if section_ok and code and size and size > 0:
            dims[code] = size

    for line in text.splitlines():
        line = line.strip()
        if line.startswith("["):
            flush()
            code = size = None
            section_ok = bool(
                _re.match(r"\[Axis \d+ Parameters Common\]", line)
            )
            continue
        if not section_ok or "=" not in line:
            continue
        key, _, val = line.partition("=")
        val = val.strip().strip('"')
        if key.strip() == "AxisCode":
            code = val.upper() or None
        elif key.strip() == "MaxSize":
            try:
                size = int(val)
            except ValueError:
                size = None
    flush()
    return dims


def _parse_oif_plane_name(name: str) -> "tuple[int, int, int] | None":
    """(c, z, t) 0-based from an Olympus plane filename
    (``s_C001Z002T003.tif`` with any subset of the axis tokens, 1-based),
    or None for non-plane files."""
    import re as _re

    base = name.rsplit("/", 1)[-1]
    if not base.lower().endswith((".tif", ".tiff")):
        return None
    c = _re.search(r"[Cc](\d{2,})", base)
    z = _re.search(r"[Zz](\d{2,})", base)
    t = _re.search(r"[Tt](\d{2,})", base)
    if not (c or z or t):
        return None
    take = lambda m: max(0, int(m.group(1)) - 1) if m else 0
    return take(c), take(z), take(t)


def _decode_ifd_plane(bo, buf, ifd, width, height, dtype, filename) -> np.ndarray:
    """Strip-decode one grayscale IFD to a (height, width) array — the
    shared body of STKReader's paged layout and the Olympus plane
    decode (one strip loop to fix, not three)."""
    from tmlibrary_tpu.errors import MetadataError

    compression = _tiff_int(bo, buf, ifd, 259, 1)
    predictor = _tiff_int(bo, buf, ifd, 317, 1)
    rows_per_strip = _tiff_int(bo, buf, ifd, 278, height)
    offs, counts = _tiff_strips(bo, buf, ifd, filename)
    row_bytes = width * dtype.itemsize
    raw = bytearray()
    rows_left = height
    for off, cnt in zip(offs, counts):
        rows = min(rows_per_strip, rows_left)
        raw += _decode_strip(bytes(buf[off:off + cnt]), compression,
                             rows * row_bytes, filename)
        rows_left -= rows
    if len(raw) < height * row_bytes:
        raise MetadataError(f"truncated TIFF plane in {filename}")
    plane = np.frombuffer(bytes(raw[:height * row_bytes]), dtype).reshape(
        height, width
    )
    return _apply_predictor(plane, predictor)


def _gray_ifd_plane(bo, buf, ifd, filename, what) -> np.ndarray:
    """Validate one IFD as 8/16-bit single-sample grayscale and strip-
    decode it — the ONE guard+decode body shared by the Olympus plane
    path and the plain-TIFF Python fallback (``what`` names the caller's
    format in the error)."""
    from tmlibrary_tpu.errors import MetadataError, NotSupportedError

    width = _tiff_int(bo, buf, ifd, 256, 0)
    height = _tiff_int(bo, buf, ifd, 257, 0)
    bits = _tiff_int(bo, buf, ifd, 258, 8)
    samples = _tiff_int(bo, buf, ifd, 277, 1)
    if width <= 0 or height <= 0:
        raise MetadataError(f"corrupt TIFF dimensions in {filename}")
    if bits not in (8, 16) or samples != 1:
        raise NotSupportedError(
            f"{what} are 8/16-bit grayscale; got {bits}-bit "
            f"x{samples} in {filename}"
        )
    dtype = np.dtype(bo + ("u1" if bits == 8 else "u2"))
    return _decode_ifd_plane(bo, buf, ifd, width, height, dtype, filename)


def _tiff_single_plane(buf, filename) -> np.ndarray:
    """Decode IFD 0 of a single-plane grayscale TIFF held in ``buf``
    (bytes/mmap) — the payload format of Olympus plane files, shared by
    the on-disk ``.oif.files`` TIFFs and the in-memory OIB streams."""
    bo, ifds = _tiff_parse(buf)
    return _gray_ifd_plane(bo, buf, ifds[0], filename,
                           "Olympus plane TIFFs")


def _parse_oif_channel_names(text: str) -> "list[str] | None":
    """Dye names from ``[Channel N Parameters]`` sections (``DyeName``,
    ``CH Name`` fallback), ordered by channel number — or None."""
    import re as _re

    by_num: dict[int, str] = {}
    num = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("["):
            m = _re.match(r"\[Channel (\d+) Parameters\]", line)
            num = int(m.group(1)) if m else None
            continue
        if num is None or "=" not in line:
            continue
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip().strip('"')
        if key == "DyeName" and val:
            by_num[num] = val
        elif key == "CH Name" and val:
            by_num.setdefault(num, val)
    if not by_num:
        return None
    return [by_num[n] for n in sorted(by_num)]


class _OlympusBase(Reader):
    """Shared OIF/OIB logic: dims from the main-file INI, plane lookup
    from C/Z/T filename tokens, the linear page convention
    ``page = (c * Z + z) * T + t`` (same as DV/IMS/LSM)."""

    def _finish_open(self, text: str, plane_names) -> None:
        from tmlibrary_tpu.errors import MetadataError

        dims = _parse_oif_dims(text)
        self._planes: dict[tuple, object] = {}
        for name in plane_names:
            czt = _parse_oif_plane_name(str(name))
            if czt is not None:
                # first wins: OIBs occasionally carry duplicate preview
                # copies of plane 0 under another storage
                self._planes.setdefault(czt, name)
        if not self._planes:
            raise MetadataError(
                f"no C/Z/T plane files found in {self.filename}"
            )
        # the planes actually present are authoritative — the INI of an
        # aborted acquisition still declares the PLANNED sizes, and
        # enumerating those would make every missing (c,z,t) a
        # MetadataError at extract time.  An aborted scan's trailing
        # partial timepoint is trimmed the same way; any hole elsewhere
        # in the grid means real corruption and fails the open (the
        # handler's skip-unreadable loop logs and moves on).
        self.n_channels = max(k[0] for k in self._planes) + 1
        self.n_zplanes = max(k[1] for k in self._planes) + 1
        n_t = max(k[2] for k in self._planes) + 1
        full_cz = self.n_channels * self.n_zplanes
        while n_t > 1 and sum(
            1 for k in self._planes if k[2] == n_t - 1
        ) < full_cz:
            n_t -= 1
        self.n_tpoints = n_t
        missing = [
            (c, z, t)
            for c in range(self.n_channels)
            for z in range(self.n_zplanes)
            for t in range(self.n_tpoints)
            if (c, z, t) not in self._planes
        ]
        if missing:
            raise MetadataError(
                f"incomplete Olympus plane grid in {self.filename}: "
                f"missing {missing[:4]}{'…' if len(missing) > 4 else ''}"
            )
        # plane shape: X/Y axis sizes when the INI carries them, else
        # decoded from the first plane (container_dimensions probes this)
        if dims.get("X", 0) > 0 and dims.get("Y", 0) > 0:
            self.width, self.height = dims["X"], dims["Y"]
        else:
            first = _tiff_single_plane(
                *self._plane_buf(self._planes[min(self._planes)])
            )
            self.height, self.width = first.shape
        # dye names, count-guarded against the observed channel grid
        names = _parse_oif_channel_names(text)
        self.channel_names = (
            names if names and len(names) == self.n_channels else None
        )

    def _plane_buf(self, key):  # pragma: no cover - abstract
        raise NotImplementedError

    def read_plane(self, c: int, z: int, t: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        name = self._planes.get((c, z, t))
        if name is None:
            raise MetadataError(
                f"missing plane C{c} Z{z} T{t} in {self.filename}"
            )
        buf, label = self._plane_buf(name)
        return _tiff_single_plane(buf, label)

    def read_plane_linear(self, page: int) -> np.ndarray:
        cz, t = divmod(page, self.n_tpoints)
        c, z = divmod(cz, self.n_zplanes)
        return self.read_plane(c, z, t)


class OIFReader(_OlympusBase):
    """First-party reader for Olympus ``.oif`` acquisitions (FluoView
    FV1000 and kin): a UTF-16 INI main file next to a
    ``<name>.oif.files/`` directory of single-plane TIFFs named by axis
    tokens (``s_C001Z002.tif``).

    Eighth entry in the Bio-Formats-gap program (SURVEY.md §3 Readers
    row).  Dims come from the ``[Axis N Parameters Common]`` sections
    (MaxSize per AxisCode), cross-checked against the plane files
    actually present.
    """

    def __enter__(self):
        from tmlibrary_tpu.errors import MetadataError

        try:
            text = _decode_oif_text(self.filename.read_bytes())
        except OSError as exc:
            raise MetadataError(
                f"unreadable OIF file: {self.filename}"
            ) from exc
        if "[Axis" not in text and "OibSaveInfo" not in text:
            raise MetadataError(
                f"not an Olympus OIF main file: {self.filename}"
            )
        files_dir = self.filename.with_name(self.filename.name + ".files")
        if not files_dir.is_dir():
            raise MetadataError(
                f"OIF companion directory missing: {files_dir}"
            )
        self._dir = files_dir  # before _finish_open: the shape probe reads a plane
        self._finish_open(
            text, [p.name for p in sorted(files_dir.iterdir())]
        )
        return self

    def _plane_buf(self, name):
        from tmlibrary_tpu.errors import MetadataError

        path = self._dir / name
        try:
            return path.read_bytes(), path
        except OSError as exc:
            raise MetadataError(f"unreadable OIF plane: {path}") from exc


class OIBReader(_OlympusBase):
    """First-party reader for Olympus ``.oib`` acquisitions — the same
    FluoView data as :class:`OIFReader` packed into one OLE2 compound
    file (parsed by :class:`tmlibrary_tpu.cfb.CompoundFile`, no JVM).

    Ninth entry in the Bio-Formats-gap program.  The root ``OibInfo.txt``
    stream maps storage streams back to their original OIF-tree names
    (``Stream00001=s_C001Z001.tif``); when it is absent the raw stream
    names are used directly.  The embedded ``.oif`` main file supplies
    the axis dims, cross-checked against the planes present.
    """

    def __enter__(self):
        import mmap

        from tmlibrary_tpu.cfb import CompoundFile
        from tmlibrary_tpu.errors import MetadataError

        # mmap + lazy CompoundFile streams: an open reader holds the
        # directory tables, not the pixel payloads (the imextract reader
        # cache keeps up to 64 containers open — see _OPEN_READERS)
        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0,
                                   access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            self._file = None
            raise MetadataError(f"empty OIB file: {self.filename}") from exc
        try:
            cf = CompoundFile(self._data, self.filename)
            # OibInfo.txt (any storage depth) maps CFB stream names back
            # to OIF-tree names.  Keys may be flat (``[OibSaveInfo]``
            # ``Stream00000=…``) or grouped in per-storage sections
            # (``[Storage00001]``): when the section names a real
            # storage, the rename is keyed by the full path so equal
            # stream basenames in different storages cannot collide.
            renames: dict[str, str] = {}
            storages = {
                p.rsplit("/", 1)[0] for p in cf.stream_paths if "/" in p
            }
            for path in cf.stream_paths:
                if path.rsplit("/", 1)[-1].lower() != "oibinfo.txt":
                    continue
                section = ""
                for line in _decode_oif_text(
                    cf.read_stream(path)
                ).splitlines():
                    line = line.strip()
                    if line.startswith("[") and line.endswith("]"):
                        section = line[1:-1]
                        continue
                    key, _, val = line.partition("=")
                    key, val = key.strip(), val.strip().strip('"')
                    if not (
                        _parse_oif_plane_name(val)
                        or val.lower().endswith(".oif")
                    ):
                        continue
                    full = f"{section}/{key}" if section in storages else key
                    renames.setdefault(full, val)
            # resolution: full-path rename, then basename rename, then
            # the bare basename; first wins in sorted storage order so a
            # later storage's preview duplicate cannot shadow the
            # acquisition plane
            named: dict[str, str] = {}
            for p in sorted(cf.stream_paths):
                base = p.rsplit("/", 1)[-1]
                named.setdefault(renames.get(p, renames.get(base, base)), p)
            main = next(
                (n for n in sorted(named) if n.lower().endswith(".oif")),
                None,
            )
            text = (
                _decode_oif_text(cf.read_stream(named[main])) if main else ""
            )
            self._cf = cf
            self._named = named
            self._finish_open(text, list(named))
        except MetadataError:
            self.__exit__()
            raise
        return self

    def __exit__(self, *exc):
        self._cf = None
        if getattr(self, "_data", None) is not None:
            try:
                self._data.close()
            except BufferError:
                # a failed parse's traceback pins memoryview exports of
                # the mmap; the mapping is freed when the last view dies
                pass
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    def _plane_buf(self, name):
        return self._cf.read_stream(self._named[name]), f"{self.filename}:{name}"


class FlexReader(Reader):
    """First-party reader for PerkinElmer Opera/Operetta ``.flex``
    containers — the reference's own instrument class (high-content
    screening), read upstream through Bio-Formats' FlexReader.

    A ``.flex`` file holds one well: a paged TIFF whose IFD pages cycle
    channel-fastest through the well's fields, with the acquisition
    described by the FLEX XML document in private tag 65200.  The
    channel set is the ordered unique ``Name`` attributes of the XML's
    ``Array`` elements (one per page, repeating per field); when the XML
    is absent or does not factor the page count, the file degrades to
    one channel with pages as fields.

    Linear page convention (shared with the ``flex`` metaconfig
    handler): ``page = field * n_channels + c`` — the raw IFD index.
    """

    _FLEX_XML = 65200

    def __enter__(self):
        import mmap
        import struct

        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        self._file = open(self.filename, "rb")
        try:
            self._data = mmap.mmap(self._file.fileno(), 0,
                                   access=mmap.ACCESS_READ)
        except ValueError as exc:
            self._file.close()
            self._file = None
            raise MetadataError(f"empty FLEX file: {self.filename}") from exc
        try:
            bo, ifds = _tiff_parse(self._data)
            self._parse_flex(bo, ifds)
        except (MetadataError, NotSupportedError):
            self.__exit__()
            raise
        except (KeyError, IndexError, struct.error) as exc:
            self.__exit__()
            raise MetadataError(
                f"corrupt FLEX structure in {self.filename}: {exc}"
            ) from exc
        return self

    def _parse_flex(self, bo: str, ifds: list) -> None:
        from tmlibrary_tpu.errors import MetadataError, NotSupportedError

        self._bo, self._ifds = bo, ifds
        buf = self._data
        first = ifds[0]
        self.width = _tiff_int(bo, buf, first, 256, 0)
        self.height = _tiff_int(bo, buf, first, 257, 0)
        bits = _tiff_int(bo, buf, first, 258, 8)
        samples = _tiff_int(bo, buf, first, 277, 1)
        if self.width <= 0 or self.height <= 0:
            raise MetadataError(f"corrupt FLEX dimensions in {self.filename}")
        if bits not in (8, 16) or samples != 1:
            raise NotSupportedError(
                f"FLEX reader handles 8/16-bit grayscale, got {bits}-bit "
                f"x{samples} in {self.filename}"
            )
        self._dtype = np.dtype(bo + ("u1" if bits == 8 else "u2"))
        for i, ifd in enumerate(ifds[1:], start=1):
            # Bio-Formats' FlexReader models per-plane sizes; this one
            # assumes page-0 geometry for every page, so a mismatched
            # page must fail loudly here rather than decode later pages
            # with misaligned rows (silently scrambled pixels)
            page = (_tiff_int(bo, buf, ifd, 256, 0),
                    _tiff_int(bo, buf, ifd, 257, 0),
                    _tiff_int(bo, buf, ifd, 258, 8),
                    _tiff_int(bo, buf, ifd, 277, 1))
            if page != (self.width, self.height, bits, samples):
                raise NotSupportedError(
                    f"FLEX page {i} geometry {page} differs from page 0 "
                    f"{(self.width, self.height, bits, samples)} in "
                    f"{self.filename}; per-page sizes are not supported"
                )
        names = self._channel_names_from_xml(bo, buf, first)
        n_pages = len(ifds)
        if names and n_pages % len(names) == 0:
            self.n_channels = len(names)
            self.channel_names = names
        else:
            self.n_channels = 1
            self.channel_names = None
        self.n_fields = n_pages // self.n_channels

    def _channel_names_from_xml(self, bo, buf, ifd) -> "list[str] | None":
        """Ordered unique Array Names of the FLEX document, or None."""
        entry = ifd.get(self._FLEX_XML)
        if entry is None:
            return None
        typ, cnt, _ = entry
        if typ not in (1, 2, 7):  # BYTE/ASCII/UNDEFINED
            return None
        base = _tiff_value_offset(bo, buf, entry)
        if base + cnt > len(buf):
            return None
        raw = bytes(buf[base:base + cnt]).rstrip(b"\x00")
        try:
            # bytes, not a decoded str: an XML encoding declaration makes
            # fromstring(str) raise (same latent issue as the CZI helper)
            root = ElementTree.fromstring(raw)
        except (ElementTree.ParseError, ValueError):
            return None
        names: list[str] = []
        for el in root.iter():
            tag = el.tag.rsplit("}", 1)[-1]
            if tag == "Array" and el.get("Name"):
                name = el.get("Name")
                if name not in names:
                    names.append(name)
        return names or None

    def __exit__(self, *exc):
        if getattr(self, "_data", None) is not None:
            self._data.close()
            self._data = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None
        return False

    def read_plane(self, field: int, channel: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        if not (0 <= field < self.n_fields
                and 0 <= channel < self.n_channels):
            raise MetadataError(
                f"plane field={field} channel={channel} out of range for "
                f"{self.filename}: fields={self.n_fields} "
                f"channels={self.n_channels}"
            )
        return self.read_plane_linear(field * self.n_channels + channel)

    def read_plane_linear(self, page: int) -> np.ndarray:
        from tmlibrary_tpu.errors import MetadataError

        if not 0 <= page < len(self._ifds):
            raise MetadataError(
                f"page {page} out of range for {self.filename}: "
                f"{len(self._ifds)} pages"
            )
        return _decode_ifd_plane(self._bo, self._data, self._ifds[page],
                                 self.width, self.height, self._dtype,
                                 self.filename)


class DatasetReader(Reader):
    """HDF5 dataset reader (reference ``DatasetReader``; h5py-backed)."""

    def __enter__(self):
        import h5py

        self._f = h5py.File(self.filename, "r")
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def read(self, path: str) -> np.ndarray:
        if path not in self._f:
            raise KeyError(f"no dataset '{path}' in {self.filename}")
        return np.asarray(self._f[path])

    def list_datasets(self, group: str = "/") -> list[str]:
        import h5py

        out = []
        self._f[group].visititems(
            lambda name, obj: out.append(name) if isinstance(obj, h5py.Dataset) else None
        )
        return out

    def exists(self, path: str) -> bool:
        return path in self._f


class JsonReader(Reader):
    def read(self):
        return json.loads(self.filename.read_text())


class XmlReader(Reader):
    def read(self) -> ElementTree.Element:
        return ElementTree.fromstring(self.filename.read_text())


class TablesReader(Reader):
    """Tabular reader (reference used pandas/HDF; here Parquet + CSV)."""

    def read(self):
        import pandas as pd

        suffix = self.filename.suffix.lower()
        if suffix == ".parquet":
            return pd.read_parquet(self.filename)
        if suffix == ".csv":
            return pd.read_csv(self.filename)
        raise NotSupportedError(f"unsupported table format '{suffix}'")
