"""Context-manager readers.

Reference parity: ``tmlib/readers.py`` — ``ImageReader`` (cv2),
``BFImageReader`` (Bio-Formats via javabridge — out of scope: no JVM;
vendor ingest goes through metaconfig's filename handlers instead),
``DatasetReader`` (HDF5/h5py), ``JsonReader``, ``XmlReader``,
``TablesReader`` (pandas/HDF) — all usable as context managers.

These exist for workflow-script parity: framework-internal IO goes through
:mod:`tmlibrary_tpu.models.store`, but user analysis scripts written
against the reference's reader API translate 1:1.
"""

from __future__ import annotations

import json
from abc import ABC
from pathlib import Path
from xml.etree import ElementTree

import numpy as np

from tmlibrary_tpu.errors import NotSupportedError


class Reader(ABC):
    """Base context-manager reader (reference ``tmlib.readers.Reader``)."""

    def __init__(self, filename):
        self.filename = Path(filename)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ImageReader(Reader):
    """Read 2-D image files; grayscale TIFFs decode through the
    first-party native reader (``native.tiff_read``), everything else
    (PNG, RGB, tiled TIFF) through cv2.  uint8/uint16 preserved."""

    def read(self, page: int = 0) -> np.ndarray:
        if str(self.filename).lower().endswith((".tif", ".tiff")):
            from tmlibrary_tpu.native import tiff_info, tiff_read

            info = tiff_info(self.filename)
            if info is not None:
                _, h, w, bits = info
                img = tiff_read(self.filename, page, h, w)
                if img is not None:
                    return img.astype(np.uint8) if bits == 8 else img

        import cv2

        img = cv2.imread(str(self.filename), cv2.IMREAD_UNCHANGED)
        if img is None:
            raise FileNotFoundError(f"cannot read image: {self.filename}")
        if img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2GRAY)
        return img


class BFImageReader(Reader):
    """Bio-Formats reader placeholder.

    The reference reads vendor microscope formats through the Java
    Bio-Formats library (``python-bioformats``/``javabridge``).  This image
    has no JVM; vendor ingest is handled by metaconfig's filename handlers
    plus plain-TIFF extraction.  Instantiating this reader states that
    clearly instead of failing deep inside a job.
    """

    def read(self):
        raise NotSupportedError(
            "Bio-Formats is not available (no JVM); convert vendor files to "
            "TIFF/PNG and use the metaconfig filename handlers"
        )


class DatasetReader(Reader):
    """HDF5 dataset reader (reference ``DatasetReader``; h5py-backed)."""

    def __enter__(self):
        import h5py

        self._f = h5py.File(self.filename, "r")
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def read(self, path: str) -> np.ndarray:
        if path not in self._f:
            raise KeyError(f"no dataset '{path}' in {self.filename}")
        return np.asarray(self._f[path])

    def list_datasets(self, group: str = "/") -> list[str]:
        import h5py

        out = []
        self._f[group].visititems(
            lambda name, obj: out.append(name) if isinstance(obj, h5py.Dataset) else None
        )
        return out

    def exists(self, path: str) -> bool:
        return path in self._f


class JsonReader(Reader):
    def read(self):
        return json.loads(self.filename.read_text())


class XmlReader(Reader):
    def read(self) -> ElementTree.Element:
        return ElementTree.fromstring(self.filename.read_text())


class TablesReader(Reader):
    """Tabular reader (reference used pandas/HDF; here Parquet + CSV)."""

    def read(self):
        import pandas as pd

        suffix = self.filename.suffix.lower()
        if suffix == ".parquet":
            return pd.read_parquet(self.filename)
        if suffix == ".csv":
            return pd.read_csv(self.filename)
        raise NotSupportedError(f"unsupported table format '{suffix}'")
