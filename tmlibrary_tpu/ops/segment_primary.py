"""Primary object segmentation (nuclei).

Reference parity: ``jtmodules/segment_primary.py`` — CellProfiler-style
IdentifyPrimaryObjects: global/adaptive threshold → fill holes → size
filter → label (declumping of touching nuclei via distance-transform maxima
is the reference's optional extra; here it is the optional ``declump`` path
built on the same level-flooding watershed as secondary segmentation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tmlibrary_tpu.ops import label as label_ops
from tmlibrary_tpu.ops import threshold as threshold_ops
from tmlibrary_tpu.ops.segment_secondary import watershed_from_seeds
from tmlibrary_tpu.ops.smooth import gaussian_smooth


def distance_transform_approx(
    mask: jax.Array, max_distance: int = 64, method: str = "auto"
) -> jax.Array:
    """Chamfer-style 8-neighbor distance-to-background, by iterative
    erosion counting (distance in "erosion rings"; exact for the
    chessboard metric which is what seed detection needs).

    The XLA path erodes under ``lax.while_loop`` with an early exit once
    everything has eroded away (bounded by ``max_distance``);
    ``method="pallas"`` (or ``"auto"`` + ``TMX_PALLAS=1`` on TPU) runs the
    identical fixpoint in VMEM; ``"native"`` computes the same values via
    a two-pass chamfer in C++ (``tm_chebyshev_dt``) — the fast path on
    the CPU backend.  ``"auto"`` resolution order (pinned): native on cpu
    when available → pallas on TPU per
    ``pallas_kernels.pallas_enabled("distance")`` (measured per-kernel
    shootout) → xla.
    """
    mask = jnp.asarray(mask, bool)
    if method == "auto":
        from tmlibrary_tpu import native

        if native.cpu_native_enabled():
            method = "native"
        else:
            from tmlibrary_tpu.ops.pallas_kernels import pallas_enabled

            method = "pallas" if pallas_enabled("distance") else "xla"
    if method == "native":
        import numpy as np

        from tmlibrary_tpu import native

        return jax.pure_callback(
            native.batch_sites(2)(
                lambda m: native.chebyshev_dt_host(np.asarray(m), max_distance)
            ),
            jax.ShapeDtypeStruct(mask.shape, jnp.float32),
            mask,
            vmap_method=native.callback_vmap_method(),
        )
    if method == "pallas":
        from tmlibrary_tpu.ops.pallas_kernels import distance_transform

        return distance_transform(
            mask, max_distance, interpret=jax.default_backend() == "cpu"
        )

    def cond(state):
        _, cur, i = state
        return jnp.any(cur) & (i < max_distance)

    def body(state):
        dist, cur, i = state
        nxt = label_ops.binary_erode(cur, connectivity=8, iterations=1)
        return dist + nxt.astype(jnp.float32), nxt, i + 1

    dist, _, _ = jax.lax.while_loop(
        cond, body, (mask.astype(jnp.float32), mask, jnp.int32(0))
    )
    return dist


def local_maxima_seeds(
    surface: jax.Array,
    mask: jax.Array,
    min_distance: int = 5,
    smooth_sigma: float = 0.0,
) -> jax.Array:
    """Find peaks of ``surface`` within ``mask`` separated by at least
    ``min_distance`` (max-filter comparison), returning a labeled seed image.

    ``smooth_sigma`` pre-blurs the surface (CellProfiler-style): on chamfer
    distance transforms the saddle between touching objects forms a flat
    plateau that would otherwise register as a spurious third maximum.
    """
    from jax import lax

    if smooth_sigma > 0:
        surface = gaussian_smooth(surface, smooth_sigma)
    size = 2 * min_distance + 1
    # windowed max via reduce_window (one fused VPU pass instead of a
    # size^2 slice-gather); -inf pad outside the image cannot beat any
    # real value, so border maxima match the old reflect-pad gather
    neigh_max = lax.reduce_window(
        jnp.asarray(surface, jnp.float32),
        -jnp.inf,
        lax.max,
        window_dimensions=(size, size),
        window_strides=(1, 1),
        padding="SAME",
    )
    is_max = (surface >= neigh_max) & jnp.asarray(mask, bool)
    seeds, _ = label_ops.connected_components(is_max, connectivity=8)
    return seeds


def segment_primary(
    intensity_image: jax.Array,
    threshold_method: str = "otsu",
    threshold_value: float = 0.0,
    correction_factor: float = 1.0,
    kernel_size: int = 31,
    constant: float = 0.0,
    smooth_sigma: float = 1.0,
    fill: bool = True,
    min_area: int = 0,
    max_area: int | None = None,
    declump: bool = False,
    declump_min_distance: int = 5,
    max_objects: int = 256,
) -> tuple[jax.Array, jax.Array]:
    """Segment primary objects; returns (labels, count)."""
    img = jnp.asarray(intensity_image, jnp.float32)
    if smooth_sigma > 0:
        img = gaussian_smooth(img, smooth_sigma)
    if threshold_method == "otsu":
        mask = threshold_ops.threshold_otsu(img, correction_factor=correction_factor)
    elif threshold_method == "manual":
        mask = threshold_ops.threshold_manual(img, threshold_value)
    elif threshold_method == "adaptive":
        mask = threshold_ops.threshold_adaptive(
            img, kernel_size=kernel_size, constant=constant
        )
    else:
        raise ValueError(f"unknown threshold method '{threshold_method}'")
    if fill:
        mask = label_ops.fill_holes(mask)
    labels, _ = label_ops.connected_components(mask, connectivity=8)
    if declump:
        # split touching objects: watershed on the distance transform from
        # its local maxima (CellProfiler shape-based declumping)
        dist = distance_transform_approx(mask)
        seeds = local_maxima_seeds(
            dist, mask, min_distance=declump_min_distance,
            smooth_sigma=declump_min_distance / 2.0,
        )
        labels = watershed_from_seeds(dist, seeds, mask)
        # watershed labels carry seed ids (peak scan order); re-rank by
        # each region's first pixel so declumped output keeps the
        # scipy-scan-order convention of the bit-identical gate.  Clip
        # first: ids beyond capacity must drop, not alias onto the last id.
        labels = label_ops.clip_label_count(labels, max_objects)
        labels = label_ops.relabel_by_scan_order(labels, max_objects)
    labels = label_ops.clip_label_count(labels, max_objects)
    if min_area > 0 or max_area is not None:
        labels = label_ops.filter_by_area(
            labels, max_objects=max_objects, min_area=min_area, max_area=max_area
        )
    count = jnp.max(labels)
    return labels.astype(jnp.int32), count
