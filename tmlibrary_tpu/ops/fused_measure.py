"""Fused measure megakernels — the ``"fused"`` reduction strategy.

Roofline motivation (DESIGN.md §16, ROADMAP open item 3): the unfused
measure family makes one pass over the site tile per reduction family —
grouped sums, min/max, the quantile histogram, the GLCM cells — and
every pass re-streams the tile from HBM while its accumulator rows
round-trip HBM through the ``fori_loop`` carry.  ``tmx perf`` attributes
those rungs as bandwidth-bound.  The kernels here keep both sides of
that traffic on chip: the tile streams through VMEM once per kernel and
the per-object accumulators live in VMEM output blocks revisited across
a sequential grid (the canonical TPU accumulation pattern), so HBM sees
one read of the pixels and one write of the ``(segments, ...)`` result.

Three kernels cover the three accumulation shapes of ``ops/measure.py``:

- :func:`grouped_stats` — ONE pass emitting per-object sum, min and max
  for any stack of pixel channels.  ``intensity_features`` gets
  count/sum/sumsq/min/max from a single call (channels ``[1, v, v²]``);
  ``morphology_features`` gets area/centroid/second-moment/perimeter
  sums AND the bounding box from its 7-channel call — one HBM read where
  the unfused path takes two full passes per family.
- :func:`intensity_hist` — the per-(object, bucket) histogram feeding
  ``intensity_quantiles``: per-pixel bounds lookup, the mahotas-parity
  quantization expression and the dual one-hot contraction all inside
  the kernel.
- :func:`glcm_all` — the second fused pass: all 4 directions' GLCM
  counts in one kernel (per-object quantization of the shifted and
  unshifted pixels in VMEM, bf16 one-hot operands contracted into an
  f32 VMEM accumulator — the exact-integer-counts trick of
  ``_glcm_matmul_all``).

Parity contract (pinned by ``tests/test_reduction.py`` and
``tests/test_fused_measure.py``, interpret mode on CPU): min/max,
counts, histogram and GLCM cells are bit-identical to every reference
strategy (order-free or exact-integer accumulations); fractional f32
sums carry the same 1e-6 relative tolerance as sort/scatter vs the
one-hot reference (different accumulation order).  The quantization
expression trees are copied verbatim from ``quantize_per_object`` so
bucket assignment cannot drift.

Capacity invariance: the pixel chunk is resolved independently of the
object capacity (:func:`fused_chunk`), so rows ``0..n`` are
bit-identical for any capacity ``>= n`` — the bucket router's contract
(``ops/reduction.capacity_segments``).  Interpret-mode fallback keeps
tier-1 hardware-independent: ``interpret=None`` resolves to ``True``
off-TPU, exactly like ``pallas_kernels``.  The VMEM chunking knob
follows ``_tuned_chunk`` conventions and shares its memoized
TUNING.json reader (``TMX_FUSED_CHUNK`` env → committed ``fused_chunk``
sweep result → the default).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tmlibrary_tpu.ops.label import shift_with_fill
from tmlibrary_tpu.ops.pallas_kernels import _tuning_results
from tmlibrary_tpu.ops.reduction import capacity_segments

#: pixels per VMEM chunk (stats/histogram kernels).  Purely a cost knob:
#: every per-object row accumulates independently of the chunking, so
#: outputs are bit-identical for any chunk — EXCEPT fractional f32 sums,
#: whose accumulation order follows the chunk walk; the knob is resolved
#: once per program (never from the capacity) so the capacity-invariance
#: contract holds bit-exactly.
FUSED_CHUNK = 2048

#: the GLCM kernel's chunk is clamped here: its (chunk, segments*levels)
#: row one-hot is the largest VMEM operand in the family (DESIGN.md §22)
GLCM_CHUNK_MAX = 512

_LANE = 128  # TPU lane width: lane-dim shapes pad to a multiple of this


def fused_chunk() -> int:
    """Resolution: explicit arg (callers/tests) → ``TMX_FUSED_CHUNK``
    env → committed ``fused_chunk`` sweep result → the default.  Shares
    :func:`pallas_kernels._tuning_results` (memoized per (path, mtime))
    instead of re-reading TUNING.json."""
    import os

    env = os.environ.get("TMX_FUSED_CHUNK")
    if env:
        try:
            return max(_LANE, (int(env) // _LANE) * _LANE)
        except ValueError:
            pass
    tuned = _tuning_results().get("fused_chunk")
    if isinstance(tuned, (int, float)) and tuned >= 1:
        return max(_LANE, (int(tuned) // _LANE) * _LANE)
    return FUSED_CHUNK


def _interpret_default() -> bool:
    """Interpret-mode fallback off-TPU, like ``pallas_enabled``'s
    backend gate — tier-1 runs the same kernels on XLA-CPU."""
    return jax.default_backend() != "tpu"


def _resolve(interpret: "bool | None", chunk: "int | None") -> tuple[bool, int]:
    if interpret is None:
        interpret = _interpret_default()
    if chunk is None:
        chunk = fused_chunk()
    chunk = max(_LANE, (int(chunk) // _LANE) * _LANE)
    return bool(interpret), chunk


def _pad_lane(n: int) -> int:
    return ((int(n) + _LANE - 1) // _LANE) * _LANE


def _chunked(flat: jax.Array, chunk: int, fill=0) -> jax.Array:
    """(P,) → (n_chunks, chunk); padded pixels carry ``fill`` (label 0
    pads land in the dropped background row, value pads are masked by
    their label-0 one-hot column)."""
    p = flat.shape[0]
    pad = (-p) % chunk
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), fill, flat.dtype)]
        )
    return flat.reshape(-1, chunk)


# ------------------------------------------------------------- stats kernel
def _stats_kernel(lab_ref, val_ref, sums_ref, mins_ref, maxs_ref):
    """One chunk's contribution to per-segment (sum, min, max) of every
    channel.  The (chunk, segments) one-hot is materialized ONCE and
    shared by the MXU sum contraction and the VPU masked min/max — the
    fusion the separate grouped_sums/grouped_minmax passes cannot get."""
    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        mins_ref[:] = jnp.full_like(mins_ref, jnp.inf)
        maxs_ref[:] = jnp.full_like(maxs_ref, -jnp.inf)

    chunk = lab_ref.shape[1]
    segs_p = sums_ref.shape[1]
    n_ch = val_ref.shape[0]
    lab = lab_ref[0, :]
    ids = lax.broadcasted_iota(jnp.int32, (chunk, segs_p), 1)
    sel = lab[:, None] == ids  # (chunk, segs_p)
    vals = val_ref[:, 0, :]  # (n_ch, chunk)
    # HIGHEST keeps f32 operand precision on the MXU — same contract as
    # grouped_sums' einsum, so integral sums stay exact / bit-identical
    sums_ref[:] += lax.dot_general(
        vals, sel.astype(jnp.float32), (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    for c in range(n_ch):  # static unroll: n_ch is a trace constant
        v = vals[c, :][:, None]
        mins_ref[c, :] = jnp.minimum(
            mins_ref[c, :], jnp.min(jnp.where(sel, v, jnp.inf), axis=0)
        )
        maxs_ref[c, :] = jnp.maximum(
            maxs_ref[c, :], jnp.max(jnp.where(sel, v, -jnp.inf), axis=0)
        )


@functools.partial(
    jax.jit, static_argnames=("max_objects", "interpret", "chunk")
)
def _stats_call(flat, stacked, max_objects, interpret, chunk):
    segs = capacity_segments(max_objects)
    segs_p = _pad_lane(segs)
    n_ch = stacked.shape[0]
    lab = _chunked(flat, chunk)
    vals = jnp.stack([_chunked(v, chunk) for v in stacked])  # (C, n, chunk)
    n_chunks = lab.shape[0]
    sums, mins, maxs = pl.pallas_call(
        _stats_kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((n_ch, 1, chunk), lambda i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n_ch, segs_p), lambda i: (0, 0)),
            pl.BlockSpec((n_ch, segs_p), lambda i: (0, 0)),
            pl.BlockSpec((n_ch, segs_p), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_ch, segs_p), jnp.float32)
            for _ in range(3)
        ],
        interpret=interpret,
    )(lab, vals)
    # drop the background row and the lane padding; rows = objects
    return (
        sums[:, 1:segs].T,
        mins[:, 1:segs].T,
        maxs[:, 1:segs].T,
    )


def grouped_stats(
    labels: jax.Array,
    channels: list[jax.Array],
    max_objects: int,
    *,
    interpret: "bool | None" = None,
    chunk: "int | None" = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-object (sums, mins, maxs) of several pixel channels in ONE
    fused pass — each ``(max_objects, n_channels)`` f32, label ids
    ``1..max_objects`` (background dropped), absent rows (0, +inf,
    -inf) like the unfused twins."""
    interpret, chunk = _resolve(interpret, chunk)
    flat = jnp.asarray(labels, jnp.int32).reshape(-1)
    stacked = jnp.stack(
        [jnp.asarray(c, jnp.float32).reshape(-1) for c in channels]
    )
    return _stats_call(flat, stacked, max_objects, interpret, chunk)


# --------------------------------------------------------- histogram kernel
def _hist_kernel(lab_ref, img_ref, lo_ref, span_ref, counts_ref, *, bins):
    """Per-(object, bucket) counts with the per-pixel bounds lookup and
    quantization INSIDE the kernel.  The bounds lookup is a masked sum
    over the label one-hot — exact (each pixel selects one finite table
    entry), mirroring ``lookup_by_label``'s one-nonzero-term guarantee;
    the quantization expression is ``quantize_per_object``'s verbatim,
    so bucket assignment (and therefore every count) is bit-identical."""
    @pl.when(pl.program_id(0) == 0)
    def _init():
        counts_ref[:] = jnp.zeros_like(counts_ref)

    chunk = lab_ref.shape[1]
    segs_p = lo_ref.shape[1]
    bins_p = counts_ref.shape[1]
    lab = lab_ref[0, :]
    v = img_ref[0, :]
    ids = lax.broadcasted_iota(jnp.int32, (chunk, segs_p), 1)
    sel = lab[:, None] == ids
    lo_pix = jnp.sum(jnp.where(sel, lo_ref[0, :][None, :], 0.0), axis=1)
    span_pix = jnp.sum(jnp.where(sel, span_ref[0, :][None, :], 0.0), axis=1)
    span_pix = jnp.maximum(span_pix, 1e-6)
    q = jnp.floor((v - lo_pix) * (bins - 1) / span_pix)
    q = jnp.clip(q, 0, bins - 1).astype(jnp.int32)
    bin_ids = lax.broadcasted_iota(jnp.int32, (chunk, bins_p), 1)
    oh_q = (q[:, None] == bin_ids).astype(jnp.bfloat16)
    # bf16 one-hot operands are exact (0.0/1.0) and the MXU accumulates
    # f32 — integer counts < 2^24, the _glcm_matmul_all trick
    counts_ref[:] += lax.dot_general(
        sel.astype(jnp.bfloat16), oh_q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("max_objects", "bins", "interpret", "chunk")
)
def _hist_call(flat, img, lo_full, span_full, max_objects, bins,
               interpret, chunk):
    segs = capacity_segments(max_objects)
    segs_p = _pad_lane(segs)
    bins_p = _pad_lane(bins)
    lab = _chunked(flat, chunk)
    vals = _chunked(img, chunk)
    # lane-pad the bounds tables; padded columns are never selected
    # (labels <= max_objects), lo=0/span=1 keeps them inert regardless
    lo_p = jnp.concatenate(
        [lo_full, jnp.zeros((segs_p - segs,), jnp.float32)]
    )[None, :]
    span_p = jnp.concatenate(
        [span_full, jnp.ones((segs_p - segs,), jnp.float32)]
    )[None, :]
    counts = pl.pallas_call(
        functools.partial(_hist_kernel, bins=bins),
        grid=(lab.shape[0],),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, segs_p), lambda i: (0, 0)),
            pl.BlockSpec((1, segs_p), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((segs_p, bins_p), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((segs_p, bins_p), jnp.float32),
        interpret=interpret,
    )(lab, vals, lo_p, span_p)
    return counts[1:segs, :bins]


def _masked_bounds(bounds):
    """(raw_lo, raw_hi) → (lo_full, span_full) with the background row
    prepended — the exact expression tree of ``quantize_per_object``."""
    raw_lo, raw_hi = bounds
    present = raw_hi >= raw_lo
    lo = jnp.where(present, raw_lo, 0.0)
    span = jnp.where(present, raw_hi - lo, 1.0)
    lo_full = jnp.concatenate([jnp.zeros((1,), jnp.float32), lo])
    span_full = jnp.concatenate([jnp.ones((1,), jnp.float32), span])
    return lo_full, span_full


def intensity_hist(
    labels: jax.Array,
    intensity: jax.Array,
    max_objects: int,
    bins: int,
    bounds: tuple[jax.Array, jax.Array],
    *,
    interpret: "bool | None" = None,
    chunk: "int | None" = None,
) -> jax.Array:
    """Per-object intensity histogram ``(max_objects, bins)`` for
    ``intensity_quantiles`` — quantization and accumulation fused in one
    kernel pass.  ``bounds`` is the raw ``grouped_minmax`` output (±inf
    for absent objects), normally the fused stats kernel's min/max so
    the tile is read once for bounds and once for the histogram instead
    of three-plus times."""
    interpret, chunk = _resolve(interpret, chunk)
    flat = jnp.asarray(labels, jnp.int32).reshape(-1)
    img = jnp.asarray(intensity, jnp.float32).reshape(-1)
    lo_full, span_full = _masked_bounds(bounds)
    return _hist_call(
        flat, img, lo_full, span_full, max_objects, bins, interpret, chunk
    )


# -------------------------------------------------------------- GLCM kernel
def _glcm_kernel(lab_ref, img_ref, lab2_ref, img2_ref, lo_ref, span_ref,
                 counts_ref, *, levels, n_dirs):
    """All directions' GLCM counts for one chunk: quantize the unshifted
    and each direction's shifted pixels against the per-object bounds,
    then contract the shared (label, q1) row one-hot against the
    concatenated per-direction column one-hots — ``_glcm_matmul_all``'s
    factored contraction with the quantization pulled on chip."""
    @pl.when(pl.program_id(0) == 0)
    def _init():
        counts_ref[:] = jnp.zeros_like(counts_ref)

    chunk = lab_ref.shape[1]
    segs_p = lo_ref.shape[1]
    rows_p, cols_p = counts_ref.shape
    lo_row = lo_ref[0, :][None, :]
    span_row = span_ref[0, :][None, :]
    seg_ids = lax.broadcasted_iota(jnp.int32, (chunk, segs_p), 1)

    def quantize(lab, v):
        sel = lab[:, None] == seg_ids
        lo_pix = jnp.sum(jnp.where(sel, lo_row, 0.0), axis=1)
        span_pix = jnp.maximum(
            jnp.sum(jnp.where(sel, span_row, 0.0), axis=1), 1e-6
        )
        q = jnp.floor((v - lo_pix) * (levels - 1) / span_pix)
        return jnp.clip(q, 0, levels - 1).astype(jnp.int32)

    lab = lab_ref[0, :]
    q = quantize(lab, img_ref[0, :])
    row = jnp.where(lab > 0, lab * levels + q, 0)
    row_ids = lax.broadcasted_iota(jnp.int32, (chunk, rows_p), 1)
    oh_r = (row[:, None] == row_ids).astype(jnp.bfloat16)
    lvl_ids = lax.broadcasted_iota(jnp.int32, (chunk, levels), 1)
    cols = []
    for d in range(n_dirs):  # static unroll
        lab2 = lab2_ref[d, 0, :]
        q2 = quantize(lab2, img2_ref[d, 0, :])
        valid = (lab > 0) & (lab2 == lab)
        col = jnp.where(valid, q2, 0)
        cols.append(
            (col[:, None] == lvl_ids).astype(jnp.bfloat16)
            * valid[:, None].astype(jnp.bfloat16)
        )
    if cols_p > n_dirs * levels:
        cols.append(
            jnp.zeros((chunk, cols_p - n_dirs * levels), jnp.bfloat16)
        )
    oh_c = jnp.concatenate(cols, axis=1)  # (chunk, cols_p)
    counts_ref[:] += lax.dot_general(
        oh_r, oh_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_objects", "levels", "offsets", "interpret", "chunk"),
)
def _glcm_call(labels, img, lo_full, span_full, max_objects, levels,
               offsets, interpret, chunk):
    segs = capacity_segments(max_objects)
    segs_p = _pad_lane(segs)
    k = len(offsets)
    rows_p = _pad_lane(segs * levels)
    cols_p = _pad_lane(k * levels)
    lab = _chunked(labels.reshape(-1), chunk)
    vals = _chunked(img.reshape(-1), chunk)
    lab2 = jnp.stack([
        _chunked(shift_with_fill(labels, -dy, -dx, 0).reshape(-1), chunk)
        for dy, dx in offsets
    ])
    img2 = jnp.stack([
        _chunked(shift_with_fill(img, -dy, -dx, 0.0).reshape(-1), chunk)
        for dy, dx in offsets
    ])
    lo_p = jnp.concatenate(
        [lo_full, jnp.zeros((segs_p - segs,), jnp.float32)]
    )[None, :]
    span_p = jnp.concatenate(
        [span_full, jnp.ones((segs_p - segs,), jnp.float32)]
    )[None, :]
    n_chunks = lab.shape[0]
    counts = pl.pallas_call(
        functools.partial(_glcm_kernel, levels=levels, n_dirs=k),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((k, 1, chunk), lambda i: (0, i, 0)),
            pl.BlockSpec((k, 1, chunk), lambda i: (0, i, 0)),
            pl.BlockSpec((1, segs_p), lambda i: (0, 0)),
            pl.BlockSpec((1, segs_p), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows_p, cols_p), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, cols_p), jnp.float32),
        interpret=interpret,
    )(lab, vals, lab2, img2, lo_p, span_p)
    out = []
    for d in range(k):
        glcm = counts[: segs * levels, d * levels : (d + 1) * levels]
        glcm = glcm.reshape(segs, levels, levels)[1:]
        out.append(glcm + jnp.swapaxes(glcm, 1, 2))
    return out


def glcm_all(
    labels: jax.Array,
    intensity: jax.Array,
    max_objects: int,
    levels: int,
    offsets: list[tuple[int, int]],
    bounds: tuple[jax.Array, jax.Array],
    *,
    interpret: "bool | None" = None,
    chunk: "int | None" = None,
) -> list[jax.Array]:
    """All directions' symmetrized per-object GLCMs
    (``(max_objects, levels, levels)`` each) in one fused pass —
    quantization included.  ``bounds`` is the raw per-object min/max of
    ``intensity`` (the fused stats kernel supplies it).  The chunk is
    clamped to :data:`GLCM_CHUNK_MAX`: the (chunk, segments×levels) row
    one-hot dominates the kernel's VMEM budget (DESIGN.md §22)."""
    interpret, chunk = _resolve(interpret, chunk)
    chunk = min(chunk, GLCM_CHUNK_MAX)
    labels = jnp.asarray(labels, jnp.int32)
    img = jnp.asarray(intensity, jnp.float32)
    lo_full, span_full = _masked_bounds(bounds)
    return _glcm_call(
        labels, img, lo_full, span_full, max_objects, levels,
        tuple(tuple(o) for o in offsets), interpret, chunk,
    )


# ------------------------------------------------------------ VMEM budgeting
def vmem_bytes_estimate(
    capacity: int,
    *,
    strategy: str = "fused",
    n_channels: int = 7,
    bins: int = 256,
    levels: int = 32,
    n_directions: int = 4,
    chunk: "int | None" = None,
) -> int:
    """Coarse on-chip working-set estimate (bytes) for one measure pass
    at ``capacity`` — the number bench sweep rows record so a rung's
    VMEM pressure is readable next to its throughput.  For ``"fused"``
    it is the worst kernel's resident bytes (inputs + one-hots +
    accumulator, per DESIGN.md §22's budget table); for the unfused
    strategies, the dominant chunked one-hot / accumulator operand of
    the XLA path (a bound on what XLA must keep live per chunk
    iteration, not a Pallas budget)."""
    segs = capacity_segments(capacity)
    segs_p = _pad_lane(segs)
    if chunk is None:
        chunk = fused_chunk()
    if strategy == "fused":
        gchunk = min(chunk, GLCM_CHUNK_MAX)
        stats = (
            chunk * (1 + n_channels) * 4      # label + channel blocks
            + chunk * segs_p * 4              # shared one-hot / mask
            + 3 * n_channels * segs_p * 4     # sum/min/max accumulators
        )
        hist = (
            chunk * 2 * 4                     # label + value blocks
            + chunk * segs_p * 4              # label one-hot
            + chunk * _pad_lane(bins) * 2     # bucket one-hot (bf16)
            + segs_p * _pad_lane(bins) * 4    # counts accumulator
        )
        glcm = (
            gchunk * 2 * (1 + n_directions) * 4       # shifted pixel blocks
            + gchunk * _pad_lane(segs * levels) * 2   # row one-hot (bf16)
            + gchunk * _pad_lane(n_directions * levels) * 2
            + _pad_lane(segs * levels) * _pad_lane(n_directions * levels) * 4
        )
        return max(stats, hist, glcm)
    if strategy == "onehot":
        # grouped_sums' (chunk, segs) f32 one-hot vs the GLCM bf16 pair
        from tmlibrary_tpu.ops.measure import _GLCM_CHUNK, _SUM_CHUNK

        return max(
            _SUM_CHUNK * segs * 4,
            _GLCM_CHUNK * (segs * levels + n_directions * levels) * 2,
        )
    # sort/scatter: flat operands plus the largest segmented accumulator
    # (the (segs*levels*levels) GLCM cells); no chunked one-hots
    return segs * levels * levels * 4 + segs * bins * 4
