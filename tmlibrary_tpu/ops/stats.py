"""Online illumination statistics (corilla's numeric core).

Reference parity: ``tmlib/workflow/corilla/stats.py`` ``OnlineStatistics`` —
Welford per-pixel mean/variance over all sites of a channel, computed in the
log10 domain, plus intensity percentiles; results feed
``ChannelImage.correct`` (SURVEY.md §4.4).

TPU design (BASELINE north star): the per-site update loop becomes
``lax.scan`` over the site axis on each shard; shards combine with the
parallel-variance (Chan et al.) merge — deterministic fold in device order,
because floating-point Welford merging is order-sensitive (SURVEY.md §8 hard
part #2).  Percentiles are EXACT for uint16 data: a 65536-bin histogram is
accumulated alongside and inverted at finalize time.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

HIST_BINS = 65536  # exact for uint16 pixel data


class WelfordState(NamedTuple):
    """Per-pixel running statistics + global intensity histogram.

    ``mean``/``m2`` track the log-domain values SHIFTED by ``offset`` (the
    first sample seen, captured per pixel): with an fp32 carry, the raw
    running mean sits at ~4.8 (log10 of uint16-range data) where eps is
    ~5e-7, and low-contrast channels' per-sample deltas vanish below it —
    the variance of a nearly-flat channel collapses to zero.  Shifted
    deltas are ~N(0, sigma) and keep full relative precision (SURVEY.md §8
    hard part #2).  The physical mean is ``offset + mean`` (finalize).
    """

    n: jax.Array  # scalar float32 — number of sites seen
    mean: jax.Array  # (H, W) float32 — running mean MINUS offset (log domain)
    m2: jax.Array  # (H, W) float32 — running sum of squared deviations
    offset: jax.Array  # (H, W) float32 — per-pixel shift (first sample)
    hist: jax.Array  # (HIST_BINS,) float32 — raw-intensity histogram


def welford_init(shape: tuple[int, int]) -> WelfordState:
    return WelfordState(
        n=jnp.zeros((), jnp.float32),
        mean=jnp.zeros(shape, jnp.float32),
        m2=jnp.zeros(shape, jnp.float32),
        offset=jnp.zeros(shape, jnp.float32),
        hist=jnp.zeros((HIST_BINS,), jnp.float32),
    )


def welford_update(state: WelfordState, raw: jax.Array) -> WelfordState:
    """Fold one site (raw uint16-range image) into the statistics.

    The mean/variance track ``log10(1 + raw)`` (the correction domain);
    the histogram tracks raw intensities (the percentile domain) — same
    split as the reference, which keeps separate stats and percentile
    accumulators.
    """
    raw_f = jnp.asarray(raw, jnp.float32)
    x = jnp.log10(1.0 + raw_f)
    # first sample becomes the per-pixel shift (see WelfordState docstring)
    offset = jnp.where(state.n == 0, x, state.offset)
    xs = x - offset
    n = state.n + 1.0
    delta = xs - state.mean
    mean = state.mean + delta / n
    m2 = state.m2 + delta * (xs - mean)
    idx = jnp.clip(raw_f, 0, HIST_BINS - 1).astype(jnp.int32)
    # 65536-bin exact histogram: a scatter-add serializes on TPU, so the
    # bin index is factored into (hi, lo) digits and counted by one small
    # matmul per chunk (ops.histogram) — MXU instead of serialized scatter.
    # On CPU the scatter is pinned EXPLICITLY: this update runs inside
    # ``lax.scan``, where auto's native host callback would fire once per
    # scan step with no batching to amortize it (measured ~10% slower
    # than the scatter on the corilla bench).
    from tmlibrary_tpu.ops.histogram import histogram_fixed_bins

    method = "scatter" if jax.default_backend() == "cpu" else "matmul"
    hist = state.hist + histogram_fixed_bins(idx, HIST_BINS, method=method)
    return WelfordState(n=n, mean=mean, m2=m2, offset=offset, hist=hist)


def welford_scan(stack: jax.Array, init: WelfordState | None = None) -> WelfordState:
    """``lax.scan`` the update over a (B, H, W) site stack."""
    stack = jnp.asarray(stack)
    if init is None:
        init = welford_init(stack.shape[1:])

    def step(state, x):
        return welford_update(state, x), None

    out, _ = lax.scan(step, init, stack)
    return out


def welford_merge(a: WelfordState, b: WelfordState) -> WelfordState:
    """Chan et al. parallel combination of two disjoint-sample states.

    The shards carry different per-pixel offsets (each captured its own
    first sample), so ``b`` is re-expressed in the surviving frame before
    the combination; m2 is shift-invariant.  The general formula is
    already exact when either side is empty: the surviving offset makes
    the frame conversion a no-op for the non-empty side, and b.n/n is
    exactly 0.0 or 1.0."""
    n = a.n + b.n
    safe_n = jnp.maximum(n, 1.0)
    offset = jnp.where(a.n > 0, a.offset, b.offset)
    b_mean = b.mean + (b.offset - offset)
    delta = b_mean - a.mean
    mean = a.mean + delta * (b.n / safe_n)
    m2 = a.m2 + b.m2 + delta * delta * (a.n * b.n / safe_n)
    return WelfordState(
        n=n, mean=mean, m2=m2, offset=offset, hist=a.hist + b.hist
    )


def welford_finalize(
    state: WelfordState, percentile_qs: tuple[float, ...] = (0.1, 1.0, 50.0, 99.0, 99.9)
) -> dict[str, jax.Array]:
    """Extract mean/std fields (log domain) and exact raw-intensity
    percentiles (inverted from the histogram)."""
    n = jnp.maximum(state.n, 1.0)
    var = state.m2 / n  # population variance, matching np.std(ddof=0)
    cum = jnp.cumsum(state.hist)
    total = jnp.maximum(cum[-1], 1.0)
    qs = jnp.asarray(percentile_qs, jnp.float32) / 100.0
    # smallest intensity with cumulative count >= q * total
    targets = qs * total
    values = jnp.searchsorted(cum, targets, side="left").astype(jnp.float32)
    return {
        "mean_log": state.offset + state.mean,
        "std_log": jnp.sqrt(jnp.maximum(var, 0.0)),
        "var_log": var,
        "n": state.n,
        "percentile_keys": jnp.asarray(percentile_qs, jnp.float32),
        "percentile_values": jnp.clip(values, 0, HIST_BINS - 1),
        "hist": state.hist,
    }
