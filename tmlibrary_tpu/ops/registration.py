"""Cycle-to-cycle image registration.

Reference parity: ``tmlib/workflow/align/registration.py`` — per-site shift
between acquisition cycles (the reference registers each cycle's site
against the reference cycle and stores ``SiteShift`` rows plus the
``SiteIntersection`` crop window).

TPU design: FFT phase correlation in ``jnp.fft`` (XLA-native), batched over
sites with ``vmap``.  Subpixel refinement is unnecessary for the reference's
integer-shift semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def phase_correlation(
    reference: jax.Array, target: jax.Array, upsample_hint: None = None
) -> tuple[jax.Array, jax.Array]:
    """Integer (dy, dx) such that rolling ``target`` by (dy, dx) aligns it
    with ``reference`` (i.e. ``reference[y, x] ≈ target[y - dy, x - dx]``).

    Classic cross-power-spectrum method; shifts are returned in the
    signed range [-H/2, H/2) / [-W/2, W/2).
    """
    a = jnp.asarray(reference, jnp.float32)
    b = jnp.asarray(target, jnp.float32)
    fa = jnp.fft.rfft2(a)
    fb = jnp.fft.rfft2(b)
    cross = fa * jnp.conj(fb)
    denom = jnp.maximum(jnp.abs(cross), 1e-12)
    corr = jnp.fft.irfft2(cross / denom, s=a.shape)
    idx = jnp.argmax(corr)
    h, w = a.shape
    dy = idx // w
    dx = idx % w
    dy = jnp.where(dy > h // 2, dy - h, dy).astype(jnp.int32)
    dx = jnp.where(dx > w // 2, dx - w, dx).astype(jnp.int32)
    return dy, dx


def phase_correlation_quality(
    reference: jax.Array, target: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(dy, dx, quality): quality is the normalized correlation-surface
    peak in [0, 1] — 1.0 for a pure circular shift of identical content,
    near 1/sqrt(H*W) for unrelated images.  A confidence the reference's
    integer-shift registration lacks; the align step uses it to zero out
    unreliable sites (empty wells, debris)."""
    a = jnp.asarray(reference, jnp.float32)
    b = jnp.asarray(target, jnp.float32)
    fa = jnp.fft.rfft2(a)
    fb = jnp.fft.rfft2(b)
    cross = fa * jnp.conj(fb)
    denom = jnp.maximum(jnp.abs(cross), 1e-12)
    corr = jnp.fft.irfft2(cross / denom, s=a.shape)
    idx = jnp.argmax(corr)
    h, w = a.shape
    dy = idx // w
    dx = idx % w
    quality = jnp.clip(corr.reshape(-1)[idx], 0.0, 1.0)
    dy = jnp.where(dy > h // 2, dy - h, dy).astype(jnp.int32)
    dx = jnp.where(dx > w // 2, dx - w, dx).astype(jnp.int32)
    return dy, dx, quality


def phase_correlation_subpixel(
    reference: jax.Array,
    target: jax.Array,
    upsample: int = 10,
) -> tuple[jax.Array, jax.Array]:
    """(dy, dx) float32 with 1/``upsample`` pixel resolution.

    Beyond the reference's integer-shift registration: the correlation
    peak is refined by evaluating the cross-power inverse DFT on an
    upsampled grid around the integer peak via two small matrix products
    (Guizar-Sicairos matrix-multiply DFT) — MXU-friendly, no giant
    zero-padded FFT.  Deterministic, jit/vmap-safe.
    """
    a = jnp.asarray(reference, jnp.float32)
    b = jnp.asarray(target, jnp.float32)
    h, w = a.shape
    fa = jnp.fft.rfft2(a)
    fb = jnp.fft.rfft2(b)
    cross_r = fa * jnp.conj(fb)
    cross = jnp.fft.fft2(a) * jnp.conj(jnp.fft.fft2(b))
    cross = cross / jnp.maximum(jnp.abs(cross), 1e-12)
    corr = jnp.fft.irfft2(cross_r / jnp.maximum(jnp.abs(cross_r), 1e-12), s=a.shape)
    idx = jnp.argmax(corr)
    dy0 = idx // w
    dx0 = idx % w
    dy0 = jnp.where(dy0 > h // 2, dy0 - h, dy0).astype(jnp.float32)
    dx0 = jnp.where(dx0 > w // 2, dx0 - w, dx0).astype(jnp.float32)

    # 1.5-pixel neighborhood around the integer peak, upsampled
    n = int(3 * upsample)
    offsets = (jnp.arange(n, dtype=jnp.float32) - n / 2.0) / upsample
    fy = jnp.fft.fftfreq(h).astype(jnp.float32)  # cycles/pixel
    fx = jnp.fft.fftfreq(w).astype(jnp.float32)
    # E_y[k, m] = exp(2i pi fy[m] (dy0 + offsets[k])) etc.
    ey = jnp.exp(
        2j * jnp.pi * (dy0 + offsets)[:, None] * fy[None, :]
    )  # (n, H)
    ex = jnp.exp(
        2j * jnp.pi * (dx0 + offsets)[:, None] * fx[None, :]
    )  # (n, W)
    # inverse-DFT evaluation: corr(u, v) = Re Σ C[h,w] e^{2iπ(fy u + fx v)}
    local = jnp.real(jnp.einsum("kh,hw,lw->kl", ey, cross, ex))
    pk = jnp.argmax(local)
    dy = dy0 + offsets[pk // n]
    dx = dx0 + offsets[pk % n]
    return dy, dx


@functools.lru_cache(maxsize=1)
def _batch_pc_jit():
    # shared jit wrappers: per-call ``jax.jit(vmap(...))`` creates a fresh
    # cache and re-traces every batch shape on every align run
    def one(a, b):
        dy, dx = phase_correlation(a, b)
        return jnp.stack([dy, dx])

    return jax.jit(jax.vmap(one))


@functools.lru_cache(maxsize=1)
def _batch_pcq_jit():
    def one(a, b):
        dy, dx, q = phase_correlation_quality(a, b)
        return jnp.stack([dy, dx]), q

    return jax.jit(jax.vmap(one))


def batch_phase_correlation(
    reference_stack: jax.Array, target_stack: jax.Array
) -> jax.Array:
    """vmap over the site axis → (B, 2) int32 shifts."""
    return _batch_pc_jit()(reference_stack, target_stack)


def batch_phase_correlation_quality(
    reference_stack: jax.Array, target_stack: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """vmap over the site axis → ((B, 2) int32 shifts, (B,) quality)."""
    return _batch_pcq_jit()(reference_stack, target_stack)


def intersection_window(all_shifts: jax.Array) -> dict[str, int]:
    """Crop window covering the overlap of all cycles at all sites
    (reference ``SiteIntersection``).

    ``all_shifts`` are the stored *corrections* (the roll
    ``shift_image`` applies at analysis time, i.e. the negated drift):
    rolling DOWN by a positive dy exposes invalid rows at the TOP, so
    the top margin absorbs the largest positive dy, the bottom margin
    the largest negative dy, and likewise left/right for dx.

    ``all_shifts``: (N, 2) stacked (dy, dx) over every cycle and site
    (host-side; returns Python ints for static crop shapes).
    """
    import numpy as np

    s = np.asarray(all_shifts)
    if s.size == 0:
        return {"top": 0, "bottom": 0, "left": 0, "right": 0}
    return {
        "top": int(np.clip(s[:, 0].max(), 0, None)),
        "bottom": int(np.clip(-s[:, 0].min(), 0, None)),
        "left": int(np.clip(s[:, 1].max(), 0, None)),
        "right": int(np.clip(-s[:, 1].min(), 0, None)),
    }
