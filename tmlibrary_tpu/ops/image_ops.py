"""Core per-image pixel operations.

Reference parity: methods of ``tmlib.image.ChannelImage`` —
``correct`` (illumination), ``align`` (shift+crop), ``clip``, ``scale``,
``extract``/``insert``, ``join``, ``pad`` (``tmlib/image.py``).

All functions here are pure ``jnp`` element-wise/window ops on a single 2-D
image so they fuse into one XLA program under ``jit`` and batch with ``vmap``
over the site axis.  Static shapes only: crops/windows take Python-int sizes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

UINT16_MAX = 65535.0


# --------------------------------------------------------------- illumination
def correct_illumination(
    img: jax.Array,
    mean_log: jax.Array,
    std_log: jax.Array,
) -> jax.Array:
    """Apply illumination correction in the log10 domain.

    The reference's corilla statistics are per-pixel mean and std images over
    all sites of a channel, applied in log-space
    (``tmlib/image.py`` ``ChannelImage.correct`` +
    ``tmlib/workflow/corilla/stats.py`` ``OnlineStatistics``): each pixel's
    log-intensity is z-scored against its per-pixel illumination field, then
    re-expressed against the global (field-average) scale so corrected images
    across the field of view are comparable.

    corrected = 10 ** ( (log10(1+img) - mean_log) / std_log * mean(std_log)
                        + mean(mean_log) ) - 1
    """
    img_f = jnp.asarray(img, jnp.float32)
    log_img = jnp.log10(1.0 + img_f)
    std_safe = jnp.where(std_log > 1e-6, std_log, 1.0)
    z = (log_img - mean_log) / std_safe
    corrected_log = z * jnp.mean(std_log) + jnp.mean(mean_log)
    corrected = jnp.power(10.0, corrected_log) - 1.0
    return jnp.clip(corrected, 0.0, UINT16_MAX)


# -------------------------------------------------------------------- aligned
def shift_image(img: jax.Array, dy: jax.Array, dx: jax.Array) -> jax.Array:
    """Translate by integer (dy, dx), zero-filling exposed borders.

    Reference: ``ChannelImage.align`` / ``ShiftedImage`` — the registration
    step stores per-site integer shifts; alignment rolls the image and blanks
    wrapped-in pixels.  ``dy``/``dx`` may be traced values (same compiled
    program serves every site).
    """
    h, w = img.shape
    rolled = jnp.roll(img, shift=(dy, dx), axis=(0, 1))
    rows = jnp.arange(h)[:, None]
    cols = jnp.arange(w)[None, :]
    valid_rows = jnp.where(dy >= 0, rows >= dy, rows < h + dy)
    valid_cols = jnp.where(dx >= 0, cols >= dx, cols < w + dx)
    return jnp.where(valid_rows & valid_cols, rolled, 0)


def crop_window(img: jax.Array, top: int, bottom: int, left: int, right: int) -> jax.Array:
    """Crop the inter-cycle intersection window (static offsets).

    Reference: ``SiteIntersection`` — after alignment every cycle's images
    are cropped to the common overlapping region.
    """
    h, w = img.shape
    return img[top : h - bottom, left : w - right]


def align(
    img: jax.Array,
    dy: jax.Array,
    dx: jax.Array,
    window: tuple[int, int, int, int] | None = None,
) -> jax.Array:
    """Shift then (optionally) crop: the full reference ``align`` semantic."""
    out = shift_image(img, dy, dx)
    if window is not None:
        out = crop_window(out, *window)
    return out


# --------------------------------------------------------------------- scale
def clip_values(img: jax.Array, lower: jax.Array, upper: jax.Array) -> jax.Array:
    """Clip to [lower, upper] (reference ``ChannelImage.clip`` with
    percentile values computed by corilla)."""
    return jnp.clip(img, lower, upper)


def rescale(img: jax.Array, lower: jax.Array, upper: jax.Array) -> jax.Array:
    """Linear stretch of [lower, upper] to [0, 1] float32
    (reference ``ChannelImage.scale`` rescales to uint8 for tiling;
    we keep float on device, quantizing only at PNG-encode time)."""
    img_f = jnp.asarray(img, jnp.float32)
    span = jnp.maximum(upper - lower, 1e-6)
    return jnp.clip((img_f - lower) / span, 0.0, 1.0)


# ----------------------------------------------------------- extract / insert
def extract(img: jax.Array, y: int, x: int, height: int, width: int) -> jax.Array:
    """Static crop (reference ``Image.extract``)."""
    return jax.lax.dynamic_slice(img, (y, x), (height, width))


def insert(img: jax.Array, patch: jax.Array, y: int, x: int) -> jax.Array:
    """Insert ``patch`` at (y, x) (reference ``Image.insert``)."""
    return jax.lax.dynamic_update_slice(img, patch.astype(img.dtype), (y, x))


def pad(img: jax.Array, top: int, bottom: int, left: int, right: int, value=0) -> jax.Array:
    """Constant-pad (reference ``Image.pad_with_background``)."""
    return jnp.pad(img, ((top, bottom), (left, right)), constant_values=value)


def join_grid(tiles: jax.Array, grid_rows: int, grid_cols: int) -> jax.Array:
    """Stitch a ``(rows*cols, H, W)`` stack into one mosaic (reference
    ``Image.join`` used by illuminati's level-0 stitching).  Tile order is
    row-major."""
    n, h, w = tiles.shape
    assert n == grid_rows * grid_cols, (n, grid_rows, grid_cols)
    return (
        tiles.reshape(grid_rows, grid_cols, h, w)
        .transpose(0, 2, 1, 3)
        .reshape(grid_rows * h, grid_cols * w)
    )


def make_batch_prep(stats=None, apply_shift: bool = False,
                    window: tuple[int, int, int, int] | None = None):
    """One jitted, vmapped site-preprocessing function: optional
    illumination correction (corilla ``stats`` container), optional
    per-site shift, optional intersection crop.

    The single implementation behind the illuminati mosaic prep and the
    image exporter (jterator's multi-channel preprocess composes the same
    ops per channel inside its fused program)."""
    import jax

    def prep(stack, shifts):
        def one(img, shift):
            out = jnp.asarray(img, jnp.float32)
            if stats is not None:
                out = correct_illumination(out, stats.mean_log, stats.std_log)
            if apply_shift:
                out = align(out, shift[0], shift[1], window)
            return out

        return jax.vmap(one)(stack, shifts)

    return jax.jit(prep)
