"""Blob (spot) detection via Laplacian-of-Gaussian.

Reference parity: ``jtmodules/detect_blobs.py`` / ``jtlib.segmentation.
detect_blobs`` — LoG spot detection for punctate structures (vesicles,
speckles, FISH dots), returning segmented blob regions and their seed
centers.

TPU design: the scale-normalized LoG response is two separable Gaussian
passes plus a 5-point Laplacian (all ``lax.conv_general_dilated`` on the
VPU/MXU); centers are local maxima found with a max-pool comparison
(``lax.reduce_window``); regions grow from the thresholded response via
the shared connected-components labeling.  All shapes static; ``vmap``-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tmlibrary_tpu.ops.label import clip_label_count, connected_components
from tmlibrary_tpu.ops.smooth import gaussian_smooth


def log_response(img: jax.Array, sigma: float) -> jax.Array:
    """Scale-normalized negative LoG response (bright blobs → positive):
    ``-sigma^2 * Laplacian(Gaussian(img))`` — matching
    ``scipy.ndimage.gaussian_laplace`` up to the sign/normalization used
    by blob detectors."""
    sm = gaussian_smooth(jnp.asarray(img, jnp.float32), sigma)
    padded = jnp.pad(sm, ((1, 1), (1, 1)), mode="symmetric")
    h, w = sm.shape
    lap = (
        lax.dynamic_slice(padded, (0, 1), (h, w))
        + lax.dynamic_slice(padded, (2, 1), (h, w))
        + lax.dynamic_slice(padded, (1, 0), (h, w))
        + lax.dynamic_slice(padded, (1, 2), (h, w))
        - 4.0 * sm
    )
    return -(float(sigma) ** 2) * lap


def local_maxima(response: jax.Array, min_distance: int = 3) -> jax.Array:
    """Boolean map of strict local maxima within a
    ``(2*min_distance+1)``-square neighborhood (ties broken toward the
    first pixel in scan order, matching peak_local_max's exclusion)."""
    size = 2 * int(min_distance) + 1
    neigh_max = lax.reduce_window(
        response,
        -jnp.inf,
        lax.max,
        window_dimensions=(size, size),
        window_strides=(1, 1),
        padding="SAME",
    )
    is_max = response >= neigh_max
    # break plateau ties: keep the scan-order-first pixel of each plateau
    h, w = response.shape
    linear = jnp.arange(h * w, dtype=jnp.float32).reshape(h, w)
    tie_break = lax.reduce_window(
        jnp.where(is_max, -linear, -jnp.inf),
        -jnp.inf,
        lax.max,
        window_dimensions=(size, size),
        window_strides=(1, 1),
        padding="SAME",
    )
    return is_max & (jnp.abs(tie_break) == linear)


def detect_blobs(
    img: jax.Array,
    sigmas: tuple[float, ...] = (1.5, 2.5, 4.0),
    threshold: float = 10.0,
    min_distance: int = 3,
    max_objects: int = 256,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-scale LoG blob detection.

    Returns ``(blobs, centers, count)``: int32 label image of blob
    regions (thresholded max-scale LoG response, connected-components
    labeled in scipy scan order), an int32 map with the blob label at
    each detected center (0 elsewhere), and the scalar blob count.
    """
    img = jnp.asarray(img, jnp.float32)
    response = log_response(img, sigmas[0])
    for s in sigmas[1:]:
        response = jnp.maximum(response, log_response(img, s))
    mask = response > threshold
    labels, count = connected_components(mask, connectivity=8)
    labels = clip_label_count(labels, max_objects)
    peaks = local_maxima(response, min_distance) & mask
    centers = jnp.where(peaks, labels, 0)
    return labels, centers, jnp.minimum(count, max_objects)
