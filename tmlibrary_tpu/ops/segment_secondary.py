"""Secondary segmentation: grow cell objects outward from primary seeds.

Reference parity: ``jtmodules/segment_secondary.py`` — CellProfiler-style
``propagate``/watershed from primary-object seeds (nuclei) constrained to a
cell mask, keeping the **same label id** as the seed so primary and
secondary objects correspond 1:1.

TPU design (SURVEY.md §8 hard part #1b): level-ordered iterative flooding.
Intensity is bucketed into ``n_levels`` descending levels; at each level,
seed labels expand (8-neighbor max-label adoption, deterministic tie-break)
into still-unlabeled mask pixels whose intensity reaches that level, to
convergence (``lax.while_loop``), before dimmer pixels are admitted.  This
approximates priority-queue watershed flooding with compiler-friendly
control flow: O(levels x diameter) dense steps instead of a heap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tmlibrary_tpu.ops.label import _neighbor_shifts, _shift_with_fill


def _adopt_step(labels: jax.Array, allowed: jax.Array, connectivity: int = 8) -> jax.Array:
    """Unlabeled allowed pixels adopt the max label among their neighbors."""
    shifts = _neighbor_shifts(connectivity)
    neigh_max = jnp.zeros_like(labels)
    for dy, dx in shifts:
        neigh_max = jnp.maximum(neigh_max, _shift_with_fill(labels, dy, dx, 0))
    return jnp.where((labels == 0) & allowed, neigh_max, labels)


def propagate_labels(
    labels: jax.Array, allowed: jax.Array, connectivity: int = 8
) -> jax.Array:
    """Expand labels into ``allowed`` until convergence."""
    labels = jnp.asarray(labels, jnp.int32)
    allowed = jnp.asarray(allowed, bool)

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        lab, _ = state
        new = _adopt_step(lab, allowed, connectivity)
        return new, jnp.any(new != lab)

    out, _ = lax.while_loop(cond, body, (labels, jnp.bool_(True)))
    return out


def expand_labels(
    labels: jax.Array, iterations: int = 1, connectivity: int = 8
) -> jax.Array:
    """Morphologically expand every object by ``iterations`` pixels
    (reference ``jtmodules/expand_or_shrink.py``).  Ties between competing
    objects resolve to the larger label id (deterministic)."""
    lab = jnp.asarray(labels, jnp.int32)
    allowed = jnp.ones(lab.shape, bool)
    for _ in range(iterations):
        lab = _adopt_step(lab, allowed, connectivity)
    return lab


def watershed_from_seeds(
    intensity: jax.Array,
    seeds: jax.Array,
    mask: jax.Array,
    n_levels: int = 32,
    connectivity: int = 8,
    method: str = "auto",
    chunk: "int | None" = None,
) -> jax.Array:
    """Level-ordered flooding of ``seeds`` through ``mask``.

    Brighter mask pixels are claimed before dimmer ones, so region borders
    fall along intensity valleys — the watershed behavior the reference gets
    from CellProfiler's ``propagate``.  Seed pixels always keep their label.
    Returns int32 labels covering ``mask`` wherever a seed can reach it.

    ``method="pallas"`` runs the whole level loop in VMEM
    (:func:`~tmlibrary_tpu.ops.pallas_kernels.watershed_flood`);
    ``"native"`` calls the C++ frontier flood (``tm_watershed_levels``)
    via ``jax.pure_callback`` — the fast path on the CPU backend, where
    per-level ``lax.while_loop`` convergence is pathological.
    ``"auto"`` resolution order (pinned): native on cpu when available →
    pallas on TPU per ``pallas_kernels.pallas_enabled("watershed")`` (the
    measured per-kernel shootout — on v5e the XLA level loop edged out
    the VMEM flood, so auto stays xla there) → xla.  Identical
    schedule and tie-breaking all three ways (the native path receives
    the level thresholds computed by the same jitted expression, so band
    membership is decided by exact float comparisons).
    """
    if method == "auto":
        from tmlibrary_tpu import native

        if native.cpu_native_enabled():
            method = "native"
        else:
            from tmlibrary_tpu.ops.pallas_kernels import pallas_enabled

            method = "pallas" if pallas_enabled("watershed") else "xla"
    if method == "pallas":
        from tmlibrary_tpu.ops.pallas_kernels import watershed_flood

        return watershed_flood(
            intensity, seeds, mask, n_levels=n_levels, connectivity=connectivity,
            interpret=jax.default_backend() == "cpu",
            chunk=chunk,
        )
    intensity = jnp.asarray(intensity, jnp.float32)
    seeds = jnp.asarray(seeds, jnp.int32)
    mask = jnp.asarray(mask, bool) | (seeds > 0)

    lo = jnp.min(jnp.where(mask, intensity, jnp.inf))
    hi = jnp.max(jnp.where(mask, intensity, -jnp.inf))
    span = jnp.maximum(hi - lo, 1e-6)

    if method == "native":
        import numpy as np

        from tmlibrary_tpu import native

        # the SAME expression level_body uses (left-assoc: (span*(i+1))/n),
        # so the host kernel compares against bit-identical thresholds
        i = jnp.arange(n_levels, dtype=jnp.int32)
        levels = hi - span * (i + 1) / n_levels
        return jax.pure_callback(
            native.batch_sites(2, 2, 2, 1)(
                lambda im, sd, mk, lv: native.watershed_levels_host(
                    np.asarray(im), np.asarray(sd), np.asarray(mk),
                    np.asarray(lv), connectivity,
                )
            ),
            jax.ShapeDtypeStruct(intensity.shape, jnp.int32),
            intensity, seeds, mask, levels,
            vmap_method=native.callback_vmap_method(),
        )

    # ONE flattened while_loop instead of {fori over levels x while to
    # convergence}: the carried level index advances the sweep after the
    # current level stops producing adoptions — exactly when the nested
    # while exited — so the final labels are bit-identical.  The payoff
    # is under the site-batch vmap: a vmapped nested loop synchronizes
    # EVERY site at EVERY level (each inner while runs until the slowest
    # site converges), while the flattened loop lets each site advance
    # its own level — total trips max-of-sums instead of sum-of-maxes
    # (round-4 VERDICT next-step #1: fewer while-loop trips).
    def cond(state):
        _, li = state
        return li <= n_levels

    def body(state):
        labels, li = state
        # descending levels: li=0 admits only the brightest band; the
        # (li + 1) -> float conversion reproduces the fori_loop
        # expression bit-for-bit (int32 counter converted, then
        # span * . / n_levels in f32 — the native path's levels use the
        # same tree)
        level = hi - span * (li + 1).astype(jnp.float32) / n_levels
        # li == n_levels is the final mop-up band: any mask pixel below
        # the lowest level (numerical edge)
        allowed = mask & ((intensity >= level) | (li >= n_levels))
        new = _adopt_step(labels, allowed, connectivity)
        li = jnp.where(jnp.any(new != labels), li, li + 1)
        return new, li

    labels, _ = lax.while_loop(cond, body, (seeds, jnp.int32(0)))
    return jnp.where(mask, labels, 0)
