"""Pyramid tiling ops (illuminati).

Reference parity: ``tmlib/workflow/illuminati/api.py`` ``PyramidBuilder`` —
zoomify-style pyramid: level 0 is the corrected/aligned/stitched well
mosaic cut into 256-px tiles; each higher level is a 2x2 mean downsample of
the previous, with per-level jobs and inter-level dependencies in the
reference (SURVEY.md §4.5).

TPU design: the mosaic is one array (sharded for big plates);
``lax.reduce_window`` mean-pooling builds the level chain on device; only
PNG encoding of tiles is host-side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

TILE_SIZE = 256


def _display_dtype() -> jnp.dtype:
    """dtype for the display-only pyramid math (``LibraryConfig``
    ``compute_dtype``, default float32).

    Trade-off of opting into bfloat16 here: it halves the pyramid's HBM
    traffic, but its ~8-bit mantissa is RELATIVE to pixel value, not to
    the display window — a dim channel stretched over a narrow clip
    window (e.g. span 40 around intensity 1000, where the bf16 ulp is 8)
    will show banding in the viewer.  Fine for well-exposed channels;
    keep float32 when narrow stretches matter.  The analysis path
    (segmentation/measurement) ignores this knob entirely: it is fp32
    with HIGHEST-precision convs because bit-identical goldens gate it
    (DESIGN.md)."""
    from tmlibrary_tpu.config import cfg

    return jnp.dtype(cfg.compute_dtype)


def downsample_2x(img: jax.Array) -> jax.Array:
    """2x2 mean pooling (one pyramid level step).  Odd trailing row/col are
    edge-padded first so shape halving rounds up, matching zoomify."""
    h, w = img.shape
    ph, pw = h % 2, w % 2
    img_f = jnp.asarray(img, _display_dtype())
    if ph or pw:
        img_f = jnp.pad(img_f, ((0, ph), (0, pw)), mode="edge")
    summed = lax.reduce_window(
        img_f, jnp.asarray(0.0, img_f.dtype), lax.add,
        window_dimensions=(2, 2), window_strides=(2, 2),
        padding="VALID",
    )
    return summed / 4.0


#: module-level jit (public: parallel/halo.py shares it): a per-call
#: ``jax.jit(downsample_2x)`` would create a fresh wrapper with an empty
#: cache and re-trace every level shape on every illuminati batch
#: (measured as re-run overhead in the workflow bench); one shared
#: wrapper re-traces each level shape once per process
downsample_2x_jit = jax.jit(downsample_2x)


def pyramid_levels(mosaic: jax.Array, n_levels: int | None = None) -> list[jax.Array]:
    """Full level chain, level 0 (native) first.  ``n_levels=None`` builds
    until the image fits in a single tile."""
    levels = [jnp.asarray(mosaic, _display_dtype())]
    if n_levels is None:
        n_levels = n_pyramid_levels(*mosaic.shape)
    for _ in range(n_levels - 1):
        levels.append(downsample_2x_jit(levels[-1]))
    return levels


def n_pyramid_levels(height: int, width: int) -> int:
    """Level count ``pyramid_levels`` builds for an image of this size
    (native level + halvings until it fits one tile)."""
    n, h, w = 1, height, width
    while max(h, w) > TILE_SIZE:
        h, w = (h + 1) // 2, (w + 1) // 2
        n += 1
    return n


def cut_tiles(level: np.ndarray) -> dict[tuple[int, int], np.ndarray]:
    """Cut one level into 256-px tiles (host-side; edge tiles zero-padded to
    full size, matching the reference's fixed tile geometry).  Keys are
    (row, col) tile indices."""
    level = np.asarray(level)
    h, w = level.shape
    tiles: dict[tuple[int, int], np.ndarray] = {}
    for ty in range(0, max(h, 1), TILE_SIZE):
        for tx in range(0, max(w, 1), TILE_SIZE):
            tile = level[ty : ty + TILE_SIZE, tx : tx + TILE_SIZE]
            if tile.shape != (TILE_SIZE, TILE_SIZE):
                full = np.zeros((TILE_SIZE, TILE_SIZE), level.dtype)
                full[: tile.shape[0], : tile.shape[1]] = tile
                tile = full
            tiles[(ty // TILE_SIZE, tx // TILE_SIZE)] = tile
    return tiles


def to_uint8(level: jax.Array, lower: float, upper: float) -> jax.Array:
    """Percentile-stretch to display range (reference ``ChannelImage.scale``
    with corilla's clip percentiles)."""
    span = max(upper - lower, 1e-6)
    return jnp.clip((jnp.asarray(level, jnp.float32) - lower) / span * 255.0, 0, 255).astype(
        jnp.uint8
    )
