"""Connected-component labeling and binary morphology on TPU.

Reference parity: ``jtmodules/label.py`` (mahotas/scipy connected components),
``jtmodules/fill.py`` (binary hole filling), ``jtmodules/filter.py``
(filter objects by feature) — all native-library calls in the reference.

TPU design (SURVEY.md §8 "hard parts" #1): labeling iterates {diagonal
neighbor min-propagation, row run-scan, column run-scan} inside
``lax.while_loop`` — each pixel carries the minimum linear index seen in
its component, and the segmented associative scans (``_run_min_scan``)
move labels across entire straight runs per iteration with **no gathers**
(TPU's slow path).  Convergence is ~O(turns of the most serpentine
component): a handful of iterations for blob-like microscopy objects.
All shapes static; ``vmap``-safe.

Label order is **bit-identical to ``scipy.ndimage.label``**: the converged
label of a component is its minimum linear index (= first pixel in row-major
scan order), and compaction ranks roots by that index — exactly scipy's
assignment order.  This is the acceptance gate from BASELINE.json
("bit-identical object counts").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_BIG = jnp.iinfo(jnp.int32).max


def _neighbor_shifts(connectivity: int) -> list[tuple[int, int]]:
    if connectivity == 4:
        return [(-1, 0), (1, 0), (0, -1), (0, 1)]
    if connectivity == 8:
        return [
            (-1, -1), (-1, 0), (-1, 1),
            (0, -1), (0, 1),
            (1, -1), (1, 0), (1, 1),
        ]
    raise ValueError("connectivity must be 4 or 8")


def shift_with_fill(arr: jax.Array, dy: int, dx: int, fill) -> jax.Array:
    """``out[y, x] = arr[y + dy, x + dx]`` with ``fill`` at exposed borders
    (the neighborhood-access primitive shared by labeling, morphology and
    the GLCM ops)."""
    h, w = arr.shape
    padded = jnp.pad(arr, ((1, 1), (1, 1)), constant_values=fill)
    return lax.dynamic_slice(padded, (1 + dy, 1 + dx), (h, w))


# backward-compat private alias (internal call sites predate the rename)
_shift_with_fill = shift_with_fill


def _propagate_min(labels: jax.Array, mask: jax.Array, shifts) -> jax.Array:
    out = labels
    for dy, dx in shifts:
        neigh = _shift_with_fill(labels, dy, dx, _BIG)
        out = jnp.minimum(out, neigh)
    return jnp.where(mask, out, _BIG)


def _run_min_scan(labels: jax.Array, mask: jax.Array, axis: int) -> jax.Array:
    """Propagate the min label across contiguous foreground runs along
    ``axis`` via a segmented associative scan (both directions) — O(log N)
    depth, no gathers (TPU gathers are the slow path)."""
    # run start: previous element along the axis is background
    is_start = mask & ~_shift_with_fill(
        mask, *((-1, 0) if axis == 0 else (0, -1)), False
    )
    # background pixels are their own segment so nothing crosses them
    resets = is_start | ~mask

    def op(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, jnp.minimum(av, bv)), ar | br

    fwd, _ = lax.associative_scan(op, (labels, resets), axis=axis)
    # reverse pass: a run's first element holds the run min after the
    # forward pass only at its end; sweep back so every element gets it.
    # run end: next element along the axis is background
    is_end = mask & ~_shift_with_fill(
        mask, *((1, 0) if axis == 0 else (0, 1)), False
    )
    resets_r = is_end | ~mask
    bwd, _ = lax.associative_scan(op, (fwd, resets_r), axis=axis, reverse=True)
    return jnp.where(mask, bwd, _BIG)


def connected_components(
    mask: jax.Array, connectivity: int = 8, method: str = "auto",
    chunk: "int | None" = None,
) -> tuple[jax.Array, jax.Array]:
    """Label connected foreground components.

    Returns ``(labels, count)``: int32 label image (0 = background, 1..N in
    scipy scan order) and the scalar component count.

    ``method``: ``"xla"`` iterates {8/4-neighbor min propagation, row
    run-scan, column run-scan} to a fixed point — the run scans move labels
    across entire straight runs per iteration, so convergence is ~O(turns
    of the most serpentine component) with no per-pixel gathers.
    ``"pallas"`` runs the same fixpoint entirely in VMEM
    (:func:`~tmlibrary_tpu.ops.pallas_kernels.cc_min_propagate`) — O(1)
    HBM traffic.  ``"native"`` calls the first-party C++ union-find
    (``native/tmnative.cpp`` ``tm_cc_label``, scipy scan order) via
    ``jax.pure_callback`` — the fast path when the whole pipeline runs on
    the CPU backend, where the while-loop fixpoint is pathological.

    ``"auto"`` resolution order (pinned): native on the cpu backend when
    the library is available and ``TMX_NATIVE`` isn't 0 → pallas on TPU
    per ``pallas_kernels.pallas_enabled("cc")`` (the measured per-kernel
    shootout; on v5e the VMEM fixpoint wins ~2.1x) → xla.  All three
    produce the identical scipy-scan-order labeling.
    """
    mask = jnp.asarray(mask, bool)
    h, w = mask.shape
    if connectivity not in (4, 8):
        raise ValueError("connectivity must be 4 or 8")
    linear = jnp.arange(h * w, dtype=jnp.int32).reshape(h, w)

    if method == "auto":
        from tmlibrary_tpu import native
        from tmlibrary_tpu.ops.pallas_kernels import pallas_enabled

        if native.cpu_native_enabled():
            method = "native"
        else:
            method = "pallas" if pallas_enabled("cc") else "xla"
    if method == "native":
        import numpy as np

        from tmlibrary_tpu import native

        @native.batch_sites(2)
        def _cc_host(m):
            labels, count = native.cc_label_host(np.asarray(m), connectivity)
            return labels, np.int32(count)

        return jax.pure_callback(
            _cc_host,
            (
                jax.ShapeDtypeStruct((h, w), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
            ),
            mask,
            vmap_method=native.callback_vmap_method(),
        )
    if method == "pallas":
        from tmlibrary_tpu.ops.pallas_kernels import cc_min_propagate

        # interpret mode keeps the pallas path testable off-TPU; chunk
        # (convergence-check interval, output-invariant) defaults to the
        # committed hardware sweep inside cc_min_propagate
        labels = cc_min_propagate(
            mask, connectivity, interpret=jax.default_backend() == "cpu",
            chunk=chunk,
        )
        labels = jnp.where(mask, labels, _BIG)
    else:
        # row+col run scans fully cover 4-neighbor propagation
        shifts = [] if connectivity == 4 else [(-1, -1), (-1, 1), (1, -1), (1, 1)]
        init = jnp.where(mask, linear, _BIG)

        def cond(state):
            labels, prev_changed = state
            return prev_changed

        def body(state):
            labels, _ = state
            new = _propagate_min(labels, mask, shifts) if shifts else labels
            new = _run_min_scan(new, mask, axis=1)
            new = _run_min_scan(new, mask, axis=0)
            changed = jnp.any(new != labels)
            return new, changed

        labels, _ = lax.while_loop(cond, body, (init, jnp.bool_(True)))

    # compact to 1..N in row-major order of component roots (scipy order)
    is_root = mask & (labels == linear)
    ranks = jnp.cumsum(is_root.reshape(-1).astype(jnp.int32))
    count = ranks[-1]
    root_rank = ranks.reshape(-1)[jnp.clip(labels.reshape(-1), 0, h * w - 1)]
    out = jnp.where(mask, root_rank.reshape(h, w), 0).astype(jnp.int32)
    return out, count


def label(mask: jax.Array, connectivity: int = 8) -> jax.Array:
    """Label image only (reference ``jtmodules/label.main``)."""
    return connected_components(mask, connectivity)[0]


# ------------------------------------------------------------ binary morphology
def binary_dilate(mask: jax.Array, connectivity: int = 8, iterations: int = 1) -> jax.Array:
    mask = jnp.asarray(mask, bool)
    shifts = _neighbor_shifts(connectivity)
    for _ in range(iterations):
        out = mask
        for dy, dx in shifts:
            out = out | _shift_with_fill(mask, dy, dx, False)
        mask = out
    return mask


def binary_erode(mask: jax.Array, connectivity: int = 8, iterations: int = 1) -> jax.Array:
    mask = jnp.asarray(mask, bool)
    shifts = _neighbor_shifts(connectivity)
    for _ in range(iterations):
        out = mask
        for dy, dx in shifts:
            out = out & _shift_with_fill(mask, dy, dx, True)
        mask = out
    return mask


def fill_holes(
    mask: jax.Array, connectivity: int = 4, method: str = "auto"
) -> jax.Array:
    """Fill background holes (reference ``jtmodules/fill.main``,
    scipy ``binary_fill_holes`` semantics: background connectivity is the
    complement of the foreground's — holes are 4-connected background regions
    not reachable from the border).

    ``method="auto"`` routes to the native border-BFS
    (``tm_fill_holes``) on the cpu backend (see
    :func:`~tmlibrary_tpu.native.cpu_native_enabled`), the VMEM pallas
    flood on TPU when the committed shootout says it wins
    (``pallas_enabled("fill")``), the XLA flood otherwise.
    """
    mask = jnp.asarray(mask, bool)
    h, w = mask.shape
    if method == "auto":
        from tmlibrary_tpu import native

        if native.cpu_native_enabled():
            method = "native"
        else:
            from tmlibrary_tpu.ops.pallas_kernels import pallas_enabled

            method = "pallas" if pallas_enabled("fill") else "xla"
    if method == "pallas":
        from tmlibrary_tpu.ops.pallas_kernels import fill_holes_flood

        return fill_holes_flood(
            mask, connectivity, interpret=jax.default_backend() == "cpu"
        )
    if method == "native":
        import numpy as np

        from tmlibrary_tpu import native

        return jax.pure_callback(
            native.batch_sites(2)(
                lambda m: native.fill_holes_host(np.asarray(m), connectivity)
            ),
            jax.ShapeDtypeStruct((h, w), jnp.bool_),
            mask,
            vmap_method=native.callback_vmap_method(),
        )
    bg = ~mask
    border = jnp.zeros_like(mask).at[0, :].set(True).at[-1, :].set(True)
    border = border.at[:, 0].set(True).at[:, -1].set(True)
    seed = bg & border
    if connectivity not in (4, 8):
        raise ValueError("connectivity must be 4 or 8")

    def cond(state):
        reach, changed = state
        return changed

    # diagonal steps are only relevant at 8-connectivity; the run scans
    # below fully cover horizontal/vertical propagation
    diag = [] if connectivity == 4 else [(-1, -1), (-1, 1), (1, -1), (1, 1)]

    def body(state):
        reach, _ = state
        grown = reach
        for dy, dx in diag:
            grown = grown | _shift_with_fill(reach, dy, dx, False)
        grown = grown & bg
        # flood entire background runs at once (reuse the min run-scan:
        # 0 = reached, 1 = not; run min 0 means the whole run is reached)
        for axis in (1, 0):
            v = jnp.where(grown, 0, 1).astype(jnp.int32)
            runmin = _run_min_scan(v, bg, axis)
            grown = (runmin == 0) & bg
        return grown, jnp.any(grown != reach)

    reach, _ = lax.while_loop(cond, body, (seed, jnp.bool_(True)))
    return mask | (bg & ~reach)


# ------------------------------------------------------------------ filtering
_REDUCE_CHUNK = 1 << 16  # pixels per compare-broadcast chunk (bounds HBM)


def _chunked_pixels(flat: jax.Array) -> jax.Array:
    """Pad ``flat`` with label-0 pixels to a multiple of ``_REDUCE_CHUNK``
    and reshape to (n_chunks, chunk) so broadcast reductions stay bounded
    under the site-batch vmap (matches ``measure.grouped_sums``)."""
    pad = (-flat.shape[0]) % _REDUCE_CHUNK
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, _REDUCE_CHUNK)


def areas_by_label(
    labels: jax.Array, max_objects: int, method: str = "auto"
) -> jax.Array:
    """Pixel count per label id 1..max_objects → (max_objects,) int32.

    TPU scatter-adds serialize (the ``segment_sum`` path measured ~3x
    slower than a fused compare+reduce on v5e), so ``method="auto"``
    streams a (chunk, max_objects) equality broadcast through one int32
    sum on accelerators and keeps the scatter on CPU, where scatters are
    cheap and the broadcast is the bottleneck."""
    flat = labels.reshape(-1)
    if method == "auto":
        method = "scatter" if jax.default_backend() == "cpu" else "reduce"
    if method == "scatter":
        ones = jnp.ones_like(flat, dtype=jnp.int32)
        # segment 0 is background; drop it
        sums = jax.ops.segment_sum(ones, flat, num_segments=max_objects + 1)
        return sums[1:]
    chunks = _chunked_pixels(flat)
    ids = jnp.arange(1, max_objects + 1, dtype=flat.dtype)

    def body(i, acc):
        # padded pixels carry label 0 → match no id in 1..max_objects
        return acc + jnp.sum(
            (chunks[i][:, None] == ids).astype(jnp.int32), axis=0
        )

    init = jnp.zeros((max_objects,), jnp.int32)
    return jax.lax.fori_loop(0, chunks.shape[0], body, init)


def remap_labels(
    labels: jax.Array, mapping: jax.Array, method: str = "auto"
) -> jax.Array:
    """Apply a small per-label-id lookup table to a label image:
    ``out[p] = mapping[labels[p]]`` with ``mapping`` of shape
    ``(max_objects + 1,)`` (row 0 = background).

    The obvious ``mapping[labels]`` gather costs ~2.6x more than a one-hot
    contraction against the table on v5e (gathers from a tiny table don't
    tile onto the MXU; the indicator matmul does).  The TPU matmul casts
    f32 operands to bf16, which only represents integers ≤ 256 exactly, so
    the table is split into four bytes — bf16-exact contractions (each
    dot product has exactly one nonzero term, so accumulation order
    cannot round) recombined in int32; exact for every non-negative
    int32 mapped value.  Out-of-range label ids clamp into the table on
    both paths (explicitly — a raw jnp gather would WRAP negative ids
    Python-style while one_hot zeroes them).  ``method="auto"``: gather
    on CPU and for tables past the one-hot sweet spot (> 4096 rows,
    where the chunk×rows indicator work outgrows the gather), matmul on
    accelerators otherwise; pixel axis chunked like
    :func:`areas_by_label`."""
    mapping = jnp.asarray(mapping, jnp.int32)
    labels = jnp.clip(labels, 0, mapping.shape[0] - 1)
    if method == "auto":
        method = (
            "gather"
            if jax.default_backend() == "cpu" or mapping.shape[0] > (1 << 12)
            else "matmul"
        )
    if method == "gather":
        return mapping[labels]
    flat = labels.reshape(-1)
    n = flat.shape[0]
    chunks = _chunked_pixels(flat)
    table = jnp.stack(
        [((mapping >> s) & 0xFF).astype(jnp.float32) for s in (24, 16, 8, 0)],
        axis=-1,
    )  # (K+1, 4) byte planes, each entry ≤ 255 → bf16-exact

    def body(i, acc):
        oh = jax.nn.one_hot(chunks[i], mapping.shape[0], dtype=jnp.float32)
        parts = (oh @ table).astype(jnp.int32)  # (chunk, 4)
        vals = (
            ((parts[:, 0] * 256 + parts[:, 1]) * 256 + parts[:, 2]) * 256
            + parts[:, 3]
        )
        return acc.at[i].set(vals)

    out = jnp.zeros(chunks.shape, jnp.int32)
    out = jax.lax.fori_loop(0, chunks.shape[0], body, out)
    return out.reshape(-1)[:n].reshape(labels.shape)


def relabel_sequential(labels: jax.Array, keep: jax.Array) -> jax.Array:
    """Keep labels where ``keep[label-1]`` is True, renumbering 1..K densely
    in ascending original-label order (scipy-compatible)."""
    keep = jnp.asarray(keep, bool)
    new_ids = jnp.cumsum(keep.astype(jnp.int32))
    mapping = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.where(keep, new_ids, 0)])
    return remap_labels(labels, mapping)


def filter_by_area(
    labels: jax.Array,
    max_objects: int,
    min_area: float = 0,
    max_area: float | None = None,
) -> jax.Array:
    """Remove objects outside [min_area, max_area] (reference
    ``jtmodules/filter.main`` with the 'area' feature).

    Labels beyond ``max_objects`` are dropped first — without this,
    the relabeling gather would clamp them onto object ``max_objects``'s id,
    silently merging distinct objects.
    """
    labels = clip_label_count(labels, max_objects)
    areas = areas_by_label(labels, max_objects)
    keep = areas >= min_area
    if max_area is not None:
        keep = keep & (areas <= max_area)
    keep = keep & (areas > 0)
    return relabel_sequential(labels, keep)


def clip_label_count(labels: jax.Array, max_objects: int) -> jax.Array:
    """Zero out labels beyond ``max_objects`` (static-shape safety valve)."""
    return jnp.where(labels <= max_objects, labels, 0)


def first_pixel_by_label(
    labels: jax.Array, max_labels: int, method: str = "auto"
) -> jax.Array:
    """Min row-major linear pixel index per label id 1..max_labels;
    ``h*w`` for absent labels → (max_labels,) int32.

    Same backend split as :func:`areas_by_label`: ``segment_min`` scatter
    on CPU, fused compare+min broadcast on accelerators (~3x on v5e)."""
    flat = jnp.asarray(labels, jnp.int32).reshape(-1)
    big = jnp.int32(flat.shape[0])
    if method == "auto":
        method = "scatter" if jax.default_backend() == "cpu" else "reduce"
    if method == "scatter":
        linear = jnp.arange(flat.shape[0], dtype=jnp.int32)
        first = jax.ops.segment_min(
            linear, flat, num_segments=max_labels + 1
        )[1:]  # min linear index per label; int32-max-clamped if absent
        return jnp.minimum(first, big)
    chunks = _chunked_pixels(flat)
    ids = jnp.arange(1, max_labels + 1, dtype=jnp.int32)

    def body(i, acc):
        linear = i * _REDUCE_CHUNK + jnp.arange(_REDUCE_CHUNK, dtype=jnp.int32)
        hit = jnp.min(
            jnp.where(chunks[i][:, None] == ids, linear[:, None], big), axis=0
        )
        return jnp.minimum(acc, hit)

    init = jnp.full((max_labels,), big, jnp.int32)
    return jax.lax.fori_loop(0, chunks.shape[0], body, init)


def relabel_by_scan_order(labels: jax.Array, max_labels: int) -> jax.Array:
    """Renumber labels 1..K by each region's first pixel in row-major scan
    order — scipy's assignment order.  Watershed/declump outputs carry seed
    scan order, which deviates from the bit-identical gate
    (``scipy.ndimage.label`` semantics); one compaction pass reconciles
    them.  Absent label ids map to 0.  jit/vmap-safe, static shapes."""
    labels = jnp.asarray(labels, jnp.int32)
    h, w = labels.shape
    big = jnp.int32(h * w)
    first = first_pixel_by_label(labels, max_labels)
    order = jnp.argsort(first)  # label-1 ids sorted by first pixel
    ranks = (
        jnp.zeros((max_labels,), jnp.int32)
        .at[order]
        .set(jnp.arange(1, max_labels + 1, dtype=jnp.int32))
    )
    present = first < big
    mapping = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.where(present, ranks, 0)]
    )
    return remap_labels(jnp.clip(labels, 0, max_labels), mapping)


def filter_by_feature(
    labels: jax.Array,
    feature: str,
    max_objects: int,
    lower: float | None = None,
    upper: float | None = None,
) -> jax.Array:
    """Remove objects whose morphology feature falls outside
    ``[lower, upper]`` (reference ``jtmodules/filter.main`` — the
    reference filters on any measured feature; this covers every
    on-device morphology feature, with ``area`` staying on the cheap
    dedicated path).

    Feature names accept the bare form (``eccentricity``) or the
    exported column name (``Morphology_eccentricity``).
    """
    from tmlibrary_tpu.ops.measure import morphology_features

    if lower is None and upper is None:
        raise ValueError(
            "filter_by_feature needs at least one of lower/upper — with "
            "neither it would be a silent no-op that still renumbers labels"
        )
    labels = clip_label_count(labels, max_objects)
    name = feature if feature.startswith("Morphology_") else f"Morphology_{feature}"
    feats = morphology_features(labels, max_objects)
    if name not in feats:
        raise ValueError(
            f"filter feature '{feature}' is not an on-device morphology "
            f"feature (available: "
            f"{sorted(k.removeprefix('Morphology_') for k in feats)})"
        )
    values = feats[name]
    present = feats["Morphology_area"] > 0
    keep = present
    if lower is not None:
        keep = keep & (values >= lower)
    if upper is not None:
        keep = keep & (values <= upper)
    return relabel_sequential(labels, keep)
