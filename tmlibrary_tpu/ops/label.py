"""Connected-component labeling and binary morphology on TPU.

Reference parity: ``jtmodules/label.py`` (mahotas/scipy connected components),
``jtmodules/fill.py`` (binary hole filling), ``jtmodules/filter.py``
(filter objects by feature) — all native-library calls in the reference.

TPU design (SURVEY.md §8 "hard parts" #1): labeling is an iterative
min-label propagation with **pointer jumping** inside ``lax.while_loop`` —
each pixel carries the linear index of some pixel in its component; per
iteration every pixel takes the min over its neighborhood, then follows its
current label's label (path halving), so convergence is ~O(log diameter)
rather than O(diameter).  All shapes static; ``vmap``-safe.

Label order is **bit-identical to ``scipy.ndimage.label``**: the converged
label of a component is its minimum linear index (= first pixel in row-major
scan order), and compaction ranks roots by that index — exactly scipy's
assignment order.  This is the acceptance gate from BASELINE.json
("bit-identical object counts").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_BIG = jnp.iinfo(jnp.int32).max


def _neighbor_shifts(connectivity: int) -> list[tuple[int, int]]:
    if connectivity == 4:
        return [(-1, 0), (1, 0), (0, -1), (0, 1)]
    if connectivity == 8:
        return [
            (-1, -1), (-1, 0), (-1, 1),
            (0, -1), (0, 1),
            (1, -1), (1, 0), (1, 1),
        ]
    raise ValueError("connectivity must be 4 or 8")


def _shift_with_fill(arr: jax.Array, dy: int, dx: int, fill) -> jax.Array:
    """Shift a 2-D array by (dy, dx), filling exposed borders with ``fill``."""
    h, w = arr.shape
    padded = jnp.pad(arr, ((1, 1), (1, 1)), constant_values=fill)
    return lax.dynamic_slice(padded, (1 + dy, 1 + dx), (h, w))


def _propagate_min(labels: jax.Array, mask: jax.Array, shifts) -> jax.Array:
    out = labels
    for dy, dx in shifts:
        neigh = _shift_with_fill(labels, dy, dx, _BIG)
        out = jnp.minimum(out, neigh)
    return jnp.where(mask, out, _BIG)


def connected_components(
    mask: jax.Array, connectivity: int = 8
) -> tuple[jax.Array, jax.Array]:
    """Label connected foreground components.

    Returns ``(labels, count)``: int32 label image (0 = background, 1..N in
    scipy scan order) and the scalar component count.
    """
    mask = jnp.asarray(mask, bool)
    h, w = mask.shape
    shifts = _neighbor_shifts(connectivity)
    linear = jnp.arange(h * w, dtype=jnp.int32).reshape(h, w)
    init = jnp.where(mask, linear, _BIG)

    def cond(state):
        labels, prev_changed = state
        return prev_changed

    def body(state):
        labels, _ = state
        new = _propagate_min(labels, mask, shifts)
        # pointer jumping (path halving): follow label -> label's label.
        # Background pixels hold _BIG; gather with a clipped index and
        # re-mask so they stay _BIG.
        flat = new.reshape(-1)
        for _ in range(2):
            idx = jnp.clip(flat, 0, h * w - 1)
            flat = jnp.minimum(flat, jnp.where(flat < _BIG, flat[idx], _BIG))
        new = jnp.where(mask, flat.reshape(h, w), _BIG)
        changed = jnp.any(new != labels)
        return new, changed

    labels, _ = lax.while_loop(cond, body, (init, jnp.bool_(True)))

    # compact to 1..N in row-major order of component roots (scipy order)
    is_root = mask & (labels == linear)
    ranks = jnp.cumsum(is_root.reshape(-1).astype(jnp.int32))
    count = ranks[-1]
    root_rank = ranks.reshape(-1)[jnp.clip(labels.reshape(-1), 0, h * w - 1)]
    out = jnp.where(mask, root_rank.reshape(h, w), 0).astype(jnp.int32)
    return out, count


def label(mask: jax.Array, connectivity: int = 8) -> jax.Array:
    """Label image only (reference ``jtmodules/label.main``)."""
    return connected_components(mask, connectivity)[0]


# ------------------------------------------------------------ binary morphology
def binary_dilate(mask: jax.Array, connectivity: int = 8, iterations: int = 1) -> jax.Array:
    mask = jnp.asarray(mask, bool)
    shifts = _neighbor_shifts(connectivity)
    for _ in range(iterations):
        out = mask
        for dy, dx in shifts:
            out = out | _shift_with_fill(mask, dy, dx, False)
        mask = out
    return mask


def binary_erode(mask: jax.Array, connectivity: int = 8, iterations: int = 1) -> jax.Array:
    mask = jnp.asarray(mask, bool)
    shifts = _neighbor_shifts(connectivity)
    for _ in range(iterations):
        out = mask
        for dy, dx in shifts:
            out = out & _shift_with_fill(mask, dy, dx, True)
        mask = out
    return mask


def fill_holes(mask: jax.Array, connectivity: int = 4) -> jax.Array:
    """Fill background holes (reference ``jtmodules/fill.main``,
    scipy ``binary_fill_holes`` semantics: background connectivity is the
    complement of the foreground's — holes are 4-connected background regions
    not reachable from the border).
    """
    mask = jnp.asarray(mask, bool)
    h, w = mask.shape
    bg = ~mask
    border = jnp.zeros_like(mask).at[0, :].set(True).at[-1, :].set(True)
    border = border.at[:, 0].set(True).at[:, -1].set(True)
    seed = bg & border
    shifts = _neighbor_shifts(connectivity)

    def cond(state):
        reach, changed = state
        return changed

    def body(state):
        reach, _ = state
        grown = reach
        for dy, dx in shifts:
            grown = grown | _shift_with_fill(reach, dy, dx, False)
        grown = grown & bg
        return grown, jnp.any(grown != reach)

    reach, _ = lax.while_loop(cond, body, (seed, jnp.bool_(True)))
    return mask | (bg & ~reach)


# ------------------------------------------------------------------ filtering
def areas_by_label(labels: jax.Array, max_objects: int) -> jax.Array:
    """Pixel count per label id 1..max_objects → (max_objects,) int32."""
    flat = labels.reshape(-1)
    ones = jnp.ones_like(flat, dtype=jnp.int32)
    # segment 0 is background; drop it
    sums = jax.ops.segment_sum(ones, flat, num_segments=max_objects + 1)
    return sums[1:]


def relabel_sequential(labels: jax.Array, keep: jax.Array) -> jax.Array:
    """Keep labels where ``keep[label-1]`` is True, renumbering 1..K densely
    in ascending original-label order (scipy-compatible)."""
    keep = jnp.asarray(keep, bool)
    new_ids = jnp.cumsum(keep.astype(jnp.int32))
    mapping = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.where(keep, new_ids, 0)])
    return mapping[labels]


def filter_by_area(
    labels: jax.Array,
    max_objects: int,
    min_area: int = 0,
    max_area: int | None = None,
) -> jax.Array:
    """Remove objects outside [min_area, max_area] (reference
    ``jtmodules/filter.main`` with the 'area' feature).

    Labels beyond ``max_objects`` are dropped first — without this,
    the relabeling gather would clamp them onto object ``max_objects``'s id,
    silently merging distinct objects.
    """
    labels = clip_label_count(labels, max_objects)
    areas = areas_by_label(labels, max_objects)
    keep = areas >= min_area
    if max_area is not None:
        keep = keep & (areas <= max_area)
    keep = keep & (areas > 0)
    return relabel_sequential(labels, keep)


def clip_label_count(labels: jax.Array, max_objects: int) -> jax.Array:
    """Zero out labels beyond ``max_objects`` (static-shape safety valve)."""
    return jnp.where(labels <= max_objects, labels, 0)
