"""Host-side polygon extraction from label images.

Reference parity: the reference converts label images into PostGIS polygons
per mapobject (``tmlib/models/mapobject.py`` ``MapobjectSegmentation``,
via shapely).  Contour tracing is ragged (variable vertices per object), so
it stays on the host — cv2's border following on a per-label mask — and its
output feeds the Parquet object table rather than a database.
"""

from __future__ import annotations

import numpy as np


def labels_to_polygons(labels: np.ndarray) -> list[tuple[int, np.ndarray]]:
    """Trace the outer contour of every labeled object.

    Returns ``[(label, contour)]`` with ``contour`` an ``(K, 2)`` int32 array
    of (y, x) vertices.  Prefers the first-party native Moore tracer
    (``native/tmnative.cpp``); falls back to cv2 border following.
    """
    import scipy.ndimage as ndi

    labels = np.asarray(labels)
    ids = np.unique(labels)
    ids = ids[ids > 0]
    # trace each object on its bounding-box crop, not the full image: a
    # per-label full-image scan/copy is O(count*H*W) — hours on a
    # plate-scale mosaic with tens of thousands of cells.  The Moore trace
    # starts at the object's first pixel in scan order, which the crop
    # preserves, so contours are unchanged up to the (y0, x0) offset.
    slices = ndi.find_objects(labels, max_label=int(ids.max()) if len(ids) else 0)

    from tmlibrary_tpu import native

    if native.available():
        out = []
        for lab in ids:
            sl = slices[int(lab) - 1]
            if sl is None:
                continue
            crop = np.ascontiguousarray(labels[sl].astype(np.int32))
            pts = native.trace_boundary_host(crop, int(lab))
            if pts is not None and len(pts):
                pts = pts + np.asarray([sl[0].start, sl[1].start], np.int32)
                out.append((int(lab), pts))
        return out

    import cv2

    out: list[tuple[int, np.ndarray]] = []
    for lab in ids:
        sl = slices[int(lab) - 1]
        if sl is None:
            continue
        offset = np.asarray([sl[0].start, sl[1].start], np.int32)
        mask = (labels[sl] == lab).astype(np.uint8)
        contours, _ = cv2.findContours(mask, cv2.RETR_EXTERNAL, cv2.CHAIN_APPROX_SIMPLE)
        if not contours:
            ys, xs = np.nonzero(mask)
            out.append(
                (int(lab),
                 np.stack([ys, xs], axis=1).astype(np.int32) + offset)
            )
            continue
        largest = max(contours, key=cv2.contourArea)
        # cv2 returns (K, 1, 2) in (x, y); convert to (K, 2) (y, x)
        contour = largest[:, 0, ::-1].astype(np.int32) + offset
        out.append((int(lab), contour))
    return out


def polygons_to_table(
    polygons: list[tuple[int, np.ndarray]], site_index: int
):
    """Flatten traced polygons into a DataFrame for the Parquet object store."""
    import pandas as pd

    rows = []
    for label, contour in polygons:
        cy, cx = contour[:, 0].mean(), contour[:, 1].mean()
        rows.append(
            {
                "site": site_index,
                "label": label,
                "centroid_y": float(cy),
                "centroid_x": float(cx),
                "n_vertices": int(contour.shape[0]),
                "contour_y": contour[:, 0].tolist(),
                "contour_x": contour[:, 1].tolist(),
            }
        )
    return pd.DataFrame(rows)
