"""Thresholding ops.

Reference parity: ``jtmodules/threshold_manual.py``,
``threshold_otsu.py``, ``threshold_adaptive.py`` (cv2/mahotas-backed in the
reference).

All return boolean masks; all are pure ``jnp`` and jit/vmap-safe.  Histogram
computations use fixed bin counts so shapes stay static under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tmlibrary_tpu.ops.smooth import gaussian_smooth, uniform_smooth


def threshold_manual(img: jax.Array, value) -> jax.Array:
    """Fixed global threshold (reference ``jtmodules/threshold_manual``)."""
    return jnp.asarray(img) > value


def otsu_value(img: jax.Array, bins: int = 256, method: str = "auto") -> jax.Array:
    """Otsu threshold value over a fixed-bin histogram.

    Matches the classic formulation (maximize between-class variance) used by
    mahotas/cv2 in the reference; with ``bins=256`` on 8-bit-scaled data the
    cut matches cv2's within one bin.  Returns a scalar in image units.

    ``method="auto"``: on the CPU backend the min/max + normalize +
    histogram run as ONE fused native pass (``tm_otsu_hist`` — the
    elementwise normalization alone cost ~0.8 ms/site as XLA-CPU passes;
    the C pass is bit-identical, so the cut cannot move); accelerators
    keep the factored one-hot matmul histogram (MXU).  The between-class
    argmax stays in XLA on the (bins,) histogram either way.
    """
    img_f = jnp.asarray(img, jnp.float32)
    if method == "auto":
        from tmlibrary_tpu import native

        method = (
            "native"
            if native.cpu_native_enabled() and native.has_site_stats()
            else "xla"
        )
    if method == "native":
        import numpy as np

        from tmlibrary_tpu import native

        nd = img_f.ndim  # unbatched rank at trace time

        if not isinstance(img_f, jax.core.Tracer):
            # EAGER caller (the spatial mosaic paths compute their
            # global threshold outside jit): one direct C pass — routing
            # an eager op through the pure_callback machinery measured
            # pathologically slow at mosaic scale (minutes for a 4 Mpix
            # well)
            hist_h, lo_h, hi_h = native.otsu_hist_host(
                np.asarray(img_f).reshape(1, -1), bins
            )
            hist = jnp.asarray(hist_h[0])
            lo = jnp.asarray(lo_h[0])
            hi = jnp.asarray(hi_h[0])
            span = jnp.maximum(hi - lo, 1e-6)
            centers = (
                lo + (jnp.arange(bins, dtype=jnp.float32) + 0.5)
                / bins * span
            )
            return _otsu_argmax(hist, centers)

        def host(a):
            from tmlibrary_tpu import native

            a = np.asarray(a)
            lead = a.shape[: a.ndim - nd]
            n = int(np.prod(lead, dtype=np.int64)) if lead else 1
            hist, lo, hi = native.otsu_hist_host(a.reshape(n, -1), bins)
            return (
                hist.reshape(lead + (bins,)),
                lo.reshape(lead),
                hi.reshape(lead),
            )

        hist, lo, hi = jax.pure_callback(
            host,
            (
                jax.ShapeDtypeStruct((bins,), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
            ),
            img_f,
            vmap_method=native.callback_vmap_method(),
        )
        span = jnp.maximum(hi - lo, 1e-6)
    else:
        lo = jnp.min(img_f)
        hi = jnp.max(img_f)
        span = jnp.maximum(hi - lo, 1e-6)
        idx = jnp.clip(
            ((img_f - lo) / span * bins).astype(jnp.int32), 0, bins - 1
        )
        # factored one-hot matmul histogram (MXU) on TPU, scatter on CPU.
        # The method is pinned callback-free: ``method="xla"`` promises a
        # pure-XLA program (the distributed paths call it on globally
        # SHARDED arrays, where a host callback cannot run), so the
        # histogram must not re-introduce one via its own auto dispatch.
        from tmlibrary_tpu.ops.histogram import histogram_fixed_bins

        hist = histogram_fixed_bins(
            idx, bins,
            method="scatter" if jax.default_backend() == "cpu" else "matmul",
        )
    centers = lo + (jnp.arange(bins, dtype=jnp.float32) + 0.5) / bins * span
    return _otsu_argmax(hist, centers)


def _otsu_argmax(hist: jax.Array, centers: jax.Array) -> jax.Array:
    """Between-class-variance argmax over a (bins,) histogram — shared
    by the traced and eager otsu paths (bit-identical math)."""
    w0 = jnp.cumsum(hist)
    w1 = w0[-1] - w0
    sum0 = jnp.cumsum(hist * centers)
    mu0 = sum0 / jnp.maximum(w0, 1e-12)
    mu1 = (sum0[-1] - sum0) / jnp.maximum(w1, 1e-12)
    between = w0 * w1 * (mu0 - mu1) ** 2
    between = jnp.where((w0 > 0) & (w1 > 0), between, -1.0)
    k = jnp.argmax(between)
    return centers[k]


def threshold_otsu(img: jax.Array, bins: int = 256, correction_factor: float = 1.0) -> jax.Array:
    """Otsu global threshold (reference ``jtmodules/threshold_otsu``).

    ``correction_factor`` scales the computed threshold, mirroring the
    reference module's knob for biasing the cut.
    """
    t = otsu_value(img, bins=bins) * correction_factor
    return jnp.asarray(img, jnp.float32) > t


def threshold_adaptive(
    img: jax.Array,
    method: str = "gaussian",
    kernel_size: int = 31,
    constant: float = 0.0,
    min_threshold: float | None = None,
    max_threshold: float | None = None,
) -> jax.Array:
    """Local (adaptive) threshold (reference ``jtmodules/threshold_adaptive``).

    The local threshold at each pixel is the ``method``-weighted mean of its
    ``kernel_size`` neighborhood **plus** ``constant``: a pixel is foreground
    when it exceeds its local background by at least ``constant``.  (This is
    cv2.adaptiveThreshold's ``mean - C`` with the sign flipped: cv2's
    document-binarization convention marks flat regions as foreground, which
    is wrong for spot/cell detection.)  ``min_threshold``/``max_threshold``
    clamp the local threshold like the reference module's bounds.
    """
    img_f = jnp.asarray(img, jnp.float32)
    if method == "gaussian":
        # cv2 derives sigma from the block size this way
        sigma = 0.3 * ((kernel_size - 1) * 0.5 - 1) + 0.8
        local = gaussian_smooth(img_f, sigma=sigma)
    elif method == "mean":
        local = uniform_smooth(img_f, size=kernel_size)
    else:
        raise ValueError(f"unknown adaptive threshold method '{method}'")
    t = local + constant
    if min_threshold is not None:
        t = jnp.maximum(t, min_threshold)
    if max_threshold is not None:
        t = jnp.minimum(t, max_threshold)
    return img_f > t
