"""Per-object feature measurement.

Reference parity: ``jtmodules/measure_intensity.py``,
``measure_morphology.py``, ``measure_texture.py`` (mahotas Haralick),
``measure_zernike.py`` and the extractors in ``jtlib/features/``.

TPU design (SURVEY.md §8 hard parts #3/#4): measurements are ragged per
site (variable object count), so everything is computed into fixed
``(max_objects, ...)`` buffers with ``jax.ops.segment_sum``-family
reductions over the label image — rows past a site's object count are
garbage and must be masked by the caller using the object count.  Haralick
GLCMs accumulate with one scatter-add per direction over
(label, level, level) cells; Zernike moments project per-object patches
(static patch size) onto radial polynomials evaluated at each object's own
scale.  Everything jit/vmap-safe, fp32.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.ops.label import shift_with_fill
from tmlibrary_tpu.ops.reduction import (
    capacity_segments,
    explicit_reduction_request,
    resolve_reduction_strategy,
    segmented_max,
    segmented_min,
    segmented_sum,
)


def _seg_sum(values: jax.Array, labels: jax.Array, max_objects: int) -> jax.Array:
    """segment_sum over label ids; returns per-object rows 1..max_objects."""
    flat = labels.reshape(-1)
    vals = values.reshape(-1)
    out = jax.ops.segment_sum(
        vals, flat, num_segments=capacity_segments(max_objects)
    )
    return out[1:]


_SUM_CHUNK = 1 << 16  # pixels per one-hot matmul chunk (bounds HBM)


def grouped_sums(
    labels: jax.Array,
    channels: list[jax.Array],
    max_objects: int,
    method: str = "auto",
) -> jax.Array:
    """Per-object sums of several pixel channels via one-hot matmuls.

    TPU scatter-adds serialize; contracting a one-hot of the label image
    against stacked value channels rides the MXU instead — one pass for any
    number of channels.  The pixel axis is processed in fixed-size chunks so
    the (chunk, max_objects+1) one-hot operand stays bounded (a full-image
    one-hot on a large site or 3-D volume would blow out HBM, and the
    site-batch vmap multiplies it).  Returns ``(max_objects, n_channels)``
    float32 (label ids 1..max_objects; background dropped).

    ``method`` is a reduction-strategy name (``ops/reduction.py``):
    ``"onehot"`` (alias ``"matmul"``) is the chunked MXU contraction,
    ``"scatter"`` the segment scatter-add, ``"sort"`` the deterministic
    sorted-run reduction, ``"native"`` the explicit-opt-in C callback.
    ``"auto"`` resolves through the strategy layer — by default the
    matmul on accelerators and the scatter on CPU, where scatters are
    cheap and the one-hot materialization is the bottleneck (~25x for
    the measurement stack on the test backend).
    """
    flat = labels.reshape(-1)
    stacked = jnp.stack(
        [jnp.asarray(c, jnp.float32).reshape(-1) for c in channels], axis=-1
    )  # (P, S)
    if method == "auto":
        # scatter stays the CPU auto choice: auto-routing the native
        # callback hung XLA-CPU's runtime inside morphology_features'
        # program at batch 128 (np.asarray of the callback operand never
        # returned; minimal reproductions with the same shapes pass, so
        # the interaction is with the surrounding program, not the
        # kernel).  "native" remains an explicit opt-in — the kernel
        # itself is bit-identical and parity-tested — and the strategy
        # resolver never selects it.
        method = resolve_reduction_strategy()
    if method == "fused":
        from tmlibrary_tpu.ops.fused_measure import grouped_stats

        sums, _, _ = grouped_stats(labels, channels, max_objects)
        return sums
    if method == "onehot":
        method = "matmul"
    if method == "native":
        # one fused C pass over the pixels for ALL channels
        # (tm_site_channel_sums — bit-identical to the segment_sum
        # below), batched like the other measurement callbacks
        from tmlibrary_tpu import native

        n_ch = stacked.shape[-1]
        nd = flat.ndim  # 1 at trace time

        def host(lab, v):
            # align_batch: an operand constant across the vmapped axis
            # arrives with a SIZE-1 lead dim under expand_dims
            lead, (labf, vf) = native.align_batch([(lab, nd), (v, 2)])
            out = native.site_channel_sums_host(
                labf, vf.transpose(0, 2, 1), max_objects
            )  # (n, C, K)
            return out.transpose(0, 2, 1).reshape(
                lead + (max_objects, n_ch)
            )

        return jax.pure_callback(
            host,
            jax.ShapeDtypeStruct((max_objects, n_ch), jnp.float32),
            flat, stacked,
            vmap_method=native.callback_vmap_method(),
        )
    if method in ("scatter", "sort"):
        out = segmented_sum(stacked, flat, capacity_segments(max_objects), method)
        return out[1:]
    if method != "matmul":
        raise ValueError(f"unknown grouped_sums method '{method}'")
    p = flat.shape[0]
    pad = (-p) % _SUM_CHUNK
    if pad:
        # padded pixels carry label 0 → they land in the dropped background row
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        stacked = jnp.concatenate(
            [stacked, jnp.zeros((pad, stacked.shape[1]), stacked.dtype)]
        )
    n_chunks = flat.shape[0] // _SUM_CHUNK
    flat = flat.reshape(n_chunks, _SUM_CHUNK)
    stacked = stacked.reshape(n_chunks, _SUM_CHUNK, -1)

    def body(i, acc):
        oh = jax.nn.one_hot(
            flat[i], capacity_segments(max_objects), dtype=jnp.float32
        )
        return acc + jnp.einsum(
            "ps,pk->ks", stacked[i], oh, precision=jax.lax.Precision.HIGHEST
        )

    init = jnp.zeros(
        (capacity_segments(max_objects), stacked.shape[-1]), jnp.float32
    )
    out = jax.lax.fori_loop(0, n_chunks, body, init)
    return out[1:]


def lookup_by_label(
    labels: jax.Array,
    table: jax.Array,
    method: str = "auto",
) -> jax.Array:
    """Per-pixel lookup of float per-object values: ``out[p] =
    table[labels[p]]`` with ``table`` of shape ``(max_objects + 1, C)``
    (row 0 = background) → ``(*labels.shape, C)`` float32.

    Gathers from a tiny table serialize on TPU (~53 ms/batch-128 net on
    v5e for one 3-column lookup) while a one-hot contraction at
    ``Precision.HIGHEST`` rides the MXU at the fetch floor AND is
    bit-identical to the gather for FINITE table entries (measured: the
    bf16x3 split reconstructs every finite f32 value exactly when each
    dot product has one nonzero term).  Non-finite entries are NOT
    supported: a ±inf/NaN row would poison every pixel's sum through
    ``0 * inf = NaN``, so the matmul path sanitizes them to 0 — callers
    holding sentinel rows (e.g. :func:`grouped_minmax` absent-object
    ±inf) must mask them to finite values first, as
    :func:`quantize_per_object` does.  ``method="auto"``: gather on CPU,
    matmul on accelerators, pixel axis chunked like
    :func:`grouped_sums`."""
    table = jnp.asarray(table, jnp.float32)
    # out-of-range ids clamp into the table on BOTH paths (explicitly —
    # a raw jnp gather would wrap negative ids Python-style while
    # one_hot zeroes them)
    labels = jnp.clip(labels, 0, table.shape[0] - 1)
    if method == "auto":
        method = "gather" if jax.default_backend() == "cpu" else "matmul"
    if method == "gather":
        return table[labels]
    from tmlibrary_tpu.ops.label import _chunked_pixels

    table = jnp.where(jnp.isfinite(table), table, 0.0)
    flat = labels.reshape(-1)
    n = flat.shape[0]
    chunks = _chunked_pixels(flat)

    def body(i, acc):
        oh = jax.nn.one_hot(chunks[i], table.shape[0], dtype=jnp.float32)
        vals = jnp.einsum(
            "pk,kc->pc", oh, table, precision=jax.lax.Precision.HIGHEST
        )
        return acc.at[i].set(vals)

    out = jnp.zeros(
        (chunks.shape[0], chunks.shape[1], table.shape[1]), jnp.float32
    )
    out = jax.lax.fori_loop(0, chunks.shape[0], body, out)
    return out.reshape(-1, table.shape[1])[:n].reshape(
        *labels.shape, table.shape[1]
    )


def grouped_minmax(
    labels: jax.Array,
    values: jax.Array,
    max_objects: int,
    method: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Per-object (min, max) of ``values`` via a fused masked reduce
    (streams the (chunk, K) broadcast through one reduction — ~2.4x faster
    than two segment_min/max scatters on TPU).  The pixel axis is chunked
    like :func:`grouped_sums` so the broadcast operand stays bounded on
    large sites / 3-D volumes under the site-batch vmap.  Rows for absent
    labels come back as (+inf, -inf).  ``method="auto"`` resolves through
    the strategy layer: segment_min/max scatters on CPU (see
    :func:`grouped_sums`), the masked reduce elsewhere.  ``"onehot"``
    aliases ``"reduce"`` — min/max have no matmul form, so the dense
    masked broadcast is that strategy's shape here; all strategies agree
    bit-exactly (min/max are accumulation-order-free)."""
    flat_l = labels.reshape(-1)
    flat_v = jnp.asarray(values, jnp.float32).reshape(-1)
    if method == "auto":
        # see grouped_minmax_multi: native is explicit opt-in on CPU
        method = resolve_reduction_strategy()
    if method == "fused":
        from tmlibrary_tpu.ops.fused_measure import grouped_stats

        _, mn, mx = grouped_stats(labels, [values], max_objects)
        return mn[:, 0], mx[:, 0]
    if method == "onehot":
        method = "reduce"
    if method in ("scatter", "sort"):
        segs = capacity_segments(max_objects)
        mn = segmented_min(flat_v, flat_l, segs, method)
        mx = segmented_max(flat_v, flat_l, segs, method)
        return mn[1:], mx[1:]
    if method != "reduce":
        raise ValueError(f"unknown grouped_minmax method '{method}'")
    p = flat_l.shape[0]
    pad = (-p) % _SUM_CHUNK
    if pad:
        # padded pixels carry label 0 → they match no id in 1..max_objects
        flat_l = jnp.concatenate([flat_l, jnp.zeros((pad,), flat_l.dtype)])
        flat_v = jnp.concatenate([flat_v, jnp.zeros((pad,), flat_v.dtype)])
    n_chunks = flat_l.shape[0] // _SUM_CHUNK
    flat_l = flat_l.reshape(n_chunks, _SUM_CHUNK)
    flat_v = flat_v.reshape(n_chunks, _SUM_CHUNK)
    ids = jnp.arange(1, max_objects + 1, dtype=flat_l.dtype)

    def body(i, carry):
        mn, mx = carry
        sel = flat_l[i][:, None] == ids
        v = flat_v[i][:, None]
        mx = jnp.maximum(mx, jnp.max(jnp.where(sel, v, -jnp.inf), axis=0))
        mn = jnp.minimum(mn, jnp.min(jnp.where(sel, v, jnp.inf), axis=0))
        return mn, mx

    init = (
        jnp.full((max_objects,), jnp.inf, jnp.float32),
        jnp.full((max_objects,), -jnp.inf, jnp.float32),
    )
    return jax.lax.fori_loop(0, n_chunks, body, init)


def grouped_minmax_multi(
    labels: jax.Array,
    values: list[jax.Array],
    max_objects: int,
    method: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Per-object (min, max) of SEVERAL pixel value channels in one pass
    over the pixels — (M, K) mins and maxs.  One chunked loop carrying 2K
    accumulators instead of K :func:`grouped_minmax` sweeps (the masked
    broadcast is the dominant cost on TPU).  CPU uses segment scatters."""
    k = len(values)
    flat_l = labels.reshape(-1)
    stacked = jnp.stack(
        [jnp.asarray(v, jnp.float32).reshape(-1) for v in values], axis=-1
    )  # (P, K)
    if method == "auto":
        # scatter stays the CPU auto choice here: routing this through
        # the native callback alongside grouped_sums' callback in ONE
        # jitted program hung XLA-CPU's runtime on mosaic-scale batches
        # (the second callback never returned from materializing its
        # operands); "native" remains an explicit opt-in until that
        # interaction is understood, and the strategy resolver never
        # selects it
        method = resolve_reduction_strategy()
    if method == "fused":
        from tmlibrary_tpu.ops.fused_measure import grouped_stats

        _, mn, mx = grouped_stats(labels, values, max_objects)
        return mn, mx
    if method == "onehot":
        method = "reduce"
    if method == "native":
        # fused C pass (tm_site_channel_minmax), bit-identical to the
        # segment scatters below
        from tmlibrary_tpu import native

        nd = flat_l.ndim  # 1 at trace time

        def host(lab, v):
            lead, (labf, vf) = native.align_batch([(lab, nd), (v, 2)])
            mn, mx = native.site_channel_minmax_host(
                labf, vf.transpose(0, 2, 1), max_objects
            )  # (n, C, M) each
            shape = lead + (max_objects, k)
            return (
                mn.transpose(0, 2, 1).reshape(shape),
                mx.transpose(0, 2, 1).reshape(shape),
            )

        return jax.pure_callback(
            host,
            (
                jax.ShapeDtypeStruct((max_objects, k), jnp.float32),
                jax.ShapeDtypeStruct((max_objects, k), jnp.float32),
            ),
            flat_l, stacked,
            vmap_method=native.callback_vmap_method(),
        )
    if method in ("scatter", "sort"):
        segs = capacity_segments(max_objects)
        mn = segmented_min(stacked, flat_l, segs, method)
        mx = segmented_max(stacked, flat_l, segs, method)
        return mn[1:], mx[1:]
    if method != "reduce":
        raise ValueError(f"unknown grouped_minmax_multi method '{method}'")
    p = flat_l.shape[0]
    pad = (-p) % _SUM_CHUNK
    if pad:
        flat_l = jnp.concatenate([flat_l, jnp.zeros((pad,), flat_l.dtype)])
        stacked = jnp.concatenate(
            [stacked, jnp.zeros((pad, k), stacked.dtype)]
        )
    n_chunks = flat_l.shape[0] // _SUM_CHUNK
    flat_l = flat_l.reshape(n_chunks, _SUM_CHUNK)
    stacked = stacked.reshape(n_chunks, _SUM_CHUNK, k)
    ids = jnp.arange(1, max_objects + 1, dtype=flat_l.dtype)

    def body(i, carry):
        mn, mx = carry
        sel = flat_l[i][:, None] == ids  # (chunk, M)
        v = stacked[i]  # (chunk, K)
        vm = jnp.where(sel[:, :, None], v[:, None, :], jnp.inf)
        vx = jnp.where(sel[:, :, None], v[:, None, :], -jnp.inf)
        return (
            jnp.minimum(mn, jnp.min(vm, axis=0)),
            jnp.maximum(mx, jnp.max(vx, axis=0)),
        )

    init = (
        jnp.full((max_objects, k), jnp.inf, jnp.float32),
        jnp.full((max_objects, k), -jnp.inf, jnp.float32),
    )
    return jax.lax.fori_loop(0, n_chunks, body, init)


# ------------------------------------------------------------------ intensity
def _native_site_stats(
    labels: jax.Array, img: jax.Array, max_objects: int
) -> tuple[jax.Array, ...]:
    """One fused native pass over the pixels for (count, sum, sq, min,
    max) per label — ``vmap_method="expand_dims"`` (single-device), so a vmapped site
    batch costs ONE host callback total, not one per site (the round-3
    sequential host twin lost to XLA for exactly that reason)."""
    nd = labels.ndim  # site rank at trace time (2-D site or 3-D volume)
    k = max_objects

    def host(lab, im):
        from tmlibrary_tpu import native

        lead, (labf, imf) = native.align_batch([(lab, nd), (im, nd)])
        n = labf.shape[0]
        outs = native.site_stats_host(
            labf.reshape(n, -1), imf.reshape(n, -1), k
        )
        return tuple(o.reshape(lead + (k,)) for o in outs)

    shapes = tuple(
        jax.ShapeDtypeStruct((k,), jnp.float32) for _ in range(5)
    )
    from tmlibrary_tpu import native

    return jax.pure_callback(
        host, shapes, labels, img,
        vmap_method=native.callback_vmap_method(),
    )


def intensity_features(
    labels: jax.Array, intensity: jax.Array, max_objects: int,
    method: str = "auto",
) -> dict[str, jax.Array]:
    """Reference feature set of ``jtlib/features/intensity.py``:
    max, mean, min, sum, std per object.

    ``method="auto"``: on the CPU backend one fused native C pass
    computes all five accumulators (XLA-CPU lowers the segment reductions
    to serial element scatters — ~2.3 ms/site at 256², ~5x the C pass;
    the round-3 note that a host twin measured SLOWER was about a
    PER-SITE sequential callback — the batched ``expand_dims`` callback
    pays the graph break once per batch).  Accelerators stay pure-XLA
    (one-hot MXU contractions); the native pass reproduces the XLA
    reductions bit-for-bit (``tm_site_stats``), so dispatch cannot move
    feature values."""
    labels = jnp.asarray(labels, jnp.int32)
    img = jnp.asarray(intensity, jnp.float32)
    if method == "auto":
        # a pinned/requested "fused" strategy outranks the CPU native
        # heuristic — the megakernel is the thing being requested
        if resolve_reduction_strategy() == "fused":
            method = "fused"
        else:
            from tmlibrary_tpu import native

            method = (
                "native"
                if native.cpu_native_enabled() and native.has_site_stats()
                else "xla"
            )
    if method == "native":
        count, total, sq, mn, mx = _native_site_stats(labels, img, max_objects)
    elif method == "fused":
        # all five accumulators in ONE megakernel pass: count/sum/sumsq
        # from the sum columns, min/max of the intensity channel from the
        # same shared one-hot (the unfused path takes two full passes)
        from tmlibrary_tpu.ops.fused_measure import grouped_stats

        sums, mns, mxs = grouped_stats(
            labels, [jnp.ones_like(img), img, img * img], max_objects
        )
        count, total, sq = sums[:, 0], sums[:, 1], sums[:, 2]
        mn, mx = mns[:, 1], mxs[:, 1]
    else:
        sums = grouped_sums(
            labels, [jnp.ones_like(img), img, img * img], max_objects
        )
        count, total, sq = sums[:, 0], sums[:, 1], sums[:, 2]
        mn, mx = grouped_minmax(labels, img, max_objects)
    safe_n = jnp.maximum(count, 1.0)
    mean = total / safe_n
    var = jnp.maximum(sq / safe_n - mean * mean, 0.0)
    present = count > 0
    return {
        "Intensity_max": jnp.where(present, mx, 0.0),
        "Intensity_mean": mean,
        "Intensity_min": jnp.where(present, mn, 0.0),
        "Intensity_sum": total,
        "Intensity_std": jnp.sqrt(var),
    }


def intensity_quantiles(
    labels: jax.Array,
    intensity: jax.Array,
    max_objects: int,
    qs: tuple[float, ...] = (0.25, 0.5, 0.75),
    bins: int = 256,
    method: str = "auto",
) -> dict[str, jax.Array]:
    """Per-object intensity quantiles (p25 / median / p75 by default).

    Reference parity: quantile-type per-object intensity statistics
    (round-1 VERDICT weak item #8 — some jtlib versions export them
    alongside mean/std; SURVEY.md §3 jtlibrary row).

    TPU design: a ragged per-object sort is gather-bound, so quantiles are
    read off a per-object histogram instead: each object's gray range is
    stretched into ``bins`` buckets (reusing :func:`quantize_per_object`),
    per-(object, bucket) counts accumulate in one one-hot MXU pass (same
    trick as the GLCM rows), and the quantile is the bucket where the
    object's CDF crosses ``q``, mapped back to gray units.  Exact when an
    object's gray span has ≤ ``bins`` distinct levels (the common case for
    stained cells); otherwise quantized to span/bins granularity.

    ``method`` selects the histogram-accumulation strategy
    (``ops/reduction.py``): ``"onehot"`` the dual one-hot contraction,
    ``"scatter"``/``"sort"`` a fused (label*bins + bucket) index into one
    segmented count.  Counts are integers < 2^24 → exact in f32, so every
    strategy returns bit-identical quantiles.
    """
    labels = jnp.asarray(labels, jnp.int32)
    img = jnp.asarray(intensity, jnp.float32)
    raw_lo, raw_hi = grouped_minmax(labels, img, max_objects)
    present = raw_hi >= raw_lo
    lo = jnp.where(present, raw_lo, 0.0)
    span = jnp.where(present, raw_hi - lo, 1.0)
    strategy = resolve_reduction_strategy(method)
    if strategy == "fused":
        # quantization + accumulation inside the megakernel; the bounds
        # come from the fused min/max above, so counts (exact integers)
        # are bit-identical to every other strategy
        from tmlibrary_tpu.ops.fused_measure import intensity_hist

        counts = intensity_hist(
            labels, img, max_objects, bins, (raw_lo, raw_hi)
        )
        return _quantiles_from_counts(counts, lo, span, present, qs, bins)

    q_pix = quantize_per_object(
        labels, img, max_objects, bins, bounds=(raw_lo, raw_hi)
    )
    # per-(object, bucket) counts as ONE contraction: label one-hot
    # (P, M+1) x bucket one-hot (P, bins) -> (M+1, bins) on the MXU, chunked
    # over pixels so both operands stay bounded under the site-batch vmap
    # (a fused (M+1)*bins one-hot would be ~2 GB at M=bins=256).  On CPU a
    # plain fused-index scatter is the fast path (see grouped_sums).
    lab_flat = labels.reshape(-1)
    q_flat = q_pix.reshape(-1)
    if strategy in ("scatter", "sort"):
        idx = lab_flat * bins + q_flat
        segs = capacity_segments(max_objects)
        counts = segmented_sum(
            jnp.ones_like(idx, jnp.float32), idx,
            segs * bins, strategy,
        ).reshape(segs, bins)[1:]
        return _quantiles_from_counts(counts, lo, span, present, qs, bins)
    p = lab_flat.shape[0]
    pad = (-p) % _GLCM_CHUNK
    if pad:
        lab_flat = jnp.concatenate([lab_flat, jnp.zeros((pad,), lab_flat.dtype)])
        q_flat = jnp.concatenate([q_flat, jnp.zeros((pad,), q_flat.dtype)])
    n_chunks = lab_flat.shape[0] // _GLCM_CHUNK
    lab_flat = lab_flat.reshape(n_chunks, _GLCM_CHUNK)
    q_flat = q_flat.reshape(n_chunks, _GLCM_CHUNK)

    def body(i, acc):
        oh_l = jax.nn.one_hot(
            lab_flat[i], capacity_segments(max_objects), dtype=jnp.float32
        )
        oh_q = jax.nn.one_hot(q_flat[i], bins, dtype=jnp.float32)
        return acc + jnp.einsum(
            "pm,pb->mb", oh_l, oh_q, precision=jax.lax.Precision.HIGHEST
        )

    counts = jax.lax.fori_loop(
        0, n_chunks, body,
        jnp.zeros((capacity_segments(max_objects), bins), jnp.float32),
    )[1:]
    return _quantiles_from_counts(counts, lo, span, present, qs, bins)


def _quantiles_from_counts(counts, lo, span, present, qs, bins):
    """Nearest-rank quantiles read off per-object histogram counts."""
    cdf = jnp.cumsum(counts, axis=1)  # (M, bins)
    total = jnp.maximum(cdf[:, -1:], 1.0)
    out: dict[str, jax.Array] = {}
    centers = lo[:, None] + (
        jnp.arange(bins, dtype=jnp.float32)[None, :] * span[:, None] / (bins - 1)
    )
    for q in qs:
        # first bucket where CDF >= q * n  (nearest-rank quantile)
        reached = cdf >= q * total
        idx = jnp.argmax(reached, axis=1)
        val = jnp.take_along_axis(centers, idx[:, None], axis=1)[:, 0]
        name = "Intensity_median" if q == 0.5 else f"Intensity_p{int(round(q * 100)):02d}"
        out[name] = jnp.where(present, val, 0.0)
    return out


# ----------------------------------------------------------------- morphology
def morphology_features(labels: jax.Array, max_objects: int) -> dict[str, jax.Array]:
    """Reference feature set of ``jtlib/features/morphology.py``
    (CellProfiler-style): area, centroids, bounding box/extent, perimeter
    (8-connected boundary pixel count), equivalent diameter, form factor,
    second-moment ellipse (major/minor axis length, eccentricity,
    orientation).  Convex-hull features (solidity) are host-side only and
    live in the polygon pathway.
    """
    labels = jnp.asarray(labels, jnp.int32)
    h, w = labels.shape
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
    )
    ones = jnp.ones((h, w), jnp.float32)

    # perimeter mask: pixels with at least one 4-neighbor of a different label
    boundary = jnp.zeros((h, w), bool)
    for dy, dx in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        boundary = boundary | (shift_with_fill(labels, dy, dx, 0) != labels)
    boundary = boundary & (labels > 0)

    chans = [
        ones, yy, xx, yy * yy, xx * xx, yy * xx, boundary.astype(jnp.float32)
    ]
    if resolve_reduction_strategy() == "fused":
        # all 7 per-object sums AND the bounding box from ONE megakernel
        # pass — the min/max of the yy/xx channels ride the same shared
        # one-hot as the sums (the unfused path below is two passes)
        from tmlibrary_tpu.ops.fused_measure import grouped_stats

        sums, mins_all, maxs_all = grouped_stats(labels, chans, max_objects)
        mins, maxs = mins_all[:, 1:3], maxs_all[:, 1:3]
    else:
        # all per-object sums in one MXU pass
        sums = grouped_sums(labels, chans, max_objects)
        # bounding box: both axes' min/max in ONE pass over the pixels
        mins, maxs = grouped_minmax_multi(labels, [yy, xx], max_objects)
    area = sums[:, 0]
    safe_a = jnp.maximum(area, 1.0)
    cy = sums[:, 1] / safe_a
    cx = sums[:, 2] / safe_a
    perimeter = sums[:, 6]

    y_min, x_min = mins[:, 0], mins[:, 1]
    y_max, x_max = maxs[:, 0], maxs[:, 1]
    present = area > 0
    bbox_h = jnp.where(present, y_max - y_min + 1.0, 0.0)
    bbox_w = jnp.where(present, x_max - x_min + 1.0, 0.0)
    extent = area / jnp.maximum(bbox_h * bbox_w, 1.0)

    # central second moments -> ellipse fit (CellProfiler/regionprops math)
    mu_yy = sums[:, 3] / safe_a - cy * cy
    mu_xx = sums[:, 4] / safe_a - cx * cx
    mu_yx = sums[:, 5] / safe_a - cy * cx
    # regionprops adds 1/12 (pixel as unit square) to the diagonal
    mu_yy = mu_yy + 1.0 / 12.0
    mu_xx = mu_xx + 1.0 / 12.0
    common = jnp.sqrt(jnp.maximum((mu_yy - mu_xx) ** 2 + 4.0 * mu_yx**2, 0.0))
    l1 = (mu_yy + mu_xx + common) / 2.0
    l2 = (mu_yy + mu_xx - common) / 2.0
    l2 = jnp.clip(l2, 1e-12, None)
    major = 4.0 * jnp.sqrt(jnp.maximum(l1, 0.0))
    minor = 4.0 * jnp.sqrt(jnp.maximum(l2, 0.0))
    eccentricity = jnp.sqrt(jnp.clip(1.0 - l2 / jnp.maximum(l1, 1e-12), 0.0, 1.0))
    # angle of the major axis measured from the +x (column) axis in
    # (-pi/2, pi/2]; note skimage regionprops measures from the row axis
    orientation = 0.5 * jnp.arctan2(2.0 * mu_yx, mu_xx - mu_yy)

    equivalent_diameter = jnp.sqrt(4.0 * area / jnp.pi)
    form_factor = 4.0 * jnp.pi * area / jnp.maximum(perimeter**2, 1.0)

    z = jnp.zeros_like(area)
    def m(v):
        return jnp.where(present, v, z)

    return {
        "Morphology_area": area,
        "Morphology_centroid_y": m(cy),
        "Morphology_centroid_x": m(cx),
        "Morphology_bbox_height": bbox_h,
        "Morphology_bbox_width": bbox_w,
        "Morphology_extent": m(extent),
        "Morphology_perimeter": perimeter,
        "Morphology_equivalent_diameter": m(equivalent_diameter),
        "Morphology_form_factor": m(form_factor),
        "Morphology_major_axis_length": m(major),
        "Morphology_minor_axis_length": m(minor),
        "Morphology_eccentricity": m(eccentricity),
        "Morphology_orientation": m(orientation),
    }


# -------------------------------------------------------------------- texture
_GLCM_CHUNK = 1 << 13  # pixels per matmul chunk: (chunk, (M+1)*L) one-hot


def _glcm_matmul_all(
    labels: jax.Array,
    quantized: jax.Array,
    max_objects: int,
    levels: int,
    offsets: list[tuple[int, int]],
) -> list[jax.Array]:
    """All directions' GLCMs in ONE chunked contraction.

    The (label, q1) row one-hot is direction-independent once validity is
    moved entirely into the column operand (invalid pairs contribute a
    zero column vector), so the 4 directions share each chunk's expensive
    row one-hot and contract against their column one-hots concatenated
    to (P, 4L) — one wider MXU matmul instead of four, and one pass over
    the pixels instead of four."""
    row = jnp.where(labels > 0, labels * levels + quantized, 0).reshape(-1)
    cols = []
    for dy, dx in offsets:
        lab2 = shift_with_fill(labels, -dy, -dx, 0)
        q2 = shift_with_fill(quantized, -dy, -dx, 0)
        valid = (labels > 0) & (lab2 == labels)
        cols.append(
            (jnp.where(valid, q2, 0).reshape(-1), valid.reshape(-1))
        )

    p = row.shape[0]
    pad = (-p) % _GLCM_CHUNK
    if pad:
        row = jnp.concatenate([row, jnp.zeros((pad,), row.dtype)])
        cols = [
            (
                jnp.concatenate([c, jnp.zeros((pad,), c.dtype)]),
                jnp.concatenate([v, jnp.zeros((pad,), bool)]),
            )
            for c, v in cols
        ]
    n_chunks = row.shape[0] // _GLCM_CHUNK
    row = row.reshape(n_chunks, _GLCM_CHUNK)
    cols = [
        (c.reshape(n_chunks, _GLCM_CHUNK), v.reshape(n_chunks, _GLCM_CHUNK))
        for c, v in cols
    ]
    n_rows = capacity_segments(max_objects) * levels
    k = len(offsets)

    def body(i, acc):
        # bf16 operands are EXACT here (one-hot entries are 0.0/1.0, both
        # representable) and the MXU accumulates into f32 via
        # preferred_element_type, so a single bf16 pass produces the same
        # integer counts as the multi-pass HIGHEST f32 matmul at a
        # fraction of the cost (counts are < 2^24, exact in f32)
        oh_rc = jax.nn.one_hot(row[i], n_rows, dtype=jnp.bfloat16)
        oh_cols = jnp.concatenate(
            [
                jax.nn.one_hot(c[i], levels, dtype=jnp.bfloat16)
                * v[i][:, None].astype(jnp.bfloat16)
                for c, v in cols
            ],
            axis=-1,
        )  # (chunk, k*L)
        return acc + jnp.einsum(
            "pr,pc->rc", oh_rc, oh_cols, preferred_element_type=jnp.float32
        )

    init = jnp.zeros((n_rows, k * levels), jnp.float32)
    counts = jax.lax.fori_loop(0, n_chunks, body, init)
    out = []
    for d in range(k):
        glcm = counts[:, d * levels : (d + 1) * levels].reshape(
            capacity_segments(max_objects), levels, levels
        )[1:]
        out.append(glcm + jnp.swapaxes(glcm, 1, 2))
    return out


def _glcm_scatter(
    labels: jax.Array,
    quantized: jax.Array,
    max_objects: int,
    levels: int,
    offset: tuple[int, int],
    strategy: str = "scatter",
) -> jax.Array:
    """GLCM accumulation via one segmented count per direction over fused
    (label, q1, q2) cell indices — ``strategy="scatter"`` (portable
    fallback; fastest on CPU where scatters are cheap) or ``"sort"`` (the
    deterministic sorted-run form; counts are order-free integers, so the
    result is bit-identical either way)."""
    dy, dx = offset
    lab2 = shift_with_fill(labels, -dy, -dx, 0)
    q2 = shift_with_fill(quantized, -dy, -dx, 0)
    valid = (labels > 0) & (lab2 == labels)
    # count into (label, q1, q2) cells
    idx = (
        labels.astype(jnp.int32) * (levels * levels)
        + quantized * levels
        + q2
    )
    idx = jnp.where(valid, idx, 0)
    counts = segmented_sum(
        valid.reshape(-1).astype(jnp.float32),
        idx.reshape(-1),
        capacity_segments(max_objects) * levels * levels,
        strategy,
    )
    glcm = counts.reshape(capacity_segments(max_objects), levels, levels)[1:]
    return glcm + jnp.swapaxes(glcm, 1, 2)


def _resolve_glcm_method(method: str) -> str:
    if method == "onehot":
        return "matmul"
    if method != "auto":
        return method
    # an explicit strategy request (CLI env, config, the tuned
    # reduction_strategy verdict, or a build-time pin) overrides the
    # backend heuristics below — including GLCM's own matmul-vs-scatter
    # verdict, which only decides genuinely-unrequested "auto"
    requested = explicit_reduction_request()
    if requested is not None:
        return "matmul" if requested == "onehot" else requested
    backend = jax.default_backend()
    if backend == "cpu":
        # "native" (tm_site_glcm: quantization + all 4 GLCMs in one C
        # pass, bit-identical — counts are exact integers) stays an
        # EXPLICIT opt-in like the channel-sum kernels: auto-routing it
        # stalled XLA-CPU's runtime from batch 16 up regardless of vmap
        # method (batch 8 and the whole existing callback family run
        # fine; the direct C call does the full batch-128 workload in
        # 0.12 s), so the stall is a runtime interaction this release
        # does not ship on by default.
        return "scatter"
    if backend == "tpu":
        # the committed tuning verdict was measured on a TPU — scope it
        from tmlibrary_tpu.ops.pallas_kernels import _tuning_results

        wins = _tuning_results().get("glcm_matmul_wins")
        return "matmul" if wins in (None, True) else "scatter"
    return "matmul"  # gpu and friends: untuned, keep the matmul default


def quantize_per_object(
    labels: jax.Array,
    intensity: jax.Array,
    max_objects: int,
    levels: int,
    bounds: tuple[jax.Array, jax.Array] | None = None,
) -> jax.Array:
    """Per-object gray-level stretch to ``[0, levels-1]`` — mahotas
    semantics (``jtlib/features/texture.py`` stretches each object's
    region before ``mahotas.features.haralick``; ``mh.stretch``:
    ``floor((v - min) * (levels-1) / (max - min))``).  Quantizing by the
    *global* image range instead shifts every object's GLCM and breaks
    fidelity (round-1 VERDICT missing item #3)."""
    labels = jnp.asarray(labels, jnp.int32)
    img = jnp.asarray(intensity, jnp.float32)
    # (M,) per-object range; +inf/-inf marks absent.  ``bounds`` lets a
    # caller that already holds grouped_minmax output skip the second full
    # reduction pass over all pixels.
    lo, hi = bounds if bounds is not None else grouped_minmax(
        labels, img, max_objects
    )
    present = hi >= lo
    lo = jnp.where(present, lo, 0.0)
    span = jnp.where(present, hi - lo, 1.0)
    lo_full = jnp.concatenate([jnp.zeros((1,), jnp.float32), lo])
    span_full = jnp.concatenate([jnp.ones((1,), jnp.float32), span])
    per_pix = lookup_by_label(labels, jnp.stack([lo_full, span_full], axis=-1))
    lo_pix = per_pix[..., 0]
    span_pix = jnp.maximum(per_pix[..., 1], 1e-6)
    q = jnp.floor((img - lo_pix) * (levels - 1) / span_pix)
    return jnp.clip(q, 0, levels - 1).astype(jnp.int32)


def haralick_features(
    labels: jax.Array,
    intensity: jax.Array,
    max_objects: int,
    levels: int = 32,
    distance: int = 1,
    quantization: str = "object",
    glcm_method: str = "auto",
) -> dict[str, jax.Array]:
    """Haralick texture features averaged over the 4 directions
    (reference: mahotas.features.haralick via ``jtlib/features/texture.py``).

    Features: angular second moment, contrast, correlation, sum of squares
    variance, inverse difference moment (homogeneity), sum average, sum
    variance, sum entropy, entropy, difference variance, difference entropy,
    and the two information measures of correlation.

    ``quantization="object"`` (default) stretches each object's own gray
    range into ``levels`` bins, matching the reference's per-object
    ``mh.stretch`` + integer-level GLCM; ``"global"`` keeps the round-1
    whole-image quantization (cheaper: no per-object min/max pass).
    """
    labels = jnp.asarray(labels, jnp.int32)
    img = jnp.asarray(intensity, jnp.float32)
    method = _resolve_glcm_method(glcm_method)
    offsets = [(0, distance), (distance, 0), (distance, distance), (distance, -distance)]
    i_idx = jnp.arange(levels, dtype=jnp.float32)[None, :, None]
    j_idx = jnp.arange(levels, dtype=jnp.float32)[None, None, :]
    eps = 1e-10

    if method == "native" and quantization == "object":
        # quantization + all 4 directions in one C pass (bit-identical:
        # GLCM counts are exact integers, the per-object stretch is the
        # same f32 expression tree) — labels + image are the only
        # operands, both batched under the site vmap
        from tmlibrary_tpu import native

        nd = labels.ndim  # 2 at trace time

        def host(lab, im):
            lead, (labf, imf) = native.align_batch([(lab, nd), (im, nd)])
            out = native.site_glcm_host(
                labf, imf, max_objects, levels, distance
            )
            return out.reshape(lead + out.shape[1:])

        # vmap_method pinned to the SPMD-safe sequential form: the
        # batched expand_dims variant of THIS callback (like
        # morphology's) stalls XLA-CPU's runtime at batch 128 — the
        # callback never returns from materializing its operands, while
        # minimal reproductions with identical shapes/results pass.
        # Sequential still collapses the whole quantize+GLCM chain into
        # one C call per site (~10x the scatter stage).
        packed = jax.pure_callback(
            host,
            jax.ShapeDtypeStruct(
                (4, max_objects, levels, levels), jnp.float32
            ),
            labels, img,
            vmap_method="sequential",
        )
        glcms = [packed[d] for d in range(4)]
    elif method == "fused" and quantization == "object":
        # quantization + all 4 directions in the fused Pallas pass; the
        # bounds come from the fused stats kernel (counts are exact
        # integers, the per-object stretch the same f32 expression tree,
        # so the GLCMs are bit-identical to the matmul/scatter paths)
        from tmlibrary_tpu.ops.fused_measure import glcm_all

        bounds = grouped_minmax(labels, img, max_objects, method="fused")
        glcms = glcm_all(labels, img, max_objects, levels, offsets, bounds)
    else:
        if method == "native":
            method = "scatter"  # global quantization: no native path
        if method == "fused":
            method = "matmul"  # global quantization: no per-object bounds
        if quantization == "object":
            q = quantize_per_object(labels, img, max_objects, levels)
        elif quantization == "global":
            lo = jnp.min(img)
            hi = jnp.max(img)
            span = jnp.maximum(hi - lo, 1e-6)
            q = jnp.clip(
                ((img - lo) / span * levels).astype(jnp.int32), 0, levels - 1
            )
        else:
            raise ValueError(f"unknown quantization '{quantization}'")

        if method == "matmul":
            # all 4 directions share each chunk's row one-hot in one pass
            glcms = _glcm_matmul_all(labels, q, max_objects, levels, offsets)
        elif method in ("scatter", "sort"):
            glcms = [
                _glcm_scatter(labels, q, max_objects, levels, off, method)
                for off in offsets
            ]
        else:
            raise ValueError(f"unknown glcm method '{method}'")

    acc: dict[str, jax.Array] = {}
    for glcm in glcms:
        total = jnp.maximum(glcm.sum(axis=(1, 2), keepdims=True), eps)
        p = glcm / total  # (M, L, L) normalized

        px = p.sum(axis=2)  # (M, L)
        py = p.sum(axis=1)
        mu_x = (px * i_idx[:, :, 0]).sum(axis=1)
        mu_y = (py * i_idx[:, :, 0]).sum(axis=1)
        sd_x = jnp.sqrt(jnp.maximum((px * (i_idx[:, :, 0] - mu_x[:, None]) ** 2).sum(axis=1), 0.0))
        sd_y = jnp.sqrt(jnp.maximum((py * (i_idx[:, :, 0] - mu_y[:, None]) ** 2).sum(axis=1), 0.0))

        asm = (p**2).sum(axis=(1, 2))
        contrast = (p * (i_idx - j_idx) ** 2).sum(axis=(1, 2))
        corr_num = (p * (i_idx - mu_x[:, None, None]) * (j_idx - mu_y[:, None, None])).sum(axis=(1, 2))
        correlation = corr_num / jnp.maximum(sd_x * sd_y, eps)
        variance = (p * (i_idx - mu_x[:, None, None]) ** 2).sum(axis=(1, 2))
        idm = (p / (1.0 + (i_idx - j_idx) ** 2)).sum(axis=(1, 2))
        entropy = -(p * jnp.log(p + eps)).sum(axis=(1, 2))

        # p_{x+y}(k), k = i+j in [0, 2L-2]; p_{x-y}(k), k = |i-j| in [0, L-1]
        k_sum = jnp.arange(2 * levels - 1, dtype=jnp.float32)
        sum_idx = (jnp.arange(levels)[:, None] + jnp.arange(levels)[None, :]).reshape(-1)
        p_flat = p.reshape(max_objects, -1)
        p_sum = jax.vmap(
            lambda row: jax.ops.segment_sum(row, sum_idx, num_segments=2 * levels - 1)
        )(p_flat)
        diff_idx = jnp.abs(jnp.arange(levels)[:, None] - jnp.arange(levels)[None, :]).reshape(-1)
        p_diff = jax.vmap(
            lambda row: jax.ops.segment_sum(row, diff_idx, num_segments=levels)
        )(p_flat)

        sum_avg = (p_sum * k_sum).sum(axis=1)
        sum_entropy = -(p_sum * jnp.log(p_sum + eps)).sum(axis=1)
        sum_var = (p_sum * (k_sum - sum_entropy[:, None]) ** 2).sum(axis=1)  # Haralick's defn
        k_diff = jnp.arange(levels, dtype=jnp.float32)
        diff_avg = (p_diff * k_diff).sum(axis=1)
        diff_var = (p_diff * (k_diff - diff_avg[:, None]) ** 2).sum(axis=1)
        diff_entropy = -(p_diff * jnp.log(p_diff + eps)).sum(axis=1)

        hx = -(px * jnp.log(px + eps)).sum(axis=1)
        hy = -(py * jnp.log(py + eps)).sum(axis=1)
        pxpy = px[:, :, None] * py[:, None, :]
        hxy1 = -(p * jnp.log(pxpy + eps)).sum(axis=(1, 2))
        hxy2 = -(pxpy * jnp.log(pxpy + eps)).sum(axis=(1, 2))
        imc1 = (entropy - hxy1) / jnp.maximum(jnp.maximum(hx, hy), eps)
        imc2 = jnp.sqrt(jnp.clip(1.0 - jnp.exp(-2.0 * (hxy2 - entropy)), 0.0, 1.0))

        feats = {
            "Texture_angular_second_moment": asm,
            "Texture_contrast": contrast,
            "Texture_correlation": correlation,
            "Texture_sum_of_squares_variance": variance,
            "Texture_inverse_difference_moment": idm,
            "Texture_sum_average": sum_avg,
            "Texture_sum_variance": sum_var,
            "Texture_sum_entropy": sum_entropy,
            "Texture_entropy": entropy,
            "Texture_difference_variance": diff_var,
            "Texture_difference_entropy": diff_entropy,
            "Texture_info_measure_corr_1": imc1,
            "Texture_info_measure_corr_2": imc2,
        }
        for k, v in feats.items():
            acc[k] = acc.get(k, 0.0) + v / len(offsets)
    return acc


# -------------------------------------------------------------------- zernike
def _zernike_coeffs(degree: int) -> list[tuple[int, int, np.ndarray]]:
    """Static (n, m, radial-coefficient) table for n<=degree, m>=0,
    (n-m) even.  Coefficient k applies to rho^(n-2k)."""
    out = []
    for n in range(degree + 1):
        for m_ in range(n % 2, n + 1, 2):
            coeffs = np.zeros((n - m_) // 2 + 1)
            for k in range((n - m_) // 2 + 1):
                coeffs[k] = (
                    (-1) ** k
                    * math.factorial(n - k)
                    / (
                        math.factorial(k)
                        * math.factorial((n + m_) // 2 - k)
                        * math.factorial((n - m_) // 2 - k)
                    )
                )
            out.append((n, m_, coeffs))
    return out


def _host_ok() -> bool:
    """Shared gate with the native segmentation path (TMX_NATIVE=0 turns
    every cpu-fallback host routing off at once)."""
    from tmlibrary_tpu.native import tmx_native_env_enabled

    return tmx_native_env_enabled()


def _zernike_host(labels: "np.ndarray", max_objects: int, degree: int) -> "np.ndarray":
    """Host twin of the device Zernike projection, restricted to the
    object pixels (the XLA path evaluates the whole basis over EVERY
    image pixel — fine on TPU where it is fused VPU work, but it
    dominated the CPU-fallback full-feature bench at ~31 ms/site for
    typically ~10% foreground).  Same math, numpy, fg pixels only.
    Returns (max_objects, n_table) float32 magnitudes."""
    labels = np.asarray(labels)
    table = _zernike_coeffs(degree)
    out = np.zeros((max_objects, len(table)), np.float32)
    area = np.bincount(
        labels.ravel(), minlength=max_objects + 1
    )[1:max_objects + 1].astype(np.float64)
    ys, xs = np.nonzero(labels)
    lab = labels[ys, xs]
    keep = lab <= max_objects
    ys, xs, lab = ys[keep], xs[keep], lab[keep]
    if len(lab) == 0:
        return out
    safe_a = np.maximum(area, 1.0)
    cy = np.bincount(lab, weights=ys, minlength=max_objects + 1)[1:] / safe_a
    cx = np.bincount(lab, weights=xs, minlength=max_objects + 1)[1:] / safe_a
    dy = ys - cy[lab - 1]
    dx = xs - cx[lab - 1]
    r2 = dy * dy + dx * dx
    r2_max = np.zeros(max_objects, np.float64)
    np.maximum.at(r2_max, lab - 1, r2)
    r_obj = np.sqrt(np.maximum(np.where(area > 0, r2_max, 1.0), 1.0))
    rho = np.sqrt(r2) / r_obj[lab - 1]
    theta = np.arctan2(dy, dx)
    ok = (rho <= 1.0).astype(np.float64)  # fp-rounding guard, like the XLA path
    rho_pow = [np.ones_like(rho)]
    for _ in range(degree):
        rho_pow.append(rho_pow[-1] * rho)
    cos_m = [np.ones_like(theta)]
    sin_m = [np.zeros_like(theta)]
    for m_ in range(1, degree + 1):
        cos_m.append(np.cos(m_ * theta))
        sin_m.append(np.sin(m_ * theta))
    for idx, (n, m_, coeffs) in enumerate(table):
        radial = np.zeros_like(rho)
        for k, c in enumerate(coeffs):
            radial = radial + float(c) * rho_pow[n - 2 * k]
        base = radial * ok
        re = np.bincount(
            lab, weights=base * cos_m[m_], minlength=max_objects + 1
        )[1:]
        im = np.bincount(
            lab, weights=base * sin_m[m_], minlength=max_objects + 1
        )[1:]
        mag = np.sqrt(re * re + im * im) * (n + 1) / np.pi / safe_a
        out[:, idx] = np.where(area > 0, mag, 0.0)
    return out


def zernike_host_features(
    labels: "np.ndarray", count: int, degree: int = 9, row_block: int = 512
) -> "np.ndarray":
    """PUBLIC ragged host Zernike for dynamic object counts (the spatial
    mosaic path): same math and normalization as :func:`_zernike_host`,
    but processed in row blocks so transient memory stays
    O(row_block * W + count) next to a plate-scale mosaic instead of
    materializing every foreground pixel's polar tables at once.
    Returns ``(count, n_table)`` float32 magnitudes in
    :func:`_zernike_coeffs` order."""
    labels = np.asarray(labels)
    table = _zernike_coeffs(degree)
    out = np.zeros((count, len(table)), np.float32)
    if count == 0:
        return out
    h, w = labels.shape
    colf = np.arange(w, dtype=np.float64)

    # pass 1: area + centroids
    area = np.zeros(count + 1)
    ysum = np.zeros(count + 1)
    xsum = np.zeros(count + 1)
    for y0 in range(0, h, row_block):
        blk = labels[y0:y0 + row_block]
        flat = blk.ravel()
        area += np.bincount(flat, minlength=count + 1)
        rows = np.repeat(
            np.arange(y0, y0 + blk.shape[0], dtype=np.float64), w
        )
        xsum += np.bincount(flat, weights=np.tile(colf, blk.shape[0]),
                            minlength=count + 1)
        ysum += np.bincount(flat, weights=rows, minlength=count + 1)
    safe_a = np.maximum(area[1:], 1.0)
    cy = np.concatenate([[0.0], ysum[1:] / safe_a])
    cx = np.concatenate([[0.0], xsum[1:] / safe_a])

    # pass 2: per-object max radius
    r2_max = np.zeros(count + 1)
    for y0 in range(0, h, row_block):
        blk = labels[y0:y0 + row_block]
        ys, xs = np.nonzero(blk)
        if not len(ys):
            continue
        lab = blk[ys, xs]
        dy = (ys + y0) - cy[lab]
        dx = xs - cx[lab]
        np.maximum.at(r2_max, lab, dy * dy + dx * dx)
    r_obj = np.concatenate([
        [1.0],
        np.sqrt(np.maximum(np.where(area[1:] > 0, r2_max[1:], 1.0), 1.0)),
    ])

    # pass 3: basis projections
    re_acc = np.zeros((len(table), count + 1))
    im_acc = np.zeros((len(table), count + 1))
    for y0 in range(0, h, row_block):
        blk = labels[y0:y0 + row_block]
        ys, xs = np.nonzero(blk)
        if not len(ys):
            continue
        lab = blk[ys, xs]
        dy = (ys + y0) - cy[lab]
        dx = xs - cx[lab]
        r2 = dy * dy + dx * dx
        rho = np.sqrt(r2) / r_obj[lab]
        theta = np.arctan2(dy, dx)
        ok = (rho <= 1.0).astype(np.float64)
        rho_pow = [np.ones_like(rho)]
        for _ in range(degree):
            rho_pow.append(rho_pow[-1] * rho)
        cos_m = [np.ones_like(theta)]
        sin_m = [np.zeros_like(theta)]
        for m_ in range(1, degree + 1):
            cos_m.append(np.cos(m_ * theta))
            sin_m.append(np.sin(m_ * theta))
        for idx, (n, m_, coeffs) in enumerate(table):
            radial = np.zeros_like(rho)
            for k, c in enumerate(coeffs):
                radial = radial + float(c) * rho_pow[n - 2 * k]
            base = radial * ok
            re_acc[idx] += np.bincount(
                lab, weights=base * cos_m[m_], minlength=count + 1
            )
            im_acc[idx] += np.bincount(
                lab, weights=base * sin_m[m_], minlength=count + 1
            )
    for idx, (n, m_, _) in enumerate(table):
        mag = (
            np.sqrt(re_acc[idx, 1:] ** 2 + im_acc[idx, 1:] ** 2)
            * (n + 1) / np.pi / safe_a
        )
        out[:, idx] = np.where(area[1:] > 0, mag, 0.0)
    return out


def zernike_features(
    labels: jax.Array,
    max_objects: int,
    degree: int = 9,
    patch: int | None = None,
    method: str = "auto",
) -> dict[str, jax.Array]:
    """Zernike moment magnitudes |Z_nm| per object
    (reference: ``jtlib/features/zernike.py`` via centrosome/mahotas:
    binary mask mapped onto the unit disk at the object's own radius,
    projected on the Zernike basis, mass-normalized, ``*(n+1)/pi``).

    TPU design: patch-free.  Every pixel carries its OWN object's
    unit-disk coordinates via label-indexed centroid/radius lookups, the
    radial polynomials and angular harmonics are evaluated once per pixel
    (pure VPU elementwise work), and all (n, m) projections reduce in a
    single :func:`grouped_sums` MXU pass — ~60 channels at degree 9.
    This removes the round-1 static 64-px patch and its silent cropping
    of over-size objects (VERDICT weak item #5): exact at any object
    size, no dynamic-slice gathers.

    ``patch`` is accepted for backward compatibility and ignored.
    ``method="auto"`` routes to the foreground-only host twin
    (:func:`_zernike_host`) on the cpu backend — same dispatch gate as
    the native segmentation kernels (``TMX_NATIVE=0`` forces xla); the
    host path agrees within float tolerance (it sums per-object in f64,
    the device path in f32), which the golden tests' 2e-3 rtol covers.
    """
    del patch  # patch-free since round 2; kept for YAML/handle compat
    labels = jnp.asarray(labels, jnp.int32)
    h, w = labels.shape

    if method == "auto":
        method = "host" if jax.default_backend() == "cpu" and _host_ok() else "xla"
    if method == "host":
        table = _zernike_coeffs(degree)
        from tmlibrary_tpu import native

        proj = jax.pure_callback(
            native.batch_sites(2)(
                lambda lb: _zernike_host(lb, max_objects, degree)
            ),
            jax.ShapeDtypeStruct((max_objects, len(table)), jnp.float32),
            labels,
            vmap_method=native.callback_vmap_method(),
        )
        return {
            f"Zernike_{n}_{m_}": proj[:, idx]
            for idx, (n, m_, _) in enumerate(table)
        }
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
    )
    ones = jnp.ones((h, w), jnp.float32)
    sums = grouped_sums(labels, [ones, yy, xx], max_objects)
    area, sy, sx = sums[:, 0], sums[:, 1], sums[:, 2]
    safe_a = jnp.maximum(area, 1.0)
    cy = sy / safe_a
    cx = sx / safe_a

    # per-pixel centroid of the pixel's own object (label lookup)
    zero1 = jnp.zeros((1,), jnp.float32)
    cen_pix = lookup_by_label(
        labels,
        jnp.stack(
            [jnp.concatenate([zero1, cy]), jnp.concatenate([zero1, cx])],
            axis=-1,
        ),
    )
    dy = yy - cen_pix[..., 0]
    dx = xx - cen_pix[..., 1]
    r2 = dy * dy + dx * dx
    _, r2_max = grouped_minmax(labels, r2, max_objects)
    r_obj = jnp.sqrt(jnp.maximum(jnp.where(area > 0, r2_max, 1.0), 1.0))
    r_pix = lookup_by_label(
        labels, jnp.concatenate([jnp.ones((1,), jnp.float32), r_obj])[:, None]
    )[..., 0]

    # rho > 1 is impossible by construction (r_pix IS each object's max
    # radius), but TPU lowers x/y to x*(1/y) with a reciprocal approx
    # that can land one ulp above 1.0 at the extremal-radius pixel —
    # dropping it there shifted Zernike_6_0 of a 177-px object by 9%
    # (rim pixels carry R_n0(1)=1, the max radial weight).  Clamp
    # instead of masking so the rim pixel contributes at rho=1 exactly,
    # matching the f64 host twin.
    rho = jnp.minimum(jnp.sqrt(r2) / r_pix, 1.0)
    theta = jnp.arctan2(dy, dx)
    fgf = (labels > 0).astype(jnp.float32)

    # shared power/harmonic tables, evaluated once per pixel
    rho_pow = [jnp.ones_like(rho)]
    for _ in range(degree):
        rho_pow.append(rho_pow[-1] * rho)
    cos_m = [jnp.ones_like(theta)]
    sin_m = [jnp.zeros_like(theta)]
    for m_ in range(1, degree + 1):
        cos_m.append(jnp.cos(m_ * theta))
        sin_m.append(jnp.sin(m_ * theta))

    table = _zernike_coeffs(degree)
    chans: list[jax.Array] = []
    for n, m_, coeffs in table:
        radial = jnp.zeros_like(rho)
        for k, c in enumerate(coeffs):
            radial = radial + float(c) * rho_pow[n - 2 * k]
        chans.append(radial * cos_m[m_] * fgf)
        chans.append(radial * sin_m[m_] * fgf)

    proj = grouped_sums(labels, chans, max_objects)  # (M, 2K)
    out: dict[str, jax.Array] = {}
    for idx, (n, m_, _) in enumerate(table):
        re = proj[:, 2 * idx]
        im = proj[:, 2 * idx + 1]
        mag = jnp.sqrt(re**2 + im**2) * (n + 1) / jnp.pi / safe_a
        out[f"Zernike_{n}_{m_}"] = jnp.where(area > 0, mag, 0.0)
    return out


# -------------------------------------------------------------- point pattern
def point_pattern_features(
    parent_labels: jax.Array,
    point_labels: jax.Array,
    max_parents: int,
    max_points: int,
) -> dict[str, jax.Array]:
    """Spatial point-pattern statistics of child "point" objects (e.g.
    spots/speckles) within parent objects.

    Reference parity: ``jtlib/features/point_pattern.py`` (SURVEY.md §3
    jtlibrary row) — per parent: point count and density, nearest-neighbor
    distance statistics among the parent's points, the Clark–Evans
    aggregation index (observed mean NN distance over the expectation
    ``0.5/sqrt(density)`` for complete spatial randomness), distances from
    points to the parent centroid, and distances to the parent border.

    TPU design: points are reduced to centroids once (one ``grouped_sums``
    MXU pass over the point label image), then every statistic is computed
    on the fixed ``(max_points,)`` axis — the all-pairs distance matrix is
    a dense ``(max_points, max_points)`` op and per-parent aggregation is a
    masked broadcast over ``(max_points, max_parents)``, both tiny and
    tiling-friendly.  Border distance is the exact Euclidean distance from
    each point centroid to the nearest label-boundary pixel: a masked min
    over image pixels, chunked so the ``(max_points, chunk)`` tile stays
    bounded under the site-batch vmap (same metric as the NN/centroid
    distances; no chamfer approximation, no distance cap).  Everything
    jit/vmap-safe; rows for absent parents are zero.
    """
    parents = jnp.asarray(parent_labels, jnp.int32)
    points = jnp.asarray(point_labels, jnp.int32)
    h, w = parents.shape
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32),
        jnp.arange(w, dtype=jnp.float32),
        indexing="ij",
    )
    ones = jnp.ones((h, w), jnp.float32)

    # ---- point centroids + parent centroids/areas (two MXU passes)
    psums = grouped_sums(points, [ones, yy, xx], max_points)  # (P, 3)
    p_n = psums[:, 0]
    p_present = p_n > 0
    safe_pn = jnp.maximum(p_n, 1.0)
    py = psums[:, 1] / safe_pn
    px = psums[:, 2] / safe_pn

    gsums = grouped_sums(parents, [ones, yy, xx], max_parents)  # (M, 3)
    area = gsums[:, 0]
    safe_a = jnp.maximum(area, 1.0)
    g_cy = gsums[:, 1] / safe_a
    g_cx = gsums[:, 2] / safe_a
    parent_present = area > 0

    # ---- assign each point to the parent under its centroid pixel
    iy = jnp.clip(jnp.round(py).astype(jnp.int32), 0, h - 1)
    ix = jnp.clip(jnp.round(px).astype(jnp.int32), 0, w - 1)
    owner = jnp.where(p_present, parents[iy, ix], 0)  # (P,) 0 = unassigned

    # ---- nearest-neighbor distance among same-parent points
    dy = py[:, None] - py[None, :]
    dx = px[:, None] - px[None, :]
    d2 = dy * dy + dx * dx  # (P, P)
    # owner is already 0 for absent points, so owner > 0 implies presence
    pair_ok = (
        (owner[:, None] == owner[None, :])
        & (owner[:, None] > 0)
        & ~jnp.eye(max_points, dtype=bool)
    )
    BIG = jnp.float32(jnp.inf)
    nn = jnp.sqrt(jnp.min(jnp.where(pair_ok, d2, BIG), axis=1))  # (P,)
    has_nn = jnp.isfinite(nn)
    nn = jnp.where(has_nn, nn, 0.0)

    # ---- distance from each point to its parent's centroid
    oc_y = g_cy[jnp.clip(owner - 1, 0, max_parents - 1)]
    oc_x = g_cx[jnp.clip(owner - 1, 0, max_parents - 1)]
    cdist = jnp.sqrt((py - oc_y) ** 2 + (px - oc_x) ** 2)

    # ---- exact Euclidean distance from each point to the nearest
    # label-boundary pixel: masked min over pixels, chunked over the image
    boundary = jnp.zeros((h, w), bool)
    for sy, sx in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        boundary = boundary | (shift_with_fill(parents, sy, sx, -1) != parents)
    b_flat = boundary.reshape(-1)
    y_flat = yy.reshape(-1)
    x_flat = xx.reshape(-1)
    n_pix = h * w
    pad = (-n_pix) % _GLCM_CHUNK
    if pad:  # padded pixels are non-boundary -> masked to +inf below
        b_flat = jnp.concatenate([b_flat, jnp.zeros((pad,), bool)])
        y_flat = jnp.concatenate([y_flat, jnp.zeros((pad,), jnp.float32)])
        x_flat = jnp.concatenate([x_flat, jnp.zeros((pad,), jnp.float32)])
    n_chunks = b_flat.shape[0] // _GLCM_CHUNK
    b_flat = b_flat.reshape(n_chunks, _GLCM_CHUNK)
    y_flat = y_flat.reshape(n_chunks, _GLCM_CHUNK)
    x_flat = x_flat.reshape(n_chunks, _GLCM_CHUNK)

    def bd_body(i, best):
        d2b = (py[:, None] - y_flat[i][None, :]) ** 2 + (
            px[:, None] - x_flat[i][None, :]
        ) ** 2
        d2b = jnp.where(b_flat[i][None, :], d2b, BIG)
        return jnp.minimum(best, jnp.min(d2b, axis=1))

    bdist = jnp.sqrt(
        jax.lax.fori_loop(
            0, n_chunks, bd_body, jnp.full((max_points,), BIG, jnp.float32)
        )
    )

    # ---- per-parent aggregation: masked broadcast over (P, M)
    assign = owner[:, None] == jnp.arange(1, max_parents + 1)[None, :]  # (P, M)

    def _agg(vals, valid):
        sel = assign & valid[:, None]
        n = jnp.sum(sel, axis=0).astype(jnp.float32)
        s = jnp.sum(jnp.where(sel, vals[:, None], 0.0), axis=0)
        sq = jnp.sum(jnp.where(sel, (vals * vals)[:, None], 0.0), axis=0)
        mean = s / jnp.maximum(n, 1.0)
        var = jnp.maximum(sq / jnp.maximum(n, 1.0) - mean * mean, 0.0)
        return n, mean, jnp.sqrt(var)

    n_pts = jnp.sum(assign, axis=0).astype(jnp.float32)
    n_nn, nn_mean, nn_std = _agg(nn, has_nn)
    _, cd_mean, cd_std = _agg(cdist, p_present)
    _, bd_mean, bd_std = _agg(bdist, p_present)

    density = n_pts / safe_a
    # Clark–Evans: observed mean NN distance / E[NN] under CSR
    expected_nn = 0.5 / jnp.sqrt(jnp.maximum(density, 1e-12))
    clark_evans = jnp.where(n_nn > 0, nn_mean / expected_nn, 0.0)

    z = jnp.zeros_like(area)

    def m(v):
        return jnp.where(parent_present, v, z)

    return {
        "PointPattern_count": m(n_pts),
        "PointPattern_density": m(density),
        "PointPattern_nn_dist_mean": m(nn_mean),
        "PointPattern_nn_dist_std": m(nn_std),
        "PointPattern_clark_evans": m(clark_evans),
        "PointPattern_centroid_dist_mean": m(cd_mean),
        "PointPattern_centroid_dist_std": m(cd_std),
        "PointPattern_border_dist_mean": m(bd_mean),
        "PointPattern_border_dist_std": m(bd_std),
    }
