"""On-device image quality-control statistics.

The numeric core of the QC subsystem (``tmlibrary_tpu.qc``): a handful of
cheap, fully fused per-site statistics computed from the *raw* channel
image inside the jterator batch program, so quality observability rides
the existing device pass at zero marginal transfer cost — the scalars
come back with the batch result instead of forcing a second read of the
image data.

Every statistic is a deterministic element-wise/reduction composition
(no data-dependent control flow, no iota-free gathers), so fusing them
into the batch fn cannot perturb the segmentation/measurement outputs:
the pipeline's own arrays never flow *through* these ops, they are only
read.  Bit-identity of pipeline outputs with QC on/off is pinned by
``tests/test_qc.py``.

Statistics
----------
``saturation_frac``
    Fraction of pixels at/above the sensor ceiling (uint16 → 65535).
    Clipped highlights destroy intensity features silently.
``background``
    Minimum of 8×8 block means — a robust dark-level estimate that
    ignores foreground blobs (TissueMAPS estimated background from
    low-order percentiles; block-min-of-means is its streaming-friendly
    cousin and needs no histogram).
``focus_tenengrad``
    Mean squared Sobel gradient magnitude normalized by squared mean
    intensity — the classic Tenengrad autofocus proxy; out-of-focus
    sites score near zero regardless of exposure.
``laplacian_var``
    Variance of the 4-neighbour Laplacian, same normalization — the
    variance-of-Laplacian focus measure, sensitive to a different blur
    band than Tenengrad.
"""

from __future__ import annotations

import jax.numpy as jnp

#: uint16 sensor ceiling — pixels at/above this count as saturated
SATURATION_LEVEL = 65535.0

#: block edge (pixels) for the background block-mean grid
BACKGROUND_BLOCK = 8

#: the per-site statistics ``site_qc_stats`` emits, in a stable order
QC_IMAGE_METRICS = (
    "saturation_frac",
    "background",
    "focus_tenengrad",
    "laplacian_var",
)


def saturation_fraction(img: jnp.ndarray,
                        level: float = SATURATION_LEVEL) -> jnp.ndarray:
    """Fraction of pixels at or above ``level`` (scalar float32)."""
    img = jnp.asarray(img, jnp.float32)
    return jnp.mean((img >= level).astype(jnp.float32))


def background_level(img: jnp.ndarray,
                     block: int = BACKGROUND_BLOCK) -> jnp.ndarray:
    """Minimum of ``block``×``block`` tile means (scalar float32).

    The image is cropped to a whole number of tiles; images smaller
    than one tile degrade to the global mean."""
    img = jnp.asarray(img, jnp.float32)
    h, w = img.shape
    bh, bw = (h // block) * block, (w // block) * block
    if bh == 0 or bw == 0:
        return jnp.mean(img)
    tiles = img[:bh, :bw].reshape(bh // block, block, bw // block, block)
    return jnp.min(jnp.mean(tiles, axis=(1, 3)))


def focus_tenengrad(img: jnp.ndarray) -> jnp.ndarray:
    """Normalized Tenengrad focus score (scalar float32).

    Sobel gradients via shifted slices of an edge-padded image (pure
    adds — no convolution lowering), so the statistic fuses into the
    surrounding batch program."""
    img = jnp.asarray(img, jnp.float32)
    p = jnp.pad(img, 1, mode="edge")
    gx = (p[:-2, 2:] + 2.0 * p[1:-1, 2:] + p[2:, 2:]
          - p[:-2, :-2] - 2.0 * p[1:-1, :-2] - p[2:, :-2])
    gy = (p[2:, :-2] + 2.0 * p[2:, 1:-1] + p[2:, 2:]
          - p[:-2, :-2] - 2.0 * p[:-2, 1:-1] - p[:-2, 2:])
    # +1 in the denominator keeps all-dark sites finite instead of 0/0
    denom = jnp.mean(img) ** 2 + 1.0
    return jnp.mean(gx * gx + gy * gy) / denom


def laplacian_variance(img: jnp.ndarray) -> jnp.ndarray:
    """Normalized variance-of-Laplacian focus score (scalar float32)."""
    img = jnp.asarray(img, jnp.float32)
    p = jnp.pad(img, 1, mode="edge")
    lap = (p[:-2, 1:-1] + p[2:, 1:-1] + p[1:-1, :-2] + p[1:-1, 2:]
           - 4.0 * img)
    denom = jnp.mean(img) ** 2 + 1.0
    return jnp.var(lap) / denom


def site_qc_stats(img: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """All per-site QC statistics for one raw 2-D channel image.

    Returns ``{metric: scalar float32}`` with the keys of
    ``QC_IMAGE_METRICS``.  Volumetric (z-stack) channels are handled by
    the caller via max-projection before calling in here."""
    img = jnp.asarray(img, jnp.float32)
    if img.ndim == 3:  # defensive: fold an unexpected leading z axis
        img = jnp.max(img, axis=0)
    return {
        "saturation_frac": saturation_fraction(img),
        "background": background_level(img),
        "focus_tenengrad": focus_tenengrad(img),
        "laplacian_var": laplacian_variance(img),
    }
